"""Operator registry — the TPU-native replacement for the NNVM op registry.

Reference counterpart: ``include/mxnet/op_attr_types.h:185-264`` (FCompute &
attribute registration) plus the dmlc registry. Here an op is a *pure JAX
function* plus metadata; the same OpDef backs:

- the imperative path (``mx.nd.*``): eager call → jax async dispatch (the
  reference's ThreadedEngine, SURVEY §3.5, is subsumed by XLA's async
  runtime);
- the symbolic path (``mx.sym.*``): a Symbol node stores ``(op, attrs)`` and
  the executor traces ``op.fn`` into one XLA HloModule;
- autograd: backward uses ``jax.vjp`` of ``op.fn`` (pass::Gradient parity).

Randomness is functionalized: ops with ``needs_rng=True`` receive a JAX PRNG
key as leading argument, threaded by the caller (imperative: from the
context RNG resource — parity with ResourceRequest::kRandom,
``include/mxnet/resource.h:37-58``).
"""
from __future__ import annotations

import functools
import inspect

from ..base import MXNetError

_OPS: dict[str, "OpDef"] = {}
_ALIASES: dict[str, str] = {}


class OpDef:
    """A registered operator.

    Attributes
    ----------
    name: canonical op name (e.g. ``Convolution``, ``dot``, ``_plus_scalar``).
    fn: pure function ``fn(*arrays, **attrs) -> array | tuple`` (or with a
        leading PRNG ``key`` argument when ``needs_rng``).
    num_outputs: static output count, or a callable ``attrs -> int``.
    needs_rng: op consumes a PRNG key (sampling, dropout).
    mutate_inputs: indices of inputs mutated in place (optimizer update ops —
        parity with mutable inputs of sgd_update etc.,
        ref src/operator/optimizer_op.cc:39-286).
    attr_defaults: inspected kwarg defaults, used for attr parsing/doc-gen
        (the dmlc::Parameter equivalent, SURVEY §5.6 tier 3).
    nondiff: never differentiable (shape ops, samplers).
    """

    __slots__ = (
        "name",
        "fn",
        "num_outputs",
        "needs_rng",
        "mutate_inputs",
        "attr_defaults",
        "nondiff",
        "num_visible_outputs",
        "doc",
        "input_names",
        "var_inputs",
        "optional_inputs",
        "var_attrs",
        "kwarg_input_order",
        "aux_state_outputs",
    )

    def __init__(
        self,
        name,
        fn,
        num_outputs=1,
        needs_rng=False,
        mutate_inputs=(),
        nondiff=False,
        num_visible_outputs=None,
        aux_state_outputs=None,
    ):
        self.name = name
        self.fn = fn
        self.num_outputs = num_outputs
        self.needs_rng = needs_rng
        self.mutate_inputs = tuple(mutate_inputs)
        self.nondiff = nondiff
        # generic aux-state contract (generalizes BatchNorm's hardcoded
        # moving_mean/var tier): {input param name -> output index whose
        # value REPLACES that aux state each training step}
        self.aux_state_outputs = dict(aux_state_outputs or {})
        # ops like BatchNorm emit aux outputs (mean/var) hidden from the user
        # in the imperative path (ref NumVisibleOutputs in c_api_ndarray.cc)
        self.num_visible_outputs = num_visible_outputs
        self.doc = fn.__doc__ or ""
        self.attr_defaults = _kwarg_defaults(fn, needs_rng)
        # fn taking **kwargs accepts arbitrary attrs (Custom op: user
        # kwargs forward to the CustomOpProp ctor uncoerced)
        self.var_attrs = any(
            p.kind is p.VAR_KEYWORD
            for p in inspect.signature(fn).parameters.values())
        # var-input ops may define how named tensor kwargs map to input
        # order (Custom: the prop's list_arguments()); set post-register
        self.kwarg_input_order = None
        self.input_names, self.var_inputs, self.optional_inputs = (
            _input_names(fn, needs_rng))
        for n in self.input_names:
            self.attr_defaults.pop(n, None)

    def n_outputs(self, attrs) -> int:
        if callable(self.num_outputs):
            return self.num_outputs(attrs)
        return self.num_outputs

    def n_visible_outputs(self, attrs) -> int:
        if self.num_visible_outputs is None:
            return self.n_outputs(attrs)
        if callable(self.num_visible_outputs):
            return self.num_visible_outputs(attrs)
        return self.num_visible_outputs

    def parse_attrs(self, kwargs) -> dict:
        """Coerce string-typed attrs (symbol JSON / C-API parity) to python."""
        out = {}
        for k, v in kwargs.items():
            if k not in self.attr_defaults:
                if self.var_attrs:
                    out[k] = v
                    continue
                raise MXNetError(
                    "op %s: unknown attribute %r (known: %s)"
                    % (self.name, k, sorted(self.attr_defaults))
                )
            out[k] = _coerce(v, self.attr_defaults[k])
        return out

    def __repr__(self):
        return "OpDef(%s)" % self.name


# None-default params with these names are *optional tensor inputs*; any
# other defaulted param ends the input list (it's an attribute).
_OPTIONAL_TENSOR_NAMES = {"bias", "gamma", "state_cell", "sequence_length", "weight", "grid", "loc", "sc_weight"}


def _input_names(fn, needs_rng):
    """Tensor-input parameter names: the leading params with no default,
    plus contiguous None-default params whose name marks an optional tensor
    (``bias``, ``gamma``, …). A ``*args`` parameter means variable input
    count (Concat-style)."""
    sig = inspect.signature(fn)
    params = list(sig.parameters.values())
    if needs_rng and params and params[0].name == "key":
        params = params[1:]
    names = []
    optional = []
    var = False
    for p in params:
        if p.kind is p.VAR_POSITIONAL:
            var = True
            break
        if p.kind not in (p.POSITIONAL_OR_KEYWORD, p.POSITIONAL_ONLY):
            break
        if p.default is p.empty:
            names.append(p.name)
        elif p.default is None and p.name in _OPTIONAL_TENSOR_NAMES:
            names.append(p.name)
            optional.append(p.name)
        else:
            break
    return tuple(names), var, frozenset(optional)


def _kwarg_defaults(fn, needs_rng):
    sig = inspect.signature(fn)
    defaults = {}
    params = list(sig.parameters.values())
    if needs_rng and params and params[0].name == "key":
        params = params[1:]
    for p in params:
        if p.kind in (p.KEYWORD_ONLY,) or (
            p.kind is p.POSITIONAL_OR_KEYWORD and p.default is not p.empty
        ):
            defaults[p.name] = None if p.default is p.empty else p.default
    return defaults


_BOOL_STRS = {"true": True, "false": False, "1": True, "0": False, "none": None}


def _coerce(value, default):
    """String→typed coercion mirroring dmlc::Parameter string kwargs."""
    if isinstance(value, list):
        return tuple(value)
    if not isinstance(value, str):
        return value
    low = value.strip().lower()
    if isinstance(default, bool):
        if low in _BOOL_STRS:
            return bool(_BOOL_STRS[low])
        raise MXNetError("cannot parse %r as bool" % (value,))
    if low == "none":
        return None
    if isinstance(default, int) and not isinstance(default, bool):
        try:
            return int(value)
        except ValueError:
            return int(float(value))
    if isinstance(default, float):
        return float(value)
    if isinstance(default, (tuple, list)):
        return _parse_tuple(value)
    if value.startswith("(") or value.startswith("["):
        return _parse_tuple(value)
    return value


def _parse_tuple(value):
    s = value.strip().lstrip("([").rstrip(")]")
    if not s:
        return ()
    items = []
    depth = 0
    cur = ""
    for ch in s:
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        if ch == "," and depth == 0:
            items.append(cur)
            cur = ""
        else:
            cur += ch
    if cur.strip():
        items.append(cur)
    out = []
    for it in items:
        it = it.strip()
        if it.startswith("(") or it.startswith("["):
            out.append(_parse_tuple(it))
            continue
        try:
            out.append(int(it))
        except ValueError:
            try:
                out.append(float(it))
            except ValueError:
                low = it.lower()
                out.append(_BOOL_STRS[low] if low in _BOOL_STRS else it)
    return tuple(out)


def register(
    name=None,
    aliases=(),
    num_outputs=1,
    needs_rng=False,
    mutate_inputs=(),
    nondiff=False,
    num_visible_outputs=None,
    aux_state_outputs=None,
):
    """Decorator registering a pure JAX function as an operator."""

    def deco(fn):
        opname = name or fn.__name__
        op = OpDef(
            opname,
            fn,
            num_outputs=num_outputs,
            needs_rng=needs_rng,
            mutate_inputs=mutate_inputs,
            nondiff=nondiff,
            num_visible_outputs=num_visible_outputs,
            aux_state_outputs=aux_state_outputs,
        )
        if opname in _OPS:
            raise MXNetError("duplicate op registration: %s" % opname)
        _OPS[opname] = op
        for a in aliases:
            _ALIASES[a] = opname
        return fn

    return deco


def alias(extra_name, canonical):
    _ALIASES[extra_name] = canonical


def get(name) -> OpDef:
    op = _OPS.get(name)
    if op is None:
        canon = _ALIASES.get(name)
        if canon is not None:
            op = _OPS.get(canon)
    if op is None:
        raise MXNetError("operator %r is not registered" % (name,))
    return op


def exists(name) -> bool:
    return name in _OPS or name in _ALIASES


def list_ops():
    return sorted(set(_OPS) | set(_ALIASES))


def canonical_name(name):
    return _ALIASES.get(name, name)


# ---------------------------------------------------------------------------
# jitted-apply cache: per (op, frozen attrs) compiled callable for the
# imperative fast path. XLA compile cache keys on shapes/dtypes below this.
# ---------------------------------------------------------------------------
@functools.lru_cache(maxsize=8192)
def _jitted(op_name, attr_items, with_key=False):
    import jax

    op = get(op_name)
    attrs = dict(attr_items)

    def call(*arrays):
        return op.fn(*arrays, **attrs)

    return jax.jit(call)


def apply_op(op: OpDef, arrays, attrs, jit=True):
    """Invoke an op's kernel on raw jax arrays (imperative bottom half).

    This is the analogue of PushFCompute (ref:
    src/imperative/imperative_utils.h:328-440): instead of pushing a closure
    to an engine thread, we hand the computation to XLA, whose async
    dispatch provides the same read-after-write ordering the ThreadedEngine
    enforced via Var queues.
    """
    if jit and _hashable(attrs):
        fn = _jitted(op.name, tuple(sorted(attrs.items())))
        return fn(*arrays)
    return op.fn(*arrays, **attrs)


def apply_op_with_key(op: OpDef, arrays_with_key, attrs, jit=True):
    """Like apply_op for ``needs_rng`` ops: first element is the PRNG key
    (a traced argument, so repeated sampling reuses the compiled program)."""
    if jit and _hashable(attrs):
        fn = _jitted(op.name, tuple(sorted(attrs.items())), with_key=True)
        return fn(*arrays_with_key)
    return op.fn(*arrays_with_key, **attrs)


def _hashable(attrs):
    try:
        hash(tuple(sorted(attrs.items())))
        return True
    except TypeError:
        return False
