"""Neural-net operators: conv, pooling, norm, activation, softmax, dropout, RNN.

Reference surface: ``src/operator/nn/`` + legacy v1 layers (SURVEY §2.5,
~45k LoC of mshadow/cuDNN kernels). TPU-native design: convolutions lower to
``lax.conv_general_dilated`` which XLA tiles onto the MXU — the cuDNN
autotuning registry (cudnn_algoreg-inl.h) has no equivalent because XLA
selects the schedule. Layouts: MXNet is NCHW-first; we accept NCHW at the
API and let XLA pick internal layouts. The fused RNN op (ref
cudnn_rnn-inl.h:41-175) is a ``lax.scan`` over time — one XLA while-loop,
the moral equivalent of a cuDNN persistent kernel.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import alias, register


def _pair(x, n=2):
    if isinstance(x, (int, float)):
        return (int(x),) * n
    t = tuple(int(v) for v in x)
    if len(t) == 0:
        return (1,) * n
    if len(t) == 1:
        return t * n
    return t


# ---------------------------------------------------------------------------
# Convolution (ref: src/operator/nn/convolution.cc; im2col never needed — MXU)
# ---------------------------------------------------------------------------
@register(name="Convolution", aliases=("convolution", "Convolution_v1"))
def convolution(
    data,
    weight,
    bias=None,
    kernel=(),
    stride=(),
    dilate=(),
    pad=(),
    num_filter=1,
    num_group=1,
    workspace=1024,
    no_bias=False,
    cudnn_tune=None,
    cudnn_off=False,
    layout=None,
):
    nd = len(kernel) if kernel else data.ndim - 2
    stride = _pair(stride, nd)
    dilate = _pair(dilate, nd)
    pad = _pair(pad, nd) if pad else (0,) * nd
    pads = tuple((p, p) for p in pad)
    if nd == 1:
        dn = lax.conv_dimension_numbers(data.shape, weight.shape, ("NCH", "OIH", "NCH"))
    elif nd == 3:
        dn = lax.conv_dimension_numbers(data.shape, weight.shape, ("NCDHW", "OIDHW", "NCDHW"))
    else:
        dn = lax.conv_dimension_numbers(data.shape, weight.shape, ("NCHW", "OIHW", "NCHW"))
    # bf16 in/out: the TPU MXU accumulates in fp32 internally; an explicit
    # preferred_element_type here breaks the conv transpose (mixed-dtype
    # cotangent) and XLA would insert casts anyway.
    out = lax.conv_general_dilated(
        data,
        weight,
        window_strides=stride,
        padding=pads,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=int(num_group),
    )
    out = out.astype(data.dtype)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


@register(name="Deconvolution", aliases=("deconvolution",))
def deconvolution(
    data,
    weight,
    bias=None,
    kernel=(),
    stride=(),
    dilate=(),
    pad=(),
    adj=(),
    target_shape=(),
    num_filter=1,
    num_group=1,
    workspace=1024,
    no_bias=True,
    cudnn_tune=None,
    cudnn_off=False,
    layout=None,
):
    """Transposed convolution (ref: src/operator/nn/deconvolution.cc).

    Implemented as the gradient of Convolution wrt its input — which is
    exactly what conv_transpose computes; XLA maps it to the MXU.
    """
    nd = len(kernel) if kernel else 2
    stride = _pair(stride, nd)
    pad = _pair(pad, nd) if pad else (0,) * nd
    dilate = _pair(dilate, nd) if dilate else (1,) * nd
    adj = _pair(adj, nd) if adj else (0,) * nd
    kernel = _pair(kernel, nd)
    # lax.conv_transpose with explicit padding chosen to invert Convolution
    pads = tuple(
        (k - 1 - p, k - 1 - p + a)
        for k, p, a in zip(
            tuple((kk - 1) * dd + 1 for kk, dd in zip(kernel, dilate)), pad, adj
        )
    )
    # weight layout (in_ch, out_ch/group, *kernel) — same as reference
    ich = data.shape[1]
    g = int(num_group)
    if g > 1:
        data_g = data.reshape((data.shape[0], g, ich // g) + data.shape[2:])
        outs = []
        wg = weight.reshape((g, ich // g) + weight.shape[1:])
        for gi in range(g):
            outs.append(
                _deconv_single(data_g[:, gi], wg[gi], stride, pads, dilate)
            )
        out = jnp.concatenate(outs, axis=1)
    else:
        out = _deconv_single(data, weight, stride, pads, dilate)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


def _deconv_single(data, weight, stride, pads, dilate):
    nd = len(stride)
    spec = ("NCH", "IOH", "NCH") if nd == 1 else (
        ("NCHW", "IOHW", "NCHW") if nd == 2 else ("NCDHW", "IODHW", "NCDHW")
    )
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, spec)
    return lax.conv_general_dilated(
        data,
        jnp.flip(weight, axis=tuple(range(2, 2 + nd))),
        window_strides=(1,) * nd,
        padding=pads,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
    )


# ---------------------------------------------------------------------------
# FullyConnected (ref: src/operator/nn/fully_connected.cc)
# ---------------------------------------------------------------------------
@register(name="FullyConnected", aliases=("fully_connected",))
def fully_connected(data, weight, bias=None, num_hidden=1, no_bias=False, flatten=True):
    if flatten:
        x = data.reshape((data.shape[0], -1))
    else:
        x = data
    out = jnp.matmul(x, weight.T)
    if bias is not None and not no_bias:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# Pooling (ref: src/operator/nn/pooling.cc + pool.h/.cuh)
# ---------------------------------------------------------------------------
@register(name="Pooling", aliases=("pooling", "Pooling_v1"))
def pooling(
    data,
    kernel=(),
    pool_type="max",
    global_pool=False,
    cudnn_off=False,
    pooling_convention="valid",
    stride=(),
    pad=(),
):
    nd = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, data.ndim))
        if pool_type == "max":
            return jnp.max(data, axis=axes, keepdims=True)
        return jnp.mean(data, axis=axes, keepdims=True)
    kernel = _pair(kernel, nd)
    stride = _pair(stride, nd) if stride else (1,) * nd
    pad = _pair(pad, nd) if pad else (0,) * nd
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    if pooling_convention == "full":
        # ceil-mode output: pad on the high side so every input elem is covered
        pads = (0, 0), (0, 0)
        extra = []
        for i in range(nd):
            size = data.shape[2 + i]
            out_sz = int(np.ceil((size + 2 * pad[i] - kernel[i]) / stride[i])) + 1
            needed = (out_sz - 1) * stride[i] + kernel[i] - size - pad[i]
            extra.append((pad[i], max(needed, pad[i])))
        pads = ((0, 0), (0, 0)) + tuple(extra)
    else:
        pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides, pads)
    if pool_type in ("avg", "sum"):
        summed = lax.reduce_window(data, 0.0, lax.add, window, strides, pads)
        if pool_type == "sum":
            return summed
        # count_include_pad=True semantics (mxnet default)
        denom = 1
        for k in kernel:
            denom *= k
        return summed / denom
    raise ValueError("unknown pool_type %r" % pool_type)


# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------
@register(name="Activation", aliases=("activation",))
def activation(data, act_type="relu"):
    if act_type == "relu":
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    raise ValueError("unknown act_type %r" % act_type)


@register(name="LeakyReLU", aliases=("leaky_relu",), needs_rng=True)
def leaky_relu(key, data, gamma=None, act_type="leaky", slope=0.25, lower_bound=0.125, upper_bound=0.334, __is_train__=False):
    """ref: src/operator/leaky_relu.cc — leaky/prelu/elu/selu/rrelu/gelu."""
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) if gamma.ndim == 1 else gamma
        return jnp.where(data > 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * jnp.expm1(data))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data > 0, data, alpha * jnp.expm1(data))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        lo, hi = float(lower_bound), float(upper_bound)
        if __is_train__:
            s = jax.random.uniform(key, data.shape, minval=lo, maxval=hi).astype(data.dtype)
        else:
            # inference uses the deterministic mean slope (reference parity)
            s = jnp.asarray((lo + hi) / 2.0, data.dtype)
        return jnp.where(data > 0, data, s * data)
    raise ValueError("unknown act_type %r" % act_type)


# ---------------------------------------------------------------------------
# softmax family (ref: src/operator/nn/softmax-inl.h, softmax_output.cc)
# ---------------------------------------------------------------------------
@register(name="softmax")
def softmax(data, axis=-1, temperature=None, length=None):
    x = data if temperature in (None, "None", 1.0) else data / float(temperature)
    return jax.nn.softmax(x, axis=int(axis))


@register(name="log_softmax")
def log_softmax(data, axis=-1, temperature=None):
    x = data if temperature in (None, "None", 1.0) else data / float(temperature)
    return jax.nn.log_softmax(x, axis=int(axis))


@register(name="softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    return -jnp.sum(jnp.take_along_axis(logp, label.astype(jnp.int32)[:, None], axis=-1))


@register(name="SoftmaxActivation", aliases=("softmax_activation",))
def softmax_activation(data, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


@register(name="SoftmaxOutput", aliases=("softmax_output", "Softmax"))
def softmax_output(
    data,
    label,
    grad_scale=1.0,
    ignore_label=-1.0,
    multi_output=False,
    use_ignore=False,
    preserve_shape=False,
    normalization="null",
    out_grad=False,
    smooth_alpha=0.0,
):
    """Fused softmax + CE-grad head (ref: src/operator/softmax_output.cc).

    Forward emits softmax probabilities; the custom backward produces
    (p - onehot(label)) * grad_scale, matching the reference's fused loss
    semantics (label input gets zero grad). Attrs are closed over (not
    traced) so the custom_vjp only sees arrays.
    """
    multi_output = bool(multi_output)
    use_ignore = bool(use_ignore)
    preserve_shape = bool(preserve_shape)

    def fwd_only(d):
        if multi_output:
            return jax.nn.softmax(d, axis=1)
        if preserve_shape:
            return jax.nn.softmax(d, axis=-1)
        return jax.nn.softmax(d.reshape(d.shape[0], -1), axis=-1).reshape(d.shape)

    @jax.custom_vjp
    def f(d, l):
        return fwd_only(d)

    def so_fwd(d, l):
        out = fwd_only(d)
        return out, (out, l)

    def so_bwd(res, g):
        out, lab_arr = res
        if multi_output:
            # trailing spatial dims flatten against the label (the
            # reference accepts label (N, d1*d2...) for data
            # (N, C, d1, d2...), softmax_output-inl.h:154-170)
            out3 = out.reshape(out.shape[0], out.shape[1], -1)
            lab = lab_arr.reshape(out.shape[0], -1).astype(jnp.int32)
            onehot = jax.nn.one_hot(lab, out.shape[1], dtype=out.dtype, axis=1)
            if smooth_alpha:
                k = out.shape[1]
                onehot = onehot * (1 - smooth_alpha) + smooth_alpha / (k - 1) * (1 - onehot)
            grad = out3 - onehot
            if use_ignore:
                mask = (lab != int(ignore_label)).astype(out.dtype)
                grad = grad * jnp.expand_dims(mask, 1)
            denom = 1.0
            if normalization == "batch":
                denom = out.shape[0]
            elif normalization == "valid" and use_ignore:
                denom = jnp.maximum((lab_arr != ignore_label).sum().astype(out.dtype), 1.0)
            grad = (grad * (grad_scale / denom)).reshape(out.shape)
        else:
            flat = out.reshape(out.shape[0], -1)
            lab = lab_arr.reshape(-1).astype(jnp.int32)
            onehot = jax.nn.one_hot(lab, flat.shape[1], dtype=out.dtype)
            if smooth_alpha:
                k = flat.shape[1]
                onehot = onehot * (1 - smooth_alpha) + smooth_alpha / (k - 1) * (1 - onehot)
            grad = flat - onehot
            if use_ignore:
                mask = (lab != int(ignore_label)).astype(out.dtype)
                grad = grad * mask[:, None]
            denom = 1.0
            if normalization == "batch":
                denom = out.shape[0]
            elif normalization == "valid" and use_ignore:
                denom = jnp.maximum((lab != int(ignore_label)).sum().astype(out.dtype), 1.0)
            grad = (grad * (grad_scale / denom)).reshape(out.shape)
        return (grad, jnp.zeros_like(lab_arr))

    f.defvjp(so_fwd, so_bwd)
    return f(data, label)


@register(name="SVMOutput", aliases=("svm_output",))
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0, use_linear=False):
    use_linear = bool(use_linear)
    reg = regularization_coefficient

    @jax.custom_vjp
    def f(d, l):
        return d

    def svm_fwd(d, l):
        return d, (d, l)

    def svm_bwd(res, g):
        d, l = res
        lab = l.astype(jnp.int32)
        onehot = jax.nn.one_hot(lab, d.shape[1], dtype=d.dtype)
        score_correct = jnp.take_along_axis(d, lab[:, None], axis=1)
        viol = (margin - (score_correct - d)) > 0
        viol = jnp.logical_and(viol, onehot == 0)
        if use_linear:
            gwrong = jnp.where(viol, reg, 0.0).astype(d.dtype)
        else:
            gwrong = jnp.where(viol, 2 * reg * (margin - (score_correct - d)), 0.0).astype(d.dtype)
        gright = -jnp.sum(gwrong, axis=1, keepdims=True) * onehot
        return (gwrong * (1 - onehot) + gright, jnp.zeros_like(l))

    f.defvjp(svm_fwd, svm_bwd)
    return f(data, label)


@register(name="LinearRegressionOutput", aliases=("linear_regression_output",))
def linear_regression_output(data, label, grad_scale=1.0):
    return _regression_out(data, label, grad_scale, "linear")


@register(name="MAERegressionOutput", aliases=("mae_regression_output",))
def mae_regression_output(data, label, grad_scale=1.0):
    return _regression_out(data, label, grad_scale, "mae")


@register(name="LogisticRegressionOutput", aliases=("logistic_regression_output",))
def logistic_regression_output(data, label, grad_scale=1.0):
    return _regression_out(data, label, grad_scale, "logistic")


def _regression_out(data, label, grad_scale, kind):
    @jax.custom_vjp
    def f(d, l):
        return jax.nn.sigmoid(d) if kind == "logistic" else d

    def fwd(d, l):
        return f(d, l), (d, l)

    def bwd(res, g):
        d, l = res
        l = l.reshape(d.shape)
        if kind == "linear":
            grad = d - l
        elif kind == "mae":
            grad = jnp.sign(d - l)
        else:
            grad = jax.nn.sigmoid(d) - l
        return (grad * grad_scale, jnp.zeros_like(l))

    f.defvjp(fwd, bwd)
    return f(data, label)


@register(name="make_loss", aliases=("MakeLoss",))
def make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    """Loss head: forward identity, backward a constant grad_scale field.
    normalization: 'batch' divides by batch size, 'valid' by the count of
    elements > valid_thresh (ref: src/operator/make_loss.cc)."""
    @jax.custom_vjp
    def f(d):
        return d

    def ml_fwd(d):
        if normalization == "valid":
            nv = jnp.maximum(jnp.sum(d > valid_thresh).astype(jnp.float32), 1.0)
        else:
            nv = jnp.ones((), jnp.float32)
        return d, nv

    def ml_bwd(nv, g):
        scale = grad_scale
        if normalization == "batch":
            scale = scale / g.shape[0]
        grad = jnp.full(g.shape, scale, g.dtype)
        if normalization == "valid":
            grad = grad / nv.astype(g.dtype)
        return (grad,)

    f.defvjp(ml_fwd, ml_bwd)
    return f(data)


@register(name="BlockGrad", aliases=("block_grad", "stop_gradient"))
def block_grad(data):
    return lax.stop_gradient(data)


# ---------------------------------------------------------------------------
# Normalization (ref: src/operator/nn/batch_norm.cc, layer_norm, instance_norm,
# l2_normalization, lrn)
# ---------------------------------------------------------------------------
@register(
    name="BatchNorm",
    aliases=("batch_norm", "BatchNorm_v1"),
    num_outputs=3,
    num_visible_outputs=1,
    mutate_inputs=(3, 4),
)
def batch_norm(
    data,
    gamma,
    beta,
    moving_mean,
    moving_var,
    eps=1e-3,
    momentum=0.9,
    fix_gamma=True,
    use_global_stats=False,
    output_mean_var=False,
    axis=1,
    cudnn_off=False,
    __is_train__=False,
):
    """BatchNorm with running-stat update.

    Outputs (out, batch_mean, batch_var); the imperative/executor layer
    handles the moving-stat mutation (ref: batch norm mutates aux states
    src/operator/nn/batch_norm.cc). In training mode uses batch statistics;
    in inference uses moving stats (use_global_stats forces the latter).
    """
    ax = int(axis) % data.ndim
    red = tuple(i for i in range(data.ndim) if i != ax)
    bshape = tuple(data.shape[ax] if i == ax else 1 for i in range(data.ndim))
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if __is_train__ and not use_global_stats:
        # One-pass statistics: sum and sum-of-squares reduce in a single
        # fused XLA pass over the activation (f32 accumulation). The
        # textbook mean-then-var formulation is two *sequential* passes
        # (var needs mean), which leaves conv+BN towers HBM-bound at ~1/3
        # of MXU throughput on TPU.
        n = 1
        for i in red:
            n *= data.shape[i]
        if data.dtype in (jnp.bfloat16, jnp.float16):
            # Half-precision inputs: their own quantization noise floor
            # (~(|mean|·2⁻⁸)² for bf16) sits far above the f32 cancellation
            # threshold of E[x²]−E[x]², so the unshifted one-pass is safe
            # and keeps the reduce fully fused (the perf-critical path).
            pivot = None
            xf = data.astype(jnp.float32)
        else:
            # f32 inputs: subtract a per-channel pivot (any sample) so
            # E[(x-p)²]−E[x-p]² stays clear of catastrophic cancellation
            # when |mean| >> std; both sums still fuse into one pass.
            pivot = lax.stop_gradient(
                data[tuple(slice(0, 1) if i in red else slice(None) for i in range(data.ndim))]
            ).astype(jnp.float32)
            xf = data.astype(jnp.float32) - pivot
        s1 = jnp.sum(xf, axis=red)
        s2 = jnp.sum(xf * xf, axis=red)
        mean_c = s1 / n
        var = jnp.maximum(s2 / n - mean_c * mean_c, 0.0)
        mean = mean_c if pivot is None else mean_c + pivot.reshape(mean_c.shape)
    else:
        mean = moving_mean
        var = moving_var
    # Fold (mean, var, gamma, beta) into one per-channel scale+shift so the
    # big tensor sees a single fused multiply-add.
    inv = lax.rsqrt(var.astype(jnp.float32) + eps)
    scale = (inv * g.astype(jnp.float32)).astype(data.dtype)
    shift = (beta.astype(jnp.float32) - mean.astype(jnp.float32) * inv * g.astype(jnp.float32)).astype(data.dtype)
    out = data * scale.reshape(bshape) + shift.reshape(bshape)
    # Stats take the moving-stat dtype: f32 aux gets full-precision updates,
    # and a net cast to bf16 keeps bf16 running stats (no dtype drift).
    return out, mean.astype(moving_mean.dtype), var.astype(moving_var.dtype)


@register(name="LayerNorm", aliases=("layer_norm",), num_outputs=3, num_visible_outputs=1)
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    ax = int(axis)
    mean = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.var(data, axis=ax, keepdims=True)
    inv = lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[ax] = data.shape[ax]
    out = (data - mean) * inv * gamma.reshape(shape) + beta.reshape(shape)
    return out, jnp.squeeze(mean, ax), jnp.squeeze(var, ax)


@register(name="InstanceNorm", aliases=("instance_norm",))
def instance_norm(data, gamma, beta, eps=1e-3):
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * lax.rsqrt(var + eps) * gamma.reshape(shape) + beta.reshape(shape)


@register(name="L2Normalization", aliases=("l2_normalization",))
def l2_normalization(data, eps=1e-10, mode="instance"):
    if mode == "instance":
        norm = jnp.sqrt(jnp.sum(jnp.square(data.reshape(data.shape[0], -1)), axis=1) + eps)
        return data / norm.reshape((-1,) + (1,) * (data.ndim - 1))
    if mode == "channel":
        norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=1, keepdims=True) + eps)
        return data / norm
    if mode == "spatial":
        red = tuple(range(2, data.ndim))
        norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=True) + eps)
        return data / norm
    raise ValueError(mode)


@register(name="LRN", aliases=("lrn",))
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Local response norm across channels (ref: src/operator/lrn.cc)."""
    half = int(nsize) // 2
    sq = jnp.square(data)
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    window = sum(
        padded[:, i : i + data.shape[1]] for i in range(int(nsize))
    )
    return data * jnp.power(knorm + alpha / nsize * window, -beta)


# ---------------------------------------------------------------------------
# Dropout (ref: src/operator/nn/dropout.cc) — functional PRNG
# ---------------------------------------------------------------------------
@register(name="Dropout", aliases=("dropout",), needs_rng=True, num_outputs=2, num_visible_outputs=1)
def dropout(key, data, p=0.5, mode="training", axes=(), __is_train__=False):
    if not __is_train__ and mode != "always":
        return data, jnp.ones_like(data)
    if p <= 0.0:
        return data, jnp.ones_like(data)
    shape = list(data.shape)
    for ax in axes or ():
        shape[int(ax)] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, tuple(shape)).astype(data.dtype) / keep
    return data * mask, jnp.broadcast_to(mask, data.shape)


# ---------------------------------------------------------------------------
# UpSampling / crop (ref: src/operator/upsampling.cc, crop.cc)
# ---------------------------------------------------------------------------
@register(name="UpSampling", aliases=("up_sampling",))
def upsampling(*args, scale=1, num_filter=0, sample_type="nearest", multi_input_mode="concat", num_args=1, workspace=512):
    s = int(scale)
    if sample_type == "nearest":
        outs = []
        h = max(a.shape[2] for a in args) * s // (s if len(args) == 1 else 1)
        for a in args:
            factor = s if len(args) == 1 else (h // a.shape[2])
            o = jnp.repeat(jnp.repeat(a, factor, axis=2), factor, axis=3)
            outs.append(o)
        if len(outs) == 1:
            return outs[0]
        if multi_input_mode == "sum":
            return sum(outs)
        return jnp.concatenate(outs, axis=1)
    if sample_type == "bilinear":
        data, weight = args[0], args[1]
        n, c, h, w = data.shape
        return jax.image.resize(data, (n, c, h * s, w * s), method="bilinear")
    raise ValueError(sample_type)


@register(name="Crop", aliases=("crop",))
def crop_op(*args, num_args=1, offset=(0, 0), h_w=(0, 0), center_crop=False):
    data = args[0]
    if len(args) == 2:
        th, tw = args[1].shape[2], args[1].shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
    if center_crop:
        oy = (data.shape[2] - th) // 2
        ox = (data.shape[3] - tw) // 2
    else:
        oy, ox = int(offset[0]), int(offset[1])
    return data[:, :, oy : oy + th, ox : ox + tw]


# ---------------------------------------------------------------------------
# Correlation / grid ops (legacy vision)
# ---------------------------------------------------------------------------
@register(name="GridGenerator", aliases=("grid_generator",))
def grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    th, tw = int(target_shape[0]), int(target_shape[1])
    if transform_type == "affine":
        ys = jnp.linspace(-1, 1, th)
        xs = jnp.linspace(-1, 1, tw)
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        grid = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()], axis=0)  # (3, H*W)
        theta = data.reshape(-1, 2, 3)
        out = jnp.einsum("nij,jk->nik", theta, grid)  # (N,2,H*W)
        return out.reshape(-1, 2, th, tw)
    # warp: data is flow (N,2,H,W)
    n, _, h, w = data.shape
    ys = jnp.arange(h, dtype=data.dtype)
    xs = jnp.arange(w, dtype=data.dtype)
    gx, gy = jnp.meshgrid(xs, ys)
    x = (data[:, 0] + gx) * 2 / max(w - 1, 1) - 1
    y = (data[:, 1] + gy) * 2 / max(h - 1, 1) - 1
    return jnp.stack([x, y], axis=1)


@register(name="BilinearSampler", aliases=("bilinear_sampler",))
def bilinear_sampler(data, grid, cudnn_off=False):
    """ref: src/operator/bilinear_sampler.cc — sample data at grid coords."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1) * (w - 1) / 2
    gy = (grid[:, 1] + 1) * (h - 1) / 2

    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    x1 = x0 + 1
    y1 = y0 + 1

    wx1 = gx - x0
    wy1 = gy - y0
    wx0 = 1 - wx1
    wy0 = 1 - wy1

    # vectorized gather: build (N, Ho, Wo) index maps, gather per channel
    batch_idx = jnp.arange(n).reshape(n, 1, 1)

    def gather(xi, yi):
        xi_c = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        yi_c = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        valid = ((xi >= -0.0001) & (xi <= w - 0.9999) & (yi >= -0.0001) & (yi <= h - 0.9999))
        vals = data[batch_idx, :, yi_c, xi_c]  # (N, Ho, Wo, C)
        return vals * valid[..., None].astype(data.dtype)

    out = (
        gather(x0, y0) * (wx0 * wy0)[..., None]
        + gather(x1, y0) * (wx1 * wy0)[..., None]
        + gather(x0, y1) * (wx0 * wy1)[..., None]
        + gather(x1, y1) * (wx1 * wy1)[..., None]
    )
    return jnp.transpose(out, (0, 3, 1, 2))


@register(name="SpatialTransformer", aliases=("spatial_transformer",))
def spatial_transformer(data, loc, target_shape=(0, 0), transform_type="affine", sampler_type="bilinear", cudnn_off=False):
    grid = grid_generator(loc, transform_type="affine", target_shape=target_shape)
    return bilinear_sampler(data, grid)


# ---------------------------------------------------------------------------
# Fused RNN (ref: src/operator/rnn-inl.h + cudnn_rnn-inl.h → lax.scan)
# ---------------------------------------------------------------------------
@register(
    name="RNN",
    aliases=("rnn",),
    needs_rng=True,
    num_outputs=lambda attrs: 3 if attrs.get("mode") == "lstm" else 2,
    num_visible_outputs=lambda attrs: (
        (3 if attrs.get("mode") == "lstm" else 2) if attrs.get("state_outputs") else 1
    ),
)
def rnn(
    key,
    data,
    parameters,
    state,
    state_cell=None,
    state_size=0,
    num_layers=1,
    bidirectional=False,
    mode="lstm",
    p=0.0,
    state_outputs=False,
    __is_train__=False,
):
    """Fused multi-layer (bi)RNN over the whole sequence.

    data: (T, N, I); parameters: flat vector packed cuDNN-style
    (per layer/direction: W_ih, W_hh, b_ih, b_hh for each gate);
    state: (L*D, N, H). One ``lax.scan`` per layer-direction — XLA compiles
    the whole unroll into a single while-loop program (the TPU equivalent of
    cudnnRNNForwardTraining, ref cudnn_rnn-inl.h:41-175).
    """
    T, N, I = data.shape
    H = int(state_size)
    L = int(num_layers)
    D = 2 if bidirectional else 1
    ngates = {"lstm": 4, "gru": 3, "rnn_relu": 1, "rnn_tanh": 1}[mode]

    # unpack flat parameter vector
    offset = 0

    def take_mat(rows, cols):
        nonlocal offset
        m = lax.dynamic_slice(parameters, (offset,), (rows * cols,)).reshape(rows, cols)
        offset += rows * cols
        return m

    weights = []
    for layer in range(L):
        for d in range(D):
            in_size = I if layer == 0 else H * D
            w_ih = take_mat(ngates * H, in_size)
            w_hh = take_mat(ngates * H, H)
            weights.append((w_ih, w_hh))
    biases = []
    for layer in range(L):
        for d in range(D):
            nonloc = offset
            b_ih = lax.dynamic_slice(parameters, (offset,), (ngates * H,))
            offset += ngates * H
            b_hh = lax.dynamic_slice(parameters, (offset,), (ngates * H,))
            offset += ngates * H
            biases.append((b_ih, b_hh))

    def cell_step(mode, x_proj, h, c, w_hh, b_hh):
        gates = x_proj + h @ w_hh.T + b_hh
        if mode == "lstm":
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new
        if mode == "gru":
            # cuDNN gate order: r, z, n
            xr, xz, xn = jnp.split(x_proj + b_hh * 0, 3, axis=-1)
            hr, hz, hn = jnp.split(h @ w_hh.T + b_hh, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h_new = (1 - z) * n + z * h
            return h_new, c
        act = jnp.maximum if mode == "rnn_relu" else None
        pre = gates
        h_new = jnp.maximum(pre, 0) if mode == "rnn_relu" else jnp.tanh(pre)
        return h_new, c

    x = data
    h0 = state.reshape(L, D, N, H)
    c0 = state_cell.reshape(L, D, N, H) if mode == "lstm" and state_cell is not None else jnp.zeros((L, D, N, H), data.dtype)
    h_last, c_last = [], []
    for layer in range(L):
        outs = []
        for d in range(D):
            wi = layer * D + d
            w_ih, w_hh = weights[wi]
            b_ih, b_hh = biases[wi]
            xs = x if d == 0 else jnp.flip(x, axis=0)
            x_proj = xs @ w_ih.T + b_ih  # (T, N, ngates*H)

            def step(carry, xp, _w=w_hh, _b=b_hh, _m=mode):
                h, c = carry
                h2, c2 = cell_step(_m, xp, h, c, _w, _b)
                return (h2, c2), h2

            (hT, cT), ys = lax.scan(step, (h0[layer, d], c0[layer, d]), x_proj)
            if d == 1:
                ys = jnp.flip(ys, axis=0)
            outs.append(ys)
            h_last.append(hT)
            c_last.append(cT)
        x = outs[0] if D == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0.0 and __is_train__ and layer < L - 1:
            key, sub = jax.random.split(key)
            mask = jax.random.bernoulli(sub, 1 - p, x.shape).astype(x.dtype) / (1 - p)
            x = x * mask
    hN = jnp.stack(h_last).reshape(L * D, N, H)
    cN = jnp.stack(c_last).reshape(L * D, N, H)
    if mode == "lstm":
        return x, hN, cN
    return x, hN


# ---------------------------------------------------------------------------
# misc heads
# ---------------------------------------------------------------------------
@register(name="Correlation", aliases=("correlation",))
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1, stride2=1, pad_size=0, is_multiply=True):
    """Cost-volume correlation (ref: src/operator/correlation.cc)."""
    pad = int(pad_size)
    d = int(max_displacement)
    s2 = int(stride2)
    a = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    b = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    n, c, h, w = data1.shape
    offs = range(-d, d + 1, s2)
    maps = []
    for dy in offs:
        for dx in offs:
            shifted = jnp.roll(b, (-dy, -dx), axis=(2, 3))
            prod = (a * shifted) if is_multiply else jnp.abs(a - shifted)
            maps.append(prod.mean(axis=1)[:, pad : pad + h, pad : pad + w])
    return jnp.stack(maps, axis=1)


@register(name="IdentityAttachKLSparseReg", aliases=("identity_attach_kl_sparse_reg",))
def identity_attach_kl_sparse_reg(data, sparseness_target=0.1, penalty=0.001, momentum=0.9):
    return data


# backend-specific names of the reference resolve to the one XLA kernel
alias("CuDNNBatchNorm", "BatchNorm")
alias("_contrib_SparseEmbedding", "Embedding")
