"""Contrib operators: CTC loss, MultiBox (SSD), proposals, quantization.

Reference surface: ``src/operator/contrib/`` (SURVEY §2.5 — ~15k LoC of
custom CUDA). These are the genuinely-custom kernels; the first
implementations here are pure-XLA (scan/vectorized) versions with the same
semantics; Pallas variants replace the hot ones as optimization rounds
land (multibox detection NMS, deformable conv).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import alias, register

_NEG_INF = -1e30


@register(name="_contrib_ctc_loss", aliases=("ctc_loss", "CTCLoss"))
def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False, blank_label="last"):
    """CTC negative log-likelihood via log-semiring forward scan.

    data: (T, N, C) unnormalized activations (softmax applied internally,
    matching ref warp-ctc semantics, src/operator/contrib/ctc_loss.cc);
    label: (N, L) class indices (padded with -1 or 0 when using lengths).
    """
    T, N, C = data.shape
    L = label.shape[1]
    logp = jax.nn.log_softmax(data, axis=-1)  # (T,N,C)

    blank = C - 1 if blank_label == "last" else 0
    lab = label.astype(jnp.int32)
    if blank_label == "first":
        # labels are 1-based when blank is first (ref convention)
        lab = lab - 1

    if use_label_lengths and label_lengths is not None:
        lab_len = label_lengths.astype(jnp.int32)
    else:
        # count non-padding (padding assumed <0 or ==0 per ref; use >=0 & valid)
        lab_len = jnp.sum((lab >= 0) & (lab < C), axis=1).astype(jnp.int32)
    if use_data_lengths and data_lengths is not None:
        seq_len = data_lengths.astype(jnp.int32)
    else:
        seq_len = jnp.full((N,), T, dtype=jnp.int32)

    S = 2 * L + 1
    # extended label sequence: blank, l1, blank, l2, ... blank
    ext = jnp.full((N, S), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(jnp.clip(lab, 0, C - 1))
    pos = jnp.arange(S)[None, :]  # (1,S)
    valid_ext = pos < (2 * lab_len[:, None] + 1)

    # transition allowed from s-2 when ext[s] != blank and ext[s] != ext[s-2]
    ext_prev2 = jnp.concatenate([jnp.full((N, 2), -1, dtype=jnp.int32), ext[:, :-2]], axis=1)
    can_skip = (ext != blank) & (ext != ext_prev2)

    # alpha init: alpha[0] = logp[0, blank], alpha[1] = logp[0, l1]
    batch = jnp.arange(N)
    init = jnp.full((N, S), _NEG_INF)
    init = init.at[:, 0].set(logp[0, batch, ext[:, 0]])
    init = init.at[:, 1].set(jnp.where(lab_len > 0, logp[0, batch, ext[:, 1]], _NEG_INF))

    def step(alpha, t):
        a_shift1 = jnp.concatenate([jnp.full((N, 1), _NEG_INF), alpha[:, :-1]], axis=1)
        a_shift2 = jnp.concatenate([jnp.full((N, 2), _NEG_INF), alpha[:, :-2]], axis=1)
        a_new = jnp.logaddexp(alpha, a_shift1)
        a_new = jnp.where(can_skip, jnp.logaddexp(a_new, a_shift2), a_new)
        emit = logp[t, batch[:, None], ext]  # (N,S)
        a_new = a_new + emit
        a_new = jnp.where(valid_ext, a_new, _NEG_INF)
        # freeze past sequence end
        active = (t < seq_len)[:, None]
        a_new = jnp.where(active, a_new, alpha)
        return a_new, None

    alpha, _ = lax.scan(step, init, jnp.arange(1, T))
    end1 = 2 * lab_len  # last blank
    end2 = jnp.maximum(2 * lab_len - 1, 0)
    ll = jnp.logaddexp(
        alpha[batch, end1],
        jnp.where(lab_len > 0, alpha[batch, end2], _NEG_INF),
    )
    return -ll


# ---------------------------------------------------------------------------
# SSD MultiBox ops (ref: src/operator/contrib/multibox_*.cc/.cu)
# ---------------------------------------------------------------------------
@register(name="_contrib_MultiBoxPrior", aliases=("MultiBoxPrior",), nondiff=True)
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False, steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor-box generation (ref: multibox_prior.cc). Pure XLA."""
    h, w = data.shape[2], data.shape[3]
    sizes = tuple(float(s) for s in (sizes if isinstance(sizes, (tuple, list)) else (sizes,)))
    ratios = tuple(float(r) for r in (ratios if isinstance(ratios, (tuple, list)) else (ratios,)))
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h) + offsets[0]) * step_y
    cx = (jnp.arange(w) + offsets[1]) * step_x
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), axis=-1).reshape(-1, 2)  # (h*w, 2)

    # anchors: sizes[0] with each ratio + other sizes with ratio 1 (ref layout:
    # n_anchors = len(sizes) + len(ratios) - 1)
    whs = []
    for s in sizes:
        whs.append((s * np.sqrt(ratios[0]), s / np.sqrt(ratios[0])))
    for r in ratios[1:]:
        whs.append((sizes[0] * np.sqrt(r), sizes[0] / np.sqrt(r)))
    whs = jnp.asarray(whs)  # (A, 2) — (w, h)

    n_anchor = whs.shape[0]
    centers = jnp.repeat(cyx, n_anchor, axis=0)  # (h*w*A, 2) [cy, cx]
    dims = jnp.tile(whs, (h * w, 1))  # (h*w*A, 2) [w, h]
    xmin = centers[:, 1] - dims[:, 0] / 2
    ymin = centers[:, 0] - dims[:, 1] / 2
    xmax = centers[:, 1] + dims[:, 0] / 2
    ymax = centers[:, 0] + dims[:, 1] / 2
    out = jnp.stack([xmin, ymin, xmax, ymax], axis=1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out[None]  # (1, h*w*A, 4)


@register(name="_contrib_MultiBoxTarget", aliases=("MultiBoxTarget",), num_outputs=3, nondiff=True)
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """Anchor→GT matching + box target encoding (ref: multibox_target.cc).

    anchor: (1, A, 4); label: (N, M, 5) [cls, xmin, ymin, xmax, ymax];
    cls_pred: (N, C, A). Outputs: box_target (N, A*4), box_mask (N, A*4),
    cls_target (N, A).
    """
    A = anchor.shape[1]
    N, M, _ = label.shape
    anc = anchor[0]  # (A,4)

    def iou(boxes_a, boxes_b):
        # a: (A,4), b: (M,4) → (A,M)
        ax1, ay1, ax2, ay2 = boxes_a[:, 0:1], boxes_a[:, 1:2], boxes_a[:, 2:3], boxes_a[:, 3:4]
        bx1, by1, bx2, by2 = boxes_b[:, 0], boxes_b[:, 1], boxes_b[:, 2], boxes_b[:, 3]
        ix1 = jnp.maximum(ax1, bx1[None, :])
        iy1 = jnp.maximum(ay1, by1[None, :])
        ix2 = jnp.minimum(ax2, bx2[None, :])
        iy2 = jnp.minimum(ay2, by2[None, :])
        iw = jnp.maximum(ix2 - ix1, 0)
        ih = jnp.maximum(iy2 - iy1, 0)
        inter = iw * ih
        area_a = jnp.maximum((ax2 - ax1) * (ay2 - ay1), 0)
        area_b = jnp.maximum((bx2 - bx1) * (by2 - by1), 0)
        union = area_a + area_b[None, :] - inter
        return jnp.where(union > 0, inter / union, 0.0)

    def encode(anc, gt):
        # center-size encoding with variances
        aw = anc[:, 2] - anc[:, 0]
        ah = anc[:, 3] - anc[:, 1]
        acx = (anc[:, 0] + anc[:, 2]) / 2
        acy = (anc[:, 1] + anc[:, 3]) / 2
        gw = gt[:, 2] - gt[:, 0]
        gh = gt[:, 3] - gt[:, 1]
        gcx = (gt[:, 0] + gt[:, 2]) / 2
        gcy = (gt[:, 1] + gt[:, 3]) / 2
        tx = (gcx - acx) / jnp.maximum(aw, 1e-8) / variances[0]
        ty = (gcy - acy) / jnp.maximum(ah, 1e-8) / variances[1]
        tw = jnp.log(jnp.maximum(gw / jnp.maximum(aw, 1e-8), 1e-8)) / variances[2]
        th = jnp.log(jnp.maximum(gh / jnp.maximum(ah, 1e-8), 1e-8)) / variances[3]
        return jnp.stack([tx, ty, tw, th], axis=1)

    def per_sample(lab):
        valid = lab[:, 0] >= 0  # (M,)
        ious = iou(anc, lab[:, 1:5]) * valid[None, :]  # (A,M)
        best_gt = jnp.argmax(ious, axis=1)  # (A,)
        best_iou = jnp.max(ious, axis=1)
        matched = best_iou >= overlap_threshold
        # force-match: each valid gt claims its best anchor
        best_anchor = jnp.argmax(ious, axis=0)  # (M,)
        forced = jnp.zeros((A,), dtype=bool)
        forced = forced.at[best_anchor].set(valid)
        forced_gt = jnp.zeros((A,), dtype=jnp.int32)
        forced_gt = forced_gt.at[best_anchor].set(jnp.arange(M, dtype=jnp.int32))
        use_gt = jnp.where(forced, forced_gt, best_gt)
        pos = matched | forced
        gt_boxes = lab[use_gt, 1:5]
        targets = encode(anc, gt_boxes)
        cls_t = jnp.where(pos, lab[use_gt, 0] + 1.0, 0.0)
        box_t = jnp.where(pos[:, None], targets, 0.0).reshape(-1)
        box_m = jnp.where(pos[:, None], 1.0, 0.0) * jnp.ones((A, 4))
        return box_t, box_m.reshape(-1), cls_t

    box_target, box_mask, cls_target = jax.vmap(per_sample)(label)
    return box_target, box_mask, cls_target


@register(name="_contrib_MultiBoxDetection", aliases=("MultiBoxDetection",), nondiff=True)
def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5, force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode + NMS (ref: multibox_detection.cc). Vectorized XLA NMS."""
    N, C, A = cls_prob.shape
    anc = anchor[0]

    aw = anc[:, 2] - anc[:, 0]
    ah = anc[:, 3] - anc[:, 1]
    acx = (anc[:, 0] + anc[:, 2]) / 2
    acy = (anc[:, 1] + anc[:, 3]) / 2

    def decode(loc):
        loc = loc.reshape(A, 4)
        cx = loc[:, 0] * variances[0] * aw + acx
        cy = loc[:, 1] * variances[1] * ah + acy
        w = jnp.exp(loc[:, 2] * variances[2]) * aw
        h = jnp.exp(loc[:, 3] * variances[3]) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        return boxes

    def box_iou(b):
        x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
        area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
        ix1 = jnp.maximum(x1[:, None], x1[None, :])
        iy1 = jnp.maximum(y1[:, None], y1[None, :])
        ix2 = jnp.minimum(x2[:, None], x2[None, :])
        iy2 = jnp.minimum(y2[:, None], y2[None, :])
        inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
        union = area[:, None] + area[None, :] - inter
        return jnp.where(union > 0, inter / union, 0.0)

    def per_sample(probs, loc):
        boxes = decode(loc)  # (A,4)
        # best non-background class per anchor
        fg = jnp.concatenate(
            [probs[:background_id], probs[background_id + 1 :]], axis=0
        )  # (C-1, A)
        cls_id = jnp.argmax(fg, axis=0).astype(jnp.float32)  # 0-based fg class
        score = jnp.max(fg, axis=0)
        keep = score > threshold
        order = jnp.argsort(-score)
        boxes_s = boxes[order]
        score_s = score[order]
        cls_s = cls_id[order]
        keep_s = keep[order]
        ious = box_iou(boxes_s)
        same_cls = (cls_s[:, None] == cls_s[None, :]) | force_suppress
        # suppressed if any earlier kept box overlaps > nms_threshold
        sup_matrix = (ious > nms_threshold) & same_cls & (
            jnp.arange(A)[None, :] < jnp.arange(A)[:, None]
        )

        def body(i, kept):
            sup = jnp.any(sup_matrix[i] & kept, where=None) if False else jnp.any(
                jnp.where(sup_matrix[i], kept, False)
            )
            return kept.at[i].set(keep_s[i] & ~sup)

        kept = lax.fori_loop(0, A, body, jnp.zeros((A,), dtype=bool))
        out_cls = jnp.where(kept, cls_s, -1.0)
        return jnp.concatenate(
            [out_cls[:, None], score_s[:, None], boxes_s], axis=1
        )  # (A, 6)

    return jax.vmap(per_sample)(cls_prob, loc_pred)


# ---------------------------------------------------------------------------
# quantization experiments (ref: src/operator/contrib/quantize.cc)
# ---------------------------------------------------------------------------
@register(name="_contrib_quantize", num_outputs=3, nondiff=True)
def quantize(data, min_range, max_range, out_type="uint8"):
    r_min = min_range.reshape(())
    r_max = max_range.reshape(())
    if out_type == "uint8":
        scale = 255.0 / jnp.maximum(r_max - r_min, 1e-8)
        q = jnp.clip(jnp.round((data - r_min) * scale), 0, 255).astype(jnp.uint8)
    else:
        scale = 127.0 / jnp.maximum(jnp.maximum(jnp.abs(r_min), jnp.abs(r_max)), 1e-8)
        q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    return q, r_min.reshape(1), r_max.reshape(1)


@register(name="_contrib_dequantize", nondiff=True)
def dequantize(data, min_range, max_range, out_type="float32"):
    r_min = min_range.reshape(())
    r_max = max_range.reshape(())
    if data.dtype == jnp.uint8:
        scale = (r_max - r_min) / 255.0
        return data.astype(jnp.float32) * scale + r_min
    scale = jnp.maximum(jnp.abs(r_min), jnp.abs(r_max)) / 127.0
    return data.astype(jnp.float32) * scale


@register(name="_contrib_count_sketch", nondiff=True)
def count_sketch(data, h, s, out_dim=0, processing_batch_size=32):
    """Count-sketch projection (ref: src/operator/contrib/count_sketch.cc)."""
    n, in_dim = data.shape
    od = int(out_dim)
    hh = h.reshape(-1).astype(jnp.int32) % od
    ss = s.reshape(-1)
    out = jnp.zeros((n, od), dtype=data.dtype)
    return out.at[:, hh].add(data * ss[None, :])


@register(name="_contrib_fft", nondiff=True)
def fft(data, compute_size=128):
    """ref: src/operator/contrib/fft.cc (cuFFT) → XLA fft. Output packs
    real/imag interleaved along last dim like the reference."""
    out = jnp.fft.fft(data, axis=-1)
    return jnp.stack([out.real, out.imag], axis=-1).reshape(data.shape[:-1] + (-1,)).astype(jnp.float32)


@register(name="_contrib_ifft", nondiff=True)
def ifft(data, compute_size=128):
    n = data.shape[-1] // 2
    cplx = data.reshape(data.shape[:-1] + (n, 2))
    comp = cplx[..., 0] + 1j * cplx[..., 1]
    return jnp.fft.ifft(comp, axis=-1).real.astype(jnp.float32) * n


# 2-bit gradient compression kernels (ref: src/kvstore/gradient_compression-inl.h)
@register(name="_contrib_quantize_2bit", nondiff=True, num_outputs=2)
def quantize_2bit(grad, residual, threshold=0.5):
    g = grad + residual
    q = jnp.where(g >= threshold, threshold, jnp.where(g <= -threshold, -threshold, 0.0))
    return q.astype(grad.dtype), (g - q).astype(grad.dtype)


@register(name="_contrib_dequantize_2bit", nondiff=True)
def dequantize_2bit(data, threshold=0.5):
    return data


alias("_contrib_CTCLoss", "_contrib_ctc_loss")
