"""Self-healing training: the reaction half of ISSUE 9.

The DETECTION half is the in-graph anomaly sentinel
(``parallel/spmd.py`` ``TrainStep(sentinel=...)``: per-step health word
+ device-resident counters, zero steady-state host syncs). This module
owns what happens when training is actually sick:

- :class:`HealthGuard` — the ``Module.fit`` guardrail. At a bounded
  cadence it inspects health (fused tier: drains the sentinel's device
  counters; per-executor/dist_async tier: finite-check on the batch
  outputs plus a batch cross-entropy), and on N consecutive unhealthy
  steps or a loss spike rolls the job back to
  ``CheckpointManager.latest()`` with a learning-rate backoff and a
  bounded rollback budget. On the dist_async parameter-server tier all
  ranks agree through NAMED barrier rounds (the PR 3 machinery — a
  respawn replaying an old phase can never pair with a live rollback)
  and every server restores exactly its shard through the same
  ``restore_from_checkpoint`` path elastic recovery uses (the ZeRO
  value-sharded layout included: with sharded optimizer state any
  rollback that bypasses the checkpoint layer is wrong by
  construction, arXiv:2004.13336).

- preemption-aware exit: ``launch.py``-spawned workers install a
  SIGTERM/SIGINT handler; the fit loop drains the dispatch-ahead
  in-flight steps at the next batch boundary, writes a resumable
  checkpoint inside the ``MXNET_PREEMPT_GRACE`` window, and exits with
  the distinguished :data:`EXIT_PREEMPTED` status that ``launch.py
  --max-restarts`` supervision respawns WITHOUT burning the restart
  budget. A hard-exit timer guarantees the process is gone within the
  grace window even if the checkpoint hangs.

Reference counterpart: none — the reference's answer to silent faults
is ``Monitor`` (host-side per-op stats, one device sync per batch,
python/mxnet/monitor.py) and its answer to preemption is "lose the
epoch". Counters ride ``dump_profile`` as ``healthStats``.
"""
from __future__ import annotations

import logging
import math
import os
import signal
import sys
import threading
import time

import numpy as np

from . import config, profiler
from .base import MXNetError

#: EX_TEMPFAIL — the resumable exit status a preempted worker reports
#: after its grace-window checkpoint; launch.py treats it as a FREE
#: respawn (the restart budget guards against crash loops, and a
#: preempted node did nothing wrong). Mirrored as a literal in
#: tools/launch.py, which stays stdlib-only.
EXIT_PREEMPTED = 75


class HealthGuard:
    """Detection→reaction→resumption guardrail for one ``fit()`` run.

    Constructed automatically by ``BaseModule.fit`` via
    :meth:`from_env` when the job has a coordinated checkpoint
    directory (``MXNET_CHECKPOINT_DIR``) and ``MXNET_TPU_GUARD=1``
    (the default); tests construct it directly with an explicit
    manager. All thresholds come from strict ``config.KNOBS``
    accessors — a malformed knob raises at arm time, never trains with
    a silently-substituted default.
    """

    def __init__(self, module, kv=None, manager=None, logger=None,
                 consec=None, spike=None, backoff=None, budget=None,
                 interval=None, grace=None):
        self.module = module
        self.kv = kv
        self.manager = manager
        self.logger = logger or logging.getLogger(__name__)
        self.consec = config.get_positive_int("MXNET_TPU_GUARD_CONSEC") \
            if consec is None else int(consec)
        self.spike = config.get_nonneg_float("MXNET_TPU_GUARD_SPIKE") \
            if spike is None else float(spike)
        self.backoff = config.get_positive_float("MXNET_TPU_GUARD_BACKOFF") \
            if backoff is None else float(backoff)
        if not 0.0 < self.backoff <= 1.0:
            raise MXNetError(
                "MXNET_TPU_GUARD_BACKOFF=%r must be in (0, 1] — a "
                "rollback that RAISES the learning rate re-diverges"
                % (self.backoff,))
        self.budget = config.get_nonneg_int("MXNET_TPU_GUARD_BUDGET") \
            if budget is None else int(budget)
        self.interval = config.get_positive_int("MXNET_TPU_GUARD_INTERVAL") \
            if interval is None else int(interval)
        self.grace = config.get_positive_float("MXNET_PREEMPT_GRACE") \
            if grace is None else float(grace)
        self.rollbacks = 0
        self._consec_host = 0
        self._ema = None
        self._warm = 0
        self._metric = None
        self._preempt = threading.Event()
        self._preempt_t = None
        self._handler_installed = False
        # Spike detection must only TRIGGER where every rank reaches
        # the same verdict, or the coordinated-rollback barrier never
        # pairs (one rank parks in health-rb-K-enter while its peers
        # keep training). The fused tier's sentinel word is replicated
        # by construction; the host tier's per-batch CE is rank-LOCAL,
        # so on a multi-worker server job a single rank's transient
        # spike would strand the barrier until its timeout kills the
        # job. Non-finite detection stays on everywhere: a poisoned
        # server weight poisons every rank's pulls, so that verdict IS
        # globally correlated (the bounded barrier timeout backstops
        # pathological skew).
        self._spike_coordinated = not (
            kv is not None and getattr(kv, "server_side", False)
            and int(getattr(kv, "num_workers", 1) or 1) > 1)

    # -- construction --------------------------------------------------------
    @classmethod
    def from_env(cls, module, kv=None, logger=None):
        """The armed guard for this job, or None: requires a
        coordinated checkpoint directory (rollback without a committed
        checkpoint to roll back TO is meaningless) and the
        MXNET_TPU_GUARD knob (default on)."""
        from .checkpoint import CheckpointManager

        if not config.get_strict_bool("MXNET_TPU_GUARD"):
            return None
        manager = CheckpointManager.from_env()
        if manager is None:
            return None
        return cls(module, kv=kv, manager=manager, logger=logger)

    # -- preemption-aware exit -----------------------------------------------
    def install_preemption_handler(self):
        """SIGTERM/SIGINT → resumable drain-checkpoint-exit, installed
        for launch.py-spawned workers (DMLC_ROLE=worker) from the main
        thread only; idempotent. Interactive/pytest processes (no DMLC
        role) keep the default signal disposition."""
        if self._handler_installed:
            return
        if os.environ.get("DMLC_ROLE", "").lower() != "worker":
            return
        if threading.current_thread() is not threading.main_thread():
            return
        try:
            signal.signal(signal.SIGTERM, self._on_signal)
            signal.signal(signal.SIGINT, self._on_signal)
        except ValueError:
            return  # embedded interpreter quirk: not installable
        self._handler_installed = True

    def _on_signal(self, signum, frame):
        if not self._preempt.is_set():
            self._preempt_t = time.monotonic()
            self._preempt.set()
            # the scheduler WILL kill us at the end of the grace window;
            # exiting resumable beats being SIGKILLed mid-checkpoint
            t = threading.Timer(self.grace, self._hard_exit)
            t.daemon = True
            t.start()
            os.write(2, (b"[health] preemption signal received: draining"
                         b" + checkpointing inside the grace window\n"))

    @staticmethod
    def _hard_exit():
        os.write(2, b"[health] preemption grace expired; exiting "
                    b"resumable without a fresh checkpoint\n")
        os._exit(EXIT_PREEMPTED)

    @property
    def preempt_requested(self):
        return self._preempt.is_set()

    def request_preemption(self):
        """Flag a preemption as if SIGTERM arrived (tests; also lets an
        external agent trigger the graceful path in-process). Does NOT
        arm the hard-exit timer — the caller owns the deadline."""
        self._preempt_t = time.monotonic()
        self._preempt.set()

    # -- the per-batch hook (called by BaseModule.fit) -----------------------
    def on_batch(self, epoch, nbatch, eval_metric=None, labels=None):
        """Batch-boundary hook: handles a pending preemption (raises
        ``SystemExit(EXIT_PREEMPTED)``), then runs the health check at
        its cadence and rolls back when training is sick."""
        self._metric = eval_metric
        if self._preempt.is_set():
            self._preempt_exit(epoch, nbatch)
        fused = getattr(self.module, "_fused", None)
        if fused is not None:
            if getattr(fused, "sentinel", "off") == "off":
                return  # no in-graph word; checking would mean a
                # per-batch device sync — exactly what the fused tier
                # exists to avoid (arm MXNET_TPU_SENTINEL)
            if (nbatch + 1) % self.interval:
                return
            self._check_sentinel(fused.health_stats())
        else:
            self._check_host(labels)

    # -- detection -----------------------------------------------------------
    def _check_sentinel(self, snap):
        if not snap:
            return
        if snap["consec"] >= self.consec:
            self.rollback("sentinel: %d consecutive unhealthy steps "
                          "(nonfinite loss=%d grad=%d param=%d)"
                          % (snap["consec"], snap["nonfinite_loss"],
                             snap["nonfinite_grad"],
                             snap["nonfinite_param"]))
        elif snap["last_healthy"] and self._spiked(snap["last_loss"]):
            self.rollback("loss spike: %.4g > %gx EMA %.4g"
                          % (snap["last_loss"], self.spike, self._ema))

    def _check_host(self, labels):
        """Per-executor tiers (dist_async server-side optimizer, local):
        outputs are already host-materialized at batch rate by the host
        metric path, so a finite-check adds no new sync semantics."""
        mod = self.module
        try:
            out = mod.get_outputs()[0].asnumpy()
        except Exception:
            return
        if not np.isfinite(out).all():
            self._consec_host += 1
            profiler.health_record(host_unhealthy=1)
            if self._consec_host >= self.consec:
                self.rollback("host check: %d consecutive batches with "
                              "non-finite outputs" % self._consec_host)
            return
        self._consec_host = 0
        if not self._spike_coordinated:
            return  # rank-local CE must not strand the rollback barrier
        loss = self._batch_ce(out, labels)
        if loss is not None and self._spiked(loss):
            self.rollback("loss spike: %.4g > %gx EMA %.4g"
                          % (loss, self.spike, self._ema))

    @staticmethod
    def _batch_ce(out, labels):
        """Mean cross-entropy of one batch from host prob outputs, or
        None when the shapes don't look like (probs, int labels)."""
        if not labels:
            return None
        lbl = labels[0]
        lbl = lbl.asnumpy() if hasattr(lbl, "asnumpy") else np.asarray(lbl)
        lbl = lbl.reshape(-1)
        if out.ndim != 2 or out.shape[0] != lbl.shape[0]:
            return None
        idx = lbl.astype(np.int64)
        if idx.size == 0 or idx.min() < 0 or idx.max() >= out.shape[1]:
            return None
        picked = out[np.arange(idx.size), idx]
        return float(-np.mean(np.log(picked + 1e-12)))

    _SPIKE_WARMUP = 5  # checks before the EMA is trusted

    def _spiked(self, loss):
        if self.spike <= 0 or not math.isfinite(loss):
            return False
        if self._ema is None:
            self._ema = loss
            self._warm = 1
            return False
        spiked = (self._warm >= self._SPIKE_WARMUP
                  and loss > self.spike * max(self._ema, 1e-8))
        if not spiked:
            self._ema = 0.9 * self._ema + 0.1 * loss
            self._warm += 1
        return spiked

    # -- reaction: coordinated rollback --------------------------------------
    def rollback(self, reason):
        """Roll the job back to the newest committed checkpoint with LR
        backoff. Budget-bounded: past MXNET_TPU_GUARD_BUDGET the next
        trigger fails the job loudly instead of looping — the elastic
        supervision (launch.py --max-restarts) then resumes it from the
        same checkpoint with a fresh process."""
        self.rollbacks += 1
        profiler.health_record(rollbacks=1)
        if self.rollbacks > self.budget:
            raise MXNetError(
                "health guard: %s, but the rollback budget (%d) is "
                "exhausted — failing the job (elastic supervision "
                "resumes from the last checkpoint)" % (reason, self.budget))
        ck = self.manager.latest() if self.manager is not None else None
        if ck is None:
            raise MXNetError(
                "health guard: %s, and no committed checkpoint exists "
                "to roll back to (%s)"
                % (reason, getattr(self.manager, "directory", None)))
        self.logger.warning(
            "[health] %s: rolling back to %s (epoch %d), lr backoff x%g "
            "(rollback %d/%d)", reason, ck.path, ck.epoch, self.backoff,
            self.rollbacks, self.budget)
        print("[health] event=rollback reason=%r ckpt=%s epoch=%d "
              "count=%d" % (reason, ck.path, ck.epoch, self.rollbacks),
              flush=True)
        if self.kv is not None and getattr(self.kv, "server_side", False):
            self._rollback_server(ck)
        else:
            self._rollback_local(ck)
        if self._metric is not None:
            self._metric.reset()  # drop the poisoned accumulations
        self._consec_host = 0
        self._ema = None
        self._warm = 0

    def _backoff_lr(self, opt):
        """Scale the imperative optimizer's lr; scheduler-driven lr
        cannot be backed off (set_learning_rate raises) — warn, don't
        abort the rollback that is saving the job."""
        if opt is None:
            return
        try:
            opt.set_learning_rate(opt.lr * self.backoff)
        except MXNetError as e:
            self.logger.warning("[health] lr backoff skipped: %s", e)

    def _rollback_local(self, ck):
        """kvstore='tpu' fused tier and local tiers: weights + aux +
        optimizer state restored module-side from the checkpoint; on
        the fused tier the LR backoff rebuilds the compiled step
        (reset_optimizer) so the new rate is baked into the program."""
        from .ndarray import ndarray as nd

        mod = self.module
        arg_ck, aux_ck = ck.split_weights()
        if not arg_ck:
            raise MXNetError("health guard: checkpoint %s holds no "
                             "weights to roll back to" % ck.path)
        mod.set_params({k: nd.array(v) for k, v in arg_ck.items()},
                       {k: nd.array(v) for k, v in aux_ck.items()},
                       allow_missing=False, force_init=True,
                       allow_extra=True)
        states = ck.optimizer_states_path()
        if states is None:
            shards = ck.optimizer_state_shard_paths()
            states = shards[0] if len(shards) == 1 else None
        if states is not None and getattr(mod, "optimizer_initialized",
                                          False):
            try:
                mod.load_optimizer_states(states)
            except MXNetError as e:
                # a checkpoint from another tier's format: weights are
                # restored either way; state restarts cold
                self.logger.warning(
                    "[health] optimizer state not restored (%s); "
                    "momentum restarts from zero", e)
        opt = getattr(mod, "_optimizer", None)
        self._backoff_lr(opt)
        fused = getattr(mod, "_fused", None)
        if fused is not None and opt is not None:
            fused.reset_optimizer(opt)

    def _rollback_server(self, ck):
        """dist_async tier: all ranks agree via named barrier rounds
        (PR 3 machinery) — the window between the two barriers is
        quiesced exactly like the elastic checkpoint's commit phase (no
        rank has a push in flight: barrier() drains the async
        pipeline) — then every server reloads ITS shard from its own
        checkpoint directory via the elastic-recovery restore path, and
        every worker refreshes its executors from the restored weights
        BEFORE the next forward (a forward on poisoned weights would
        push poisoned gradients right back)."""
        kv, mod = self.kv, self.module
        k = self.rollbacks
        kv.barrier("health-rb-%d-enter" % k)
        # EVERY rank drops its 2-bit error-feedback residuals inside
        # the quiesced window: a NaN-contaminated residual would
        # quantize that rank's future pushes to all-zero codes forever
        if hasattr(kv, "reset_gradient_residuals"):
            kv.reset_gradient_residuals()
        if kv.rank == 0:
            info = kv.rollback_servers(lr_scale=self.backoff, gen=k)
            self.logger.warning(
                "[health] servers restored %s keys from checkpoint "
                "epoch %s (lr now %s)", info.get("keys"),
                info.get("epoch"), info.get("lr"))
        kv.barrier("health-rb-%d-restored" % k)
        _arg_ck, aux_ck = ck.split_weights()
        from .ndarray import ndarray as nd

        for name, v in aux_ck.items():
            if name in mod._aux_params:
                nd.array(v).copyto(mod._aux_params[name])
        names = sorted(mod._arg_params)
        if names:
            kv.pull(names, [mod._arg_params[n] for n in names], priority=0)
        mod._exec_group.set_params(mod._arg_params, mod._aux_params)
        mod._params_dirty = False
        # local mirror of the server-side backoff (logs/inspection)
        self._backoff_lr(getattr(mod, "_optimizer", None))

    # -- reaction: preemption -------------------------------------------------
    def _preempt_exit(self, epoch, nbatch):
        """Drain → checkpoint → exit resumable. Runs at a batch
        boundary (the signal handler only sets a flag: the quiesce
        choreography cannot run in signal context mid-step)."""
        profiler.health_record(preemptions=1)
        mod = self.module
        fused = getattr(mod, "_fused", None)
        if fused is not None:
            try:
                fused.drain()  # retire the dispatch-ahead pipeline
            except Exception:
                pass
        wrote = False
        if self.manager is not None:
            try:
                wrote = self._write_preemption_checkpoint(epoch, nbatch)
            except Exception as e:
                self.logger.warning(
                    "[health] preemption checkpoint failed (%s); exiting "
                    "resumable against the previous checkpoint", e)
        elapsed = 0.0 if self._preempt_t is None \
            else time.monotonic() - self._preempt_t
        print("[health] event=preempted epoch=%d nbatch=%d "
              "checkpoint=%s elapsed=%.1fs exit=%d"
              % (epoch, nbatch, wrote, elapsed, EXIT_PREEMPTED),
              flush=True)
        raise SystemExit(EXIT_PREEMPTED)

    def _write_preemption_checkpoint(self, epoch, nbatch):
        """One worker's solo resumable snapshot, committed under the
        epoch it was preempted IN (semantics: 'resume at epoch E', the
        same contract as the coordinated epoch-end checkpoints — a
        re-commit of the same epoch replaces it). Deliberately
        barrier-free: a single preempted worker cannot run the 3-phase
        choreography (its peers are still training and would never
        arrive), and on the dist_async tier a snapshot without a global
        quiesce has exactly the ordering skew the asynchronous tier
        already accepts. Weights come through ``get_params`` — the
        batched server pull on dist_async, the drained device fetch on
        the fused tier."""
        mgr, mod, kv = self.manager, self.module, self.kv
        rank = int(getattr(kv, "rank", 0) or 0) if kv is not None else 0
        epoch = int(epoch)
        mgr.begin(epoch)
        mgr.write_worker_state(epoch, rank, {
            "epoch": epoch, "nbatch": int(nbatch), "preempted": True,
            "numpy_rng": np.random.get_state()})
        arg, aux = mod.get_params()
        weights = {"arg:%s" % k: v.asnumpy() for k, v in arg.items()}
        weights.update({"aux:%s" % k: v.asnumpy() for k, v in aux.items()})
        opt_config = None
        if kv is not None and getattr(kv, "server_side", False):
            kv.save_optimizer_states(
                mgr.staged_optimizer_states_path(epoch))
            opt_config = kv.get_optimizer_config()
        elif getattr(mod, "optimizer_initialized", False):
            mod.save_optimizer_states(
                mgr.staged_optimizer_states_path(epoch))
        num_workers = getattr(kv, "num_workers", 1) if kv is not None else 1
        mgr.commit(epoch, weights=weights, optimizer_config=opt_config,
                   num_workers=num_workers)
        return True
