"""The MXNET_* environment-knob surface.

Reference counterpart: the ~31 ``MXNET_*`` env vars read through
``dmlc::GetEnv`` across the reference runtime (SURVEY §5.6 tier 2).
Every reference knob is listed here with its TPU-native disposition:

- ``honored``   — changes behavior in this framework (reader cited).
- ``subsumed``  — the concern is owned by XLA/jax (e.g. stream counts,
                  memory pools, kernel tuning); setting it is a no-op by
                  design, not an accident.
- ``accepted``  — parsed and stored for API compatibility; consumers may
                  read it via :func:`get`.

``describe()`` returns the full table (the ``mx.runtime``-style
feature/knob introspection the reference never quite had); ``get``/
``get_int``/``get_bool`` are the typed accessors used by the framework
itself.
"""
from __future__ import annotations

import os

# name -> (default, status, description)
KNOBS = {
    # --- engine (src/engine/) ---
    "MXNET_ENGINE_TYPE": (
        "ThreadedEngine", "honored",
        "host dependency engine implementation (ThreadedEngine|NaiveEngine); "
        "read by engine.create (engine.py)"),
    "MXNET_CPU_WORKER_NTHREADS": (
        "4", "honored",
        "native engine worker thread count (engine.py; src/engine.cc)"),
    "MXNET_CPU_PRIORITY_NTHREADS": (
        "4", "subsumed",
        "priority pool size — XLA async dispatch owns device ordering"),
    "MXNET_GPU_WORKER_NTHREADS": (
        "2", "subsumed", "per-accelerator worker threads — XLA-owned"),
    "MXNET_ENGINE_INFO": (
        "0", "accepted", "verbose engine scheduling logs"),
    # --- executor (src/executor/) ---
    "MXNET_EXEC_BULK_EXEC_TRAIN": (
        "1", "subsumed", "op bulking — jit compiles the whole graph anyway"),
    "MXNET_EXEC_BULK_EXEC_INFERENCE": (
        "1", "subsumed", "op bulking — as above"),
    "MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN": (
        "15", "subsumed", "bulk segment cap — whole-graph jit"),
    "MXNET_EXEC_NUM_TEMP": (
        "1", "subsumed", "temp-space arenas — XLA memory planning"),
    "MXNET_BACKWARD_DO_MIRROR": (
        "0", "honored",
        "recompute-in-backward (sublinear memory): wraps the executor's "
        "fwd+bwd program in jax.checkpoint (executor.py _get_compiled)"),
    "MXNET_EXEC_INPLACE_GRAD_SUM_CAP": (
        "8", "subsumed", "gradient-sum inplace cap — XLA buffer planning"),
    # --- memory (src/storage/) ---
    "MXNET_GPU_MEM_POOL_RESERVE": (
        "5", "subsumed", "device pool watermark — XLA/TPU allocator owns HBM"),
    "MXNET_TPU_HOST_POOL_BYTES": (
        str(1 << 30), "honored",
        "native host storage-pool cap in bytes (storage.py)"),
    # --- kvstore (src/kvstore/) ---
    "MXNET_KVSTORE_REDUCTION_NTHREADS": (
        "4", "subsumed", "CPU reduce threads — reductions compile into XLA"),
    "MXNET_KVSTORE_BIGARRAY_BOUND": (
        str(1000 * 1000), "accepted",
        "big-array server-sharding threshold (serverless design: the DCN "
        "collective is already key-batched, kvstore.py DistKVStore._flush)"),
    "MXNET_KVSTORE_SERIAL_PUSH": (
        "0", "accepted", "serialize push processing"),
    "MXNET_ENABLE_GPU_P2P": (
        "1", "subsumed", "peer-to-peer copies — ICI collectives"),
    # --- cudnn/tuning (disappear into the XLA compiler) ---
    "MXNET_CUDNN_AUTOTUNE_DEFAULT": (
        "0", "subsumed", "conv algo autotuning — XLA picks"),
    "MXNET_USE_OPERATOR_TUNING": (
        "1", "subsumed", "OMP cost-model tuning — XLA fusion"),
    "MXNET_USE_NUM_CORES_OPERATOR_TUNING": (
        "0", "subsumed", "as above"),
    # --- profiler (src/engine/profiler.cc; profiler.py) ---
    "MXNET_PROFILER_MODE": (
        "symbolic", "honored",
        "profiler mode at autostart (symbolic|all) — profiler.py"),
    "MXNET_PROFILER_AUTOSTART": (
        "0", "honored",
        "start the profiler at import; dump on exit — profiler.py"),
    "MXNET_TPU_JAX_TRACE_DIR": (
        "", "honored",
        "also capture a jax/XPlane device trace into this dir when the "
        "profiler runs (profiler.py)"),
    # --- IO ---
    "MXNET_CPU_TEMP_COPY": (
        "4", "subsumed", "IO staging copies — host runtime"),
    # --- distributed roles (dmlc/ps-lite launcher contract) ---
    "DMLC_ROLE": (
        "worker", "honored",
        "worker|server|scheduler — server/scheduler are exit-0 shims in "
        "the serverless design (kvstore_server.py)"),
    "DMLC_PS_ROOT_URI": (
        "", "honored", "coordinator host (dist.py env_spec)"),
    "DMLC_PS_ROOT_PORT": (
        "9091", "honored", "coordinator port (dist.py env_spec)"),
    "DMLC_NUM_WORKER": (
        "1", "honored", "world size (dist.py env_spec)"),
    "DMLC_WORKER_ID": (
        "0", "honored", "worker rank (dist.py env_spec)"),
    # --- input pipeline / fit hot loop (ISSUE 5) ---
    "MXNET_TPU_FEED_DEPTH": (
        "2", "honored",
        "DeviceQueueIter bounded pipeline depth: batches staged on the "
        "mesh ahead of the consumer (parallel/feed.py)"),
    "MXNET_TPU_MAX_INFLIGHT": (
        "2", "honored",
        "fused fit loop dispatch-ahead bound: compiled steps in flight "
        "before the host throttles (module/spmd_group.py)"),
    "MXNET_TPU_DEVICE_METRICS": (
        "1", "honored",
        "fold per-batch metric stats computed inside the compiled step "
        "into device accumulators; host device_get only at Speedometer/"
        "epoch boundaries (module/spmd_group.py, metric.py)"),
    # --- weight-update sharding / ZeRO (ISSUE 7) ---
    "MXNET_TPU_ZERO": (
        "0", "honored",
        "shard the weight update (reduce-scatter grads, update a 1/N "
        "optimizer-state shard, all-gather weights) over the data axes "
        "of the fused SPMD step — arXiv:2004.13336 (parallel/spmd.py, "
        "module/spmd_group.py); 0|1, anything else raises"),
    "MXNET_TPU_ZERO_WIRE": (
        "raw", "honored",
        "gradient-shard wire treatment inside the ZeRO step: 'raw' or "
        "'2bit' (the PR 4 error-feedback two-bit quantizer applied to "
        "the reduce-scattered shard; residual is 1/N-sharded too) "
        "(parallel/spmd.py)"),
    "MXNET_TPU_ZERO_WIRE_THRESHOLD": (
        "0.5", "honored",
        "ternary threshold for MXNET_TPU_ZERO_WIRE=2bit; finite float "
        "> 0 (parallel/spmd.py)"),
    "MXNET_TPU_ZERO_MIN_SIZE": (
        "1024", "honored",
        "parameters with fewer elements keep replicated optimizer "
        "state (sharding tiny biases costs more collective latency "
        "than the bytes saved); shared by the fused tier and the "
        "dist_async value-sharded server tier (parallel/spmd.py, "
        "kvstore_server.py)"),
    "MXNET_TPU_ZERO_SERVER": (
        "0", "honored",
        "dist_async mirror of weight-update sharding: slice each "
        "large dense key's value AND optimizer state across ALL "
        "servers (push scatters slices, pull gathers) so per-server "
        "memory scales 1/num_servers; must be set job-wide "
        "(kvstore_server.py); 0|1, anything else raises"),
    # --- self-healing training (ISSUE 9) ---
    "MXNET_TPU_SENTINEL": (
        "off", "honored",
        "in-graph anomaly sentinel for the fused step: per-step health "
        "word (finite loss, global grad norm, updated params) computed "
        "INSIDE the compiled program with device-resident counters — "
        "no per-batch host sync. off|record|skip|halt: 'record' only "
        "counts, 'skip' additionally turns an unhealthy step into a "
        "no-op (pre-update params/opt-state selected via jnp.where — "
        "bit-identical params), 'halt' checks the health word on host "
        "EVERY step (a per-batch sync, counted in host_syncs) and "
        "raises on the first unhealthy one (parallel/spmd.py)"),
    "MXNET_TPU_GUARD": (
        "1", "honored",
        "arm the Module.fit self-healing guardrail when a coordinated "
        "checkpoint directory (MXNET_CHECKPOINT_DIR) is configured: "
        "consecutive-unhealthy / loss-spike detection triggers a "
        "coordinated rollback to CheckpointManager.latest() with LR "
        "backoff (health.py); 0|1, anything else raises"),
    "MXNET_TPU_GUARD_CONSEC": (
        "3", "honored",
        "consecutive unhealthy steps (fused: the sentinel's device "
        "consec counter; host tier: consecutive non-finite-output "
        "batches) that trigger a rollback (health.py)"),
    "MXNET_TPU_GUARD_SPIKE": (
        "10.0", "honored",
        "loss-spike rollback trigger: a checked loss above this ratio "
        "of its running EMA rolls back; 0 disables spike detection "
        "(health.py)"),
    "MXNET_TPU_GUARD_BACKOFF": (
        "0.5", "honored",
        "learning-rate multiplier applied on every rollback (in (0, "
        "1]); applied server-side on dist_async via the rollback RPC "
        "and via a fused-step rebuild on kvstore='tpu' (health.py)"),
    "MXNET_TPU_GUARD_BUDGET": (
        "2", "honored",
        "bounded rollback budget: after this many rollbacks the next "
        "trigger fails the job loudly (elastic supervision resumes it "
        "from the last checkpoint) instead of looping (health.py)"),
    "MXNET_TPU_GUARD_INTERVAL": (
        "10", "honored",
        "fused-tier guard check cadence in batches: the sentinel "
        "counters are drained (one blocking device read) every N "
        "batches, amortized like the Speedometer (health.py)"),
    "MXNET_PREEMPT_GRACE": (
        "15", "honored",
        "preemption grace window in seconds: on SIGTERM/SIGINT a "
        "launch.py-spawned worker drains in-flight steps and writes a "
        "resumable checkpoint, then exits with the distinguished "
        "EXIT_PREEMPTED status; a hard-exit timer guarantees the "
        "process is gone within the window either way (health.py)"),
    # --- elastic recovery / fault injection (ISSUE 3, registered here
    # per the ISSUE 9 knob-drift audit) ---
    "MXNET_CHECKPOINT_DIR": (
        "", "honored",
        "coordinated checkpoint directory (CheckpointManager.from_env; "
        "exported by tools/launch.py to every role)"),
    "MXNET_CHECKPOINT_PERIOD": (
        "1", "honored", "checkpoint every N epochs (checkpoint.py)"),
    "MXNET_CHECKPOINT_RETAIN": (
        "2", "honored", "newest complete checkpoints kept (checkpoint.py)"),
    "MXNET_MAX_RESTARTS": (
        "0", "honored",
        "elastic respawn budget per node; > 0 switches the tracker and "
        "server barriers into elastic mode (tracker.py, launch.py)"),
    "MXNET_FAULT_SPEC": (
        "", "honored",
        "deterministic fault injection rules (chaos.py grammar: "
        "crash/nan/preempt @step, rpc drop, heartbeat stall)"),
    # --- kvstore data plane (ISSUE 4, registered per the drift audit) ---
    "MXNET_KVSTORE_PIPELINE": (
        "1", "honored",
        "async per-shard sender pipeline for the server tier; 0 falls "
        "back to the synchronous client (kvstore_server.py)"),
    "MXNET_KVSTORE_RPC_RETRIES": (
        "2", "honored",
        "bounded kvstore RPC retries with reconnect + server "
        "rediscovery (kvstore_server.py)"),
    "MXNET_KVSTORE_RECONNECT_DEADLINE": (
        "5", "honored", "seconds per reconnect attempt (kvstore_server.py)"),
    "MXNET_KVSTORE_REDISCOVER_TIMEOUT": (
        "30", "honored",
        "seconds to wait for a respawned server's new URI via the "
        "tracker (kvstore_server.py)"),
    "MXNET_KVSTORE_COALESCE_KEYS": (
        "16", "honored", "max keys per coalesced push_multi frame"),
    "MXNET_KVSTORE_COALESCE_BYTES": (
        str(1 << 20), "honored", "max bytes per coalesced push_multi frame"),
    "MXNET_KVSTORE_BARRIER_TIMEOUT": (
        "120", "honored",
        "server barrier timeout in seconds — raises instead of "
        "spinning (kvstore_server.py)"),
    # --- tracker / process topology (ISSUE 2, registered per the
    # drift audit; the per-role DMLC-style identity vars launch.py
    # sets are allowlisted in tests/test_knob_registry.py instead) ---
    "MXNET_TRACKER_HEARTBEAT_INTERVAL": (
        "2.0", "honored", "client heartbeat period in seconds (tracker.py)"),
    "MXNET_TRACKER_HEARTBEAT_TIMEOUT": (
        "30.0", "honored",
        "scheduler-side beat-loss dead-node threshold (tracker.py)"),
    "MXNET_TRACKER_BARRIER_TIMEOUT": (
        "120", "honored", "tracker barrier timeout in seconds (tracker.py)"),
    "MXNET_PS_SERVER_URI": (
        "", "honored",
        "manual server URI list for deployments without the tracker "
        "rendezvous (kvstore_server.py)"),
    "MXNET_PS_BIND_HOST": (
        "", "honored", "server bind host override (kvstore_server.py)"),
    "MXNET_PS_BIND_PORT": (
        "0", "honored", "server bind port override (kvstore_server.py)"),
    "MXNET_PS_ADVERTISE_HOST": (
        "", "honored",
        "address a multi-host server publishes to the tracker "
        "(kvstore_server.py)"),
    # --- Pallas schedule autotuner (ISSUE 10) ---
    "MXNET_TPU_TUNE": (
        "1", "honored",
        "consult the on-disk schedule table for searched Pallas kernel "
        "schedules at trace time (kernels consult tune.schedule_for "
        "with the hand defaults as fallback — an empty table is "
        "bit-identical to the pre-autotuner behavior); 0 pins the hand "
        "defaults (tune/table.py)"),
    "MXNET_TPU_TUNE_TABLE": (
        "", "honored",
        "schedule-table path override (default ~/.cache/mxnet_tpu/"
        "schedule_table.json); written atomically by "
        "tools/tune_kernels.py, keyed (kernel, shape, dtype, backend) "
        "(tune/table.py)"),
    # --- learned cost model / ranked sweeps / background tuning
    # (ISSUE 15) ---
    "MXNET_TUNE_RANKER": (
        "1", "honored",
        "rank sweep candidates with the learned cost model and time "
        "only the top MXNET_TUNE_TOPK (hand default always timed as "
        "baseline); the ranker abstains into the exhaustive sweep when "
        "the model is missing, under-trained, or below the validation "
        "rank-correlation floor — 0 pins the PR 10 exhaustive sweep "
        "(tune/search.py)"),
    "MXNET_TUNE_TOPK": (
        "3", "honored",
        "how many model-ranked candidates a ranked sweep times, on top "
        "of the always-timed hand default (tune/search.py)"),
    "MXNET_TUNE_MODEL": (
        "", "honored",
        "cost-model path override (default: next to the schedule "
        "table, <table>.model.json); versioned JSON written atomically "
        "by model refits — corrupt files log, behave as absent, and "
        "are rewritten whole by the next fit (tune/model.py)"),
    "MXNET_TUNE_BACKGROUND": (
        "0", "honored",
        "arm tune.BackgroundTuner in Module.fit: bounded tuning slots "
        "at epoch/checkpoint drain boundaries for shapes the job "
        "traced (schedule-table misses), never inside the steady-state "
        "step loop (tune/background.py)"),
    "MXNET_TUNE_BG_BUDGET": (
        "2", "honored",
        "max timed programs per background-tuning slot, hand default "
        "included (tune/background.py)"),
    # --- misc registered per the drift audit ---
    "MXNET_TPU_FUSED_ROW_TILE": (
        "", "honored",
        "fused Pallas kernel row-tile override; strict-parsed (a "
        "malformed value raises with the knob name) and cached per "
        "value (kernels/fused_block.py)"),
    "MXNET_GLUON_REPO": (
        "", "honored",
        "gluon model-zoo repo URL or local directory "
        "(gluon/model_zoo/model_store.py)"),
    "MXNET_INFER_DEBUG": (
        "0", "honored",
        "full tracebacks from shape/type inference failures "
        "(executor.py)"),
    # --- serving tier (ISSUE 6) ---
    "MXNET_SERVE_BATCH_LADDER": (
        "1,4,16,64", "honored",
        "comma-separated batch-size buckets the AOT predictor binds; "
        "requests pad up to the nearest bucket (serving/predictor.py; "
        "malformed or non-increasing ladders raise)"),
    "MXNET_SERVE_QUEUE_DEPTH": (
        "256", "honored",
        "per-model bounded request queue; a full queue backpressures "
        "submit() (serving/broker.py)"),
    "MXNET_SERVE_MAX_EXECUTABLES": (
        "32", "honored",
        "LRU capacity of compiled (model, bucket, dtype) executables "
        "shared by all resident models (serving/predictor.py)"),
    "MXNET_SERVE_SUBMIT_TIMEOUT": (
        "60", "honored",
        "seconds submit() may block on backpressure before raising "
        "(serving/broker.py)"),
    # --- graph IR passes + quantized serving (ISSUE 13) ---
    "MXNET_IR_PASSES": (
        "fusion", "honored",
        "default pass pipeline for ir.apply_passes(passes=None): a "
        "comma list of registered pass names (fusion|residual|layout|"
        "quantize); unknown names raise naming this knob "
        "(ir/passes.py)"),
    "MXNET_IR_FUSE": (
        "1", "honored",
        "kill switch for rule-based fusion in the model builders: "
        "build_resnet(fused=True) applies the IR fusion pass when 1, "
        "returns the unfused graph when 0 (models/resnet.py); 0|1, "
        "anything else raises"),
    "MXNET_SERVE_QUANT": (
        "none", "honored",
        "default serving quantization mode when AOTPredictor "
        "quant=None: 'none' or 'int8' (int8 needs calib_data= — "
        "asking without it raises CalibrationError) "
        "(serving/predictor.py, ir/quantize.py)"),
    "MXNET_QUANT_CALIB_BATCHES": (
        "8", "honored",
        "max calibration batches the int8 quantization pass consumes "
        "from the provided calibration data; integer >= 1 "
        "(ir/quantize.py)"),
    # --- training-graph passes (ISSUE 19) ---
    "MXNET_IR_TRAIN_PASSES": (
        "", "honored",
        "default pass pipeline rewriting the TRAINING graph when "
        "TrainStep(train_passes=None): a comma list of registered "
        "pass names (fusion|residual|layout), empty = no rewrite; "
        "unknown names raise (parallel/spmd.py, ir/passes.py)"),
    "MXNET_TPU_REMAT": (
        "0", "honored",
        "default rematerialization mode when TrainStep(remat=None): "
        "0|off = none, 1 = full recompute, conv = save MXU-primitive "
        "outputs, pass = the per-site IR plan (ir/remat.py) via named "
        "checkpointing; anything else raises (parallel/spmd.py)"),
    "MXNET_IR_LAYOUT": (
        "1", "honored",
        "kill switch for the whole-graph layout-selection pass: 1 "
        "runs the transpose compose/sink/cancel rules, 0 makes the "
        "'layout' pass a no-op (ir/passes.py, ir/layout.py); 0|1, "
        "anything else raises"),
    # --- serving fleet (ISSUE 11) ---
    "MXNET_FLEET_RETRIES": (
        "2", "honored",
        "router retry budget per request BEYOND the first attempt: "
        "never-sent failures and admission rejections (draining/"
        "closed/overloaded) retry on a DIFFERENT replica, in-flight "
        "losses retry only for idempotent requests; integer >= 0 "
        "(serving/fleet.py)"),
    "MXNET_FLEET_TIMEOUT": (
        "30", "honored",
        "per-request end-to-end deadline budget in seconds across ALL "
        "router attempts (also forwarded to the replica as the "
        "deadline-at-dequeue shed bound); finite float > 0 "
        "(serving/fleet.py)"),
    "MXNET_FLEET_BACKOFF": (
        "0.05", "honored",
        "base exponential backoff in seconds between router retry "
        "attempts (doubles per attempt, capped at 1 s); finite float "
        ">= 0 (serving/fleet.py)"),
    "MXNET_FLEET_VIEW_INTERVAL": (
        "2.0", "honored",
        "tracker-view refresh period in seconds: the router re-reads "
        "the replica membership/load gauges, and each replica "
        "re-publishes its load at the same cadence; finite float > 0 "
        "(serving/fleet.py)"),
    "MXNET_FLEET_CONNECT_DEADLINE": (
        "5.0", "honored",
        "seconds the router spends connecting to one replica before "
        "counting the attempt as never-sent and failing over; finite "
        "float > 0 (serving/fleet.py)"),
    "MXNET_SERVE_DRAIN_TIMEOUT": (
        "30", "honored",
        "seconds a draining replica waits for queued + in-flight "
        "requests to finish before the drain RPC errors (the rolling "
        "fleet_swap bound); finite float > 0 (serving/fleet.py)"),
    # --- generative serving (ISSUE 12) ---
    "MXNET_GENERATE_MAX_STEPS": (
        "256", "honored",
        "decode-step cap per generate request (also the default "
        "max_new_tokens): a request that never emits EOS — wedged "
        "client, chaos generate:stall — finishes with reason 'length' "
        "at this many generated tokens and its slot + KV pages are "
        "recycled; integer >= 1 (serving/broker.py GenerateServer)"),
    "MXNET_GENERATE_SLOTS": (
        "8", "honored",
        "batch-slot count of the continuous-batching decode program: "
        "the static batch dimension every decode step runs at; new "
        "requests are admitted into vacated slots every step; integer "
        ">= 1 (serving/generate.py GenerativePredictor)"),
    "MXNET_GENERATE_PAGE_SIZE": (
        "16", "honored",
        "tokens per KV-cache page: the paged allocator's block size — "
        "a finished request returns ceil(len/page_size) pages to the "
        "pool immediately; integer >= 1 (serving/generate.py)"),
    "MXNET_GENERATE_POOL_BYTES": (
        "0", "honored",
        "KV page-pool budget in bytes; 0 auto-sizes to slots x "
        "max-context pages (no oversubscription). A smaller explicit "
        "budget oversubscribes: admission backpressures on the typed "
        "PagePoolExhausted instead of OOMing; integer >= 0 "
        "(serving/generate.py)"),
    "MXNET_GENERATE_STREAM_FLUSH": (
        "8", "honored",
        "decode steps between stream_fn token flushes: generated "
        "tokens buffer per request and flush to the streaming "
        "callback every N steps (and at finish); integer >= 1 "
        "(serving/broker.py GenerateServer)"),
    # --- shared-prefix KV cache + speculative decoding (ISSUE 16) ---
    "MXNET_GENERATE_PREFIX_CACHE": (
        "0", "honored",
        "enable the shared-prefix KV cache: a radix index over full "
        "KV pages keyed by token-id page runs — admission matches the "
        "longest cached prefix, shares those pages copy-on-write via "
        "per-page refcounts and prefills only the uncovered tail; off "
        "(the default) is bit-identical to the unshared path; "
        "0/1/true/false (serving/broker.py GenerateServer)"),
    "MXNET_GENERATE_PREFIX_EVICT": (
        "0", "honored",
        "max KV pages the prefix index may pin; crossing the bound "
        "evicts least-recently-matched entries, and pool pressure "
        "evicts regardless (sharing never causes a PagePoolExhausted "
        "a no-sharing run would avoid); 0 = bounded only by pool "
        "pressure; integer >= 0 (serving/broker.py GenerateServer)"),
    "MXNET_GENERATE_SPEC_K": (
        "0", "honored",
        "speculative-decoding depth: the draft model proposes k "
        "tokens per slot per round and ONE batched verify step of the "
        "target model accepts the longest agreeing prefix (greedy "
        "token-for-token parity with non-speculative decode); 0 "
        "disables; integer >= 0 (serving/broker.py GenerateServer)"),
    "MXNET_GENERATE_DRAFT": (
        "0", "honored",
        "self-draft layer count for speculative decoding: the draft "
        "model is the target's FIRST N transformer layers sharing "
        "embed/pos/final-LN (models/transformer.py draft_from_layers); "
        "0 means an explicit draft_config=/draft_params= must be "
        "passed when MXNET_GENERATE_SPEC_K > 0; integer >= 0 "
        "(serving/broker.py GenerateServer)"),
    # --- sharded embeddings (ISSUE 14) ---
    "MXNET_EMBED_SHARDS": (
        "0", "honored",
        "row-shard count override for ShardedEmbeddingTable; 0 (the "
        "default) shards one-per-server, shard s lives on server "
        "s %% num_servers otherwise; integer >= 0 "
        "(embedding/table.py)"),
    "MXNET_EMBED_DEDUP": (
        "1", "honored",
        "deduplicate requested row ids before pulling (one row_pull "
        "frame per shard); 0 falls back to the naive per-id pull "
        "baseline the bench compares against; 0|1, anything else "
        "raises (embedding/table.py)"),
    "MXNET_EMBED_PULL_BATCH": (
        "65536", "honored",
        "pull batch budget: max rows per row_pull RPC frame — larger "
        "requests split into multiple frames per shard; integer >= 1 "
        "(embedding/table.py)"),
    "MXNET_EMBED_WIRE": (
        "raw", "honored",
        "row-gradient wire treatment for embedding scatter pushes: "
        "'raw' or '2bit' (the PR 4 packed two-bit quantizer applied "
        "to the pushed row block, with per-row error-feedback "
        "residuals held client-side for the rows this worker touched) "
        "(embedding/table.py)"),
    "MXNET_EMBED_WIRE_THRESHOLD": (
        "0.5", "honored",
        "ternary threshold for MXNET_EMBED_WIRE=2bit; finite float "
        "> 0 (embedding/table.py)"),
    # --- sharded data input (ISSUE 17) ---
    "MXNET_DATA_SHARDS": (
        "8", "honored",
        "default shard count for write_record_shards (capped at the "
        "record count so no shard is empty); integer >= 1 "
        "(data/writer.py)"),
    "MXNET_DATA_WORKERS": (
        "0", "honored",
        "background decode/augment process-pool size for "
        "ShardedRecordStream; 0 decodes inline on the reading thread; "
        "integer >= 0 (data/service.py)"),
    "MXNET_DATA_PREFETCH": (
        "2", "honored",
        "prefetch-queue depth (read/decode chunks buffered ahead of "
        "the training thread); 0 = fully synchronous reads, the bench "
        "baseline; integer >= 0 (data/service.py)"),
    "MXNET_DATA_DETERMINISTIC": (
        "1", "honored",
        "seed record decode/augment from (epoch, shard, record-index) "
        "so elastic shard rebalancing replays byte-identical batches; "
        "0 salts seeds with worker identity; 0|1, anything else "
        "raises (data/service.py)"),
    "MXNET_DATA_LEASE_TTL": (
        "30", "honored",
        "shard-lease time-to-live in seconds: a lease not renewed "
        "(cursor committed) within the TTL returns to the pool for "
        "rebalancing; finite float > 0 (tracker.py lease books, "
        "data/service.py local authority)"),
    # --- fleet autoscaling + multi-tenant QoS (ISSUE 18) ---
    "MXNET_FLEET_AUTOSCALE_INTERVAL": (
        "1.0", "honored",
        "autoscaler control-tick period in seconds; finite float > 0 "
        "(serving/autoscale.py)"),
    "MXNET_FLEET_AUTOSCALE_MIN": (
        "1", "honored",
        "floor on the fleet's desired replica count (scale-down never "
        "goes below it); integer >= 1, must be <= _MAX "
        "(serving/autoscale.py)"),
    "MXNET_FLEET_AUTOSCALE_MAX": (
        "4", "honored",
        "ceiling on the fleet's desired replica count; integer >= 1 "
        "(serving/autoscale.py)"),
    "MXNET_FLEET_AUTOSCALE_UP_LOAD": (
        "4.0", "honored",
        "mean queued+in-flight per serving replica at/above which a "
        "tick votes scale-up; finite float > 0 (serving/autoscale.py)"),
    "MXNET_FLEET_AUTOSCALE_DOWN_LOAD": (
        "0.5", "honored",
        "mean queued+in-flight per serving replica at/below which a "
        "tick votes scale-down; float >= 0, must be < _UP_LOAD — the "
        "gap between them is the anti-flap dead band "
        "(serving/autoscale.py)"),
    "MXNET_FLEET_AUTOSCALE_HYSTERESIS": (
        "3", "honored",
        "consecutive agreeing ticks required before a scale decision "
        "acts (flap guard); integer >= 1 (serving/autoscale.py)"),
    "MXNET_FLEET_AUTOSCALE_COOLDOWN": (
        "5.0", "honored",
        "seconds after a scale action during which further actions "
        "are held (counted as holds_cooldown); float >= 0 "
        "(serving/autoscale.py)"),
    "MXNET_FLEET_AUTOSCALE_SLO_MS": (
        "0", "honored",
        "serving p99 SLO in milliseconds: any serving replica at/"
        "above it makes the tick vote scale-up regardless of queue "
        "depth; 0 disables the latency signal; float >= 0 "
        "(serving/autoscale.py)"),
    "MXNET_QOS_TENANTS": (
        "", "honored",
        "per-tenant QoS spec 'name[:k=v,...];...' with keys prio|"
        "priority (latency|normal|bulk), req_rate (requests/s > 0), "
        "tok_rate (rows/s > 0); empty disables QoS; malformed raises "
        "naming this knob (serving/qos.py)"),
    "MXNET_QOS_DEFAULT_PRIORITY": (
        "normal", "honored",
        "priority class for requests with no tenant label or an "
        "unconfigured tenant: latency|normal|bulk (serving/qos.py)"),
    "MXNET_QOS_BURST_SECONDS": (
        "1.0", "honored",
        "token-bucket burst window: a tenant may burst rate*burst "
        "units above its steady rate; finite float > 0 "
        "(serving/qos.py)"),
    # --- tensor-parallel execution (ISSUE 20) ---
    "MXNET_MP_SIZE": (
        "1", "honored",
        "tensor-parallel ('mp') mesh-axis size for the fused SPMD step "
        "and the sharded serving group: the visible devices split into "
        "a (dp = N // mp) x mp mesh, so mp must divide the device "
        "count; 1 (the default) is bit-identical to the pure "
        "data-parallel path; integer >= 1 (parallel/mesh.py "
        "train_mesh, module/spmd_group.py, serving/predictor.py)"),
    "MXNET_MP_RULES": (
        "", "honored",
        "extra parameter-sharding rules 'regex:spec;regex:spec' where "
        "spec is a comma list with one entry per dim, each '*' "
        "(replicate that dim) or a mesh-axis name — e.g. "
        "'.*proj_weight:*,mp' column-shards the last dim over mp. "
        "Applied AFTER the transformer's built-in megatron rules; a "
        "matched rule that names a missing axis or does not divide "
        "the dim raises (no silent replication); malformed grammar "
        "raises naming this knob (parallel/spmd.py parse_rules, "
        "module/spmd_group.py)"),
    # --- misc ---
    "MXNET_TPU_NO_NATIVE": (
        "0", "honored", "force pure-Python fallbacks (_native.py)"),
    "MXNET_STORAGE_FALLBACK_LOG_VERBOSE": (
        "1", "accepted", "log dense fallbacks of sparse ops"),
}


def get(name, default=None):
    """Raw string value of a knob (env wins; then registry default)."""
    if name in os.environ:
        return os.environ[name]
    if default is not None:
        return default
    if name in KNOBS:
        return KNOBS[name][0]
    return None


def get_int(name, default=None):
    v = get(name, None if default is None else str(default))
    return int(v) if v not in (None, "") else None


def get_bool(name, default=False):
    v = get(name, "1" if default else "0")
    return str(v).strip().lower() in ("1", "true", "yes", "on")


# --- strict typed accessors (PR 6 convention: a malformed knob is a
# job misconfiguration — fail loudly at the read site, never train with
# a silently-substituted default) ------------------------------------
def get_strict_bool(name):
    """0/1/true/false/yes/no/on/off; anything else raises MXNetError."""
    from .base import MXNetError

    v = str(get(name)).strip().lower()
    if v in ("1", "true", "yes", "on"):
        return True
    if v in ("0", "false", "no", "off"):
        return False
    raise MXNetError("%s=%r must be a boolean (0|1)" % (name, get(name)))


def get_choice(name, choices):
    from .base import MXNetError

    v = str(get(name)).strip().lower()
    if v not in choices:
        raise MXNetError("%s=%r must be one of %s"
                         % (name, get(name), "|".join(choices)))
    return v


def get_nonneg_int(name):
    from .base import MXNetError

    raw = get(name)
    try:
        v = int(str(raw).strip())
    except (TypeError, ValueError):
        v = -1
    if v < 0:
        raise MXNetError("%s=%r must be an integer >= 0" % (name, raw))
    return v


def get_positive_int(name):
    from .base import MXNetError

    raw = get(name)
    try:
        v = int(str(raw).strip())
    except (TypeError, ValueError):
        v = 0
    if v < 1:
        raise MXNetError("%s=%r must be an integer >= 1" % (name, raw))
    return v


def get_nonneg_float(name):
    from .base import MXNetError

    raw = get(name)
    try:
        v = float(str(raw).strip())
    except (TypeError, ValueError):
        v = float("nan")
    if not 0.0 <= v < float("inf"):  # also rejects NaN
        raise MXNetError("%s=%r must be a finite float >= 0" % (name, raw))
    return v


def get_positive_float(name):
    from .base import MXNetError

    raw = get(name)
    try:
        v = float(str(raw).strip())
    except (TypeError, ValueError):
        v = float("nan")
    if not 0.0 < v < float("inf"):  # also rejects NaN
        raise MXNetError("%s=%r must be a finite float > 0" % (name, raw))
    return v


def describe():
    """[(name, current_value, status, description)] for every knob."""
    return [(n, get(n), s, d) for n, (_, s, d) in sorted(KNOBS.items())]


def print_summary():
    for name, value, status, desc in describe():
        print("%-40s %-10s %-8s %s" % (name, value, status, desc))
