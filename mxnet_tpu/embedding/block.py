"""SparseEmbedding — the Gluon block over a server-sharded table.

Unlike :class:`gluon.nn.Embedding`, the weight table is NOT a
Parameter: it lives row-sharded on the dist_async KVStoreServers and
only the rows a batch actually touches ever reach this process. Each
``forward``:

1. deduplicates the batch's ids and pulls exactly those rows
   (``ShardedEmbeddingTable.pull``);
2. wraps the pulled ``(n_unique, dim)`` block as an autograd-marked
   variable, so ``backward`` accumulates the batch's row gradients
   into a block-local buffer (XLA's gather VJP does the in-batch
   scatter-add for repeated ids);
3. runs the stock ``Embedding`` gather against the remapped
   (``inverse``) ids.

After ``loss.backward()``, :meth:`step` pushes the accumulated row
gradients back as async scatter pushes — the server-side optimizer
applies its lazy row-sparse update on arrival (dist_async semantics:
no global synchronization, pulls return the freshest rows).

::

    kv = mx.kv.create("dist_async")
    kv.set_optimizer("sgd", learning_rate=0.05,
                     rescale_grad=1.0 / batch_size)
    emb = SparseEmbedding(64, input_dim=1 << 20, kvstore=kv,
                          key="user_emb")
    with autograd.record():
        vec = emb(user_ids)              # pull + gather
        loss = ...
    loss.backward()
    emb.step()                           # async scatter push
"""
from __future__ import annotations

import numpy as np

from .. import autograd
from ..base import MXNetError
from ..gluon.block import Block
from ..ndarray import ndarray as nd
from .table import EmbeddingShardError, ShardedEmbeddingTable

__all__ = ["SparseEmbedding"]


class SparseEmbedding(Block):
    """Gluon block whose embedding table is server-sharded.

    ``kvstore`` may be handed to the constructor or later via
    :meth:`bind_kvstore` (the table binds lazily on first use, so the
    block can be built before the dist topology exists). ``key``
    names the table on the servers; it defaults to the block's gluon
    name, but every worker must agree on it — pass it explicitly in
    multi-worker jobs (gluon auto-naming counts per process).
    """

    def __init__(self, output_dim, input_dim, kvstore=None, key=None,
                 dtype="float32", table_kwargs=None, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_dim = int(input_dim)
        self._output_dim = int(output_dim)
        self._dtype = dtype
        self._table_kwargs = dict(table_kwargs or {})
        self._key = key
        self._kv = None
        self._table = None
        self._pending = []  # [(unique_ids, grad NDArray buffer), ...]
        if kvstore is not None:
            self.bind_kvstore(kvstore)

    def bind_kvstore(self, kvstore):
        """Attach the dist_async kvstore this block's table lives on.
        Rebinding to a different store mid-training is a topology
        error and raises."""
        if self._kv is not None and self._kv is not kvstore:
            raise MXNetError(
                "SparseEmbedding %r is already bound to a kvstore"
                % self.name)
        self._kv = kvstore
        if self._table is None:
            self._table = ShardedEmbeddingTable(
                self._key or self.name, kvstore, rows=self._input_dim,
                dim=self._output_dim, dtype=self._dtype,
                **self._table_kwargs)
        return self

    @property
    def table(self):
        if self._table is None:
            raise MXNetError(
                "SparseEmbedding %r has no kvstore bound — pass "
                "kvstore= or call bind_kvstore() first" % self.name)
        return self._table

    def initialize_table(self, init_array=None, scale=None, seed=0):
        """Install the table on the servers (first-writer-wins; safe
        to call from every worker)."""
        self.table.init(init_array=init_array, scale=scale, seed=seed)
        return self

    # -- forward / backward --------------------------------------------------
    def forward(self, x):
        table = self.table
        ids_np = x.asnumpy() if hasattr(x, "asnumpy") else np.asarray(x)
        uniq, inverse, rows = table.pull(ids_np)
        if uniq.size == 0:
            raise EmbeddingShardError(
                "SparseEmbedding %r: empty id batch" % self.name)
        weight = nd.array(rows)
        if autograd.is_recording():
            grad = nd.zeros(weight.shape, dtype=rows.dtype)
            autograd.mark_variables([weight], [grad])
            self._pending.append((uniq, grad))
        inv = nd.array(inverse.reshape(np.asarray(ids_np).shape)
                       .astype(np.int32))
        return nd.invoke(
            "Embedding", [inv, weight],
            {"input_dim": int(uniq.size),
             "output_dim": self._output_dim})

    def step(self, priority=0):
        """Push every recorded forward's accumulated row gradients to
        the servers (async; the next pull of those rows waits on the
        frames). Returns the number of pushed row-gradient blocks.
        Gradient scaling is the server optimizer's ``rescale_grad`` —
        configure it like any dist_async job."""
        pending, self._pending = self._pending, []
        for uniq, grad in pending:
            self.table.push(uniq, grad.asnumpy(), priority=priority)
        return len(pending)

    def discard_grads(self):
        """Drop recorded forwards without pushing (eval passes that
        ran under record, aborted steps)."""
        self._pending = []

    def __repr__(self):
        return ("SparseEmbedding(%d -> %d, key=%r, shards=%s)"
                % (self._input_dim, self._output_dim,
                   self._key or self.name,
                   self._table.num_shards if self._table else "?"))
