"""Server-sharded embedding tables (ISSUE 14 tentpole).

:class:`ShardedEmbeddingTable` is the client handle on one embedding
table whose rows live as dense sub-tables across the dist_async
KVStoreServers (PR 2 topology, the PR 7 value-sharded precedent for
client-side routing): row shard ``s`` (``sharding.RowSharding``) is the
dense key ``<key>@embshard<s>`` on server ``s % num_servers``. Reads
pull DEDUPLICATED row ids in one ``row_pull`` frame per shard (budgeted
by ``MXNET_EMBED_PULL_BATCH``); writes push row-granular gradient
scatters on the PR 4 async sender pipeline (priority-ordered, coalesced,
seqno-deduped under retry), optionally 2-bit-compressed with per-row
error-feedback residuals. Per-server memory — sub-table plus the dense
optimizer state shadowing it — is ``~1/num_servers`` by construction
(measured via ``ServerKVStore.server_memory`` / memoryStats).

Out-of-vocabulary ids raise the typed :class:`EmbeddingShardError` at
the CLIENT, before any routing (the PR 12 out-of-vocab lesson: a clamp
or a server-side-only error silently trains/serves the wrong rows).
"""
from __future__ import annotations

import time

import numpy as np

from .. import config
from .. import profiler
from ..base import MXNetError
from ..kvstore import two_bit_quantize
from ..kvstore_server import ServerKVStore, embedding_sub_key
from .sharding import RowSharding

__all__ = ["EmbeddingShardError", "ShardedEmbeddingTable"]


class EmbeddingShardError(MXNetError):
    """Typed embedding-table failure: out-of-vocabulary row ids or a
    sharding/topology misconfiguration. Raised client-side so the
    caller that produced the bad ids sees it — never a silent clamp,
    never a server-side-only error."""


def _knob_shards(num_servers, override):
    if override is not None:
        n = int(override)
    else:
        n = config.get_nonneg_int("MXNET_EMBED_SHARDS")
    if n == 0:
        n = int(num_servers)
    if n < 1:
        raise EmbeddingShardError(
            "ShardedEmbeddingTable: shard count must be >= 1, got %d"
            % n)
    return n


class ShardedEmbeddingTable:
    """Client handle on one server-sharded embedding table.

    ::

        kv = mx.kv.create("dist_async")          # ServerKVStore
        kv.set_optimizer("sgd", learning_rate=0.05)
        table = ShardedEmbeddingTable("user_emb", kv, rows=1 << 20,
                                      dim=64)
        table.init()                             # first-writer-wins
        uniq, inverse, vecs = table.pull(ids)    # dedup pull
        table.push(uniq, row_grads)              # async scatter push

    ``dedup=False`` (or ``MXNET_EMBED_DEDUP=0``) switches pulls to the
    naive one-RPC-per-id baseline the bench variant compares against.
    """

    def __init__(self, key, kvstore, rows, dim, dtype="float32",
                 num_shards=None, dedup=None, pull_batch=None,
                 wire=None, threshold=None):
        if not isinstance(kvstore, ServerKVStore):
            raise EmbeddingShardError(
                "ShardedEmbeddingTable needs the dist_async server "
                "tier (ServerKVStore), got %r — launch with "
                "tools/launch.py -s >= 1" % type(kvstore).__name__)
        self.key = str(key)
        self._kv = kvstore
        self.rows = int(rows)
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        if self.dim < 1:
            raise EmbeddingShardError(
                "ShardedEmbeddingTable %r: dim must be >= 1, got %d"
                % (self.key, self.dim))
        self.sharding = RowSharding(
            self.rows, _knob_shards(kvstore.num_servers, num_shards))
        # strict knob reads happen unconditionally (a typo'd knob is a
        # job misconfiguration, not a silent default) even when the
        # ctor argument overrides them
        env_dedup = config.get_strict_bool("MXNET_EMBED_DEDUP")
        self.dedup = env_dedup if dedup is None else bool(dedup)
        env_batch = config.get_positive_int("MXNET_EMBED_PULL_BATCH")
        self.pull_batch = env_batch if pull_batch is None \
            else int(pull_batch)
        if self.pull_batch < 1:
            raise EmbeddingShardError(
                "ShardedEmbeddingTable %r: pull_batch must be >= 1, "
                "got %d" % (self.key, self.pull_batch))
        env_wire = config.get_choice("MXNET_EMBED_WIRE", ("raw", "2bit"))
        self.wire = env_wire if wire is None else str(wire)
        if self.wire not in ("raw", "2bit"):
            raise EmbeddingShardError(
                "ShardedEmbeddingTable %r: wire must be raw|2bit, got "
                "%r" % (self.key, self.wire))
        env_thr = config.get_positive_float("MXNET_EMBED_WIRE_THRESHOLD")
        self.threshold = env_thr if threshold is None \
            else float(threshold)
        self._residuals = {}  # global row id -> error-feedback vector
        self._sub_keys = self.sharding.sub_keys(self.key)
        self._pull_pool = None  # lazy per-shard fetch pool

    def _pool(self):
        if self._pull_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pull_pool = ThreadPoolExecutor(
                max_workers=min(self.num_shards, 8),
                thread_name_prefix="embed-pull-%s" % self.key)
        return self._pull_pool

    # -- topology ------------------------------------------------------------
    @property
    def num_shards(self):
        return self.sharding.num_shards

    def server_of(self, shard):
        """The kvstore server rank hosting row shard ``shard`` (the
        suffix routing rule, shared with a respawned server's
        ``restore_from_checkpoint``)."""
        return int(shard) % self._kv.num_servers

    # -- init ----------------------------------------------------------------
    def init(self, init_array=None, scale=None, seed=0):
        """Install the sub-tables on their servers (first-writer-wins,
        like every kvstore init — a respawned or late-joining worker's
        init never overwrites trained/restored rows). ``init_array``
        (rows, dim) scatters an explicit table (tests, warm starts);
        otherwise each sub-table fills uniform(-scale, scale) from a
        deterministic per-shard seed, so every worker offers identical
        bytes and the first-writer race is invisible."""
        if scale is None:
            scale = 1.0 / np.sqrt(self.dim)
        if init_array is not None:
            init_array = np.asarray(init_array, self.dtype)
            if init_array.shape != (self.rows, self.dim):
                raise EmbeddingShardError(
                    "init_array shape %s != (%d, %d)"
                    % (init_array.shape, self.rows, self.dim))
        for s in range(self.num_shards):
            n = self.sharding.shard_rows(s)
            if init_array is not None:
                sub = init_array[self.sharding.global_ids(s)]
            else:
                rng = np.random.RandomState(
                    (int(seed) * 1000003 + s) % (1 << 31))
                sub = rng.uniform(-scale, scale,
                                  (n, self.dim)).astype(self.dtype)
            self._kv._rpc_idx(self.server_of(s), "init",
                              self._sub_keys[s], None,
                              _arr_to_wire_np(sub))

    # -- validation ----------------------------------------------------------
    def _check_ids(self, ids, what):
        ids = np.ascontiguousarray(np.asarray(ids)).reshape(-1)
        if ids.size == 0:
            return ids.astype(np.int64)
        if not np.issubdtype(ids.dtype, np.number):
            raise EmbeddingShardError(
                "%s %r: row ids must be numeric, got dtype %s"
                % (what, self.key, ids.dtype))
        ids64 = ids.astype(np.int64)
        if np.issubdtype(ids.dtype, np.floating) \
                and not np.array_equal(ids64, ids):
            raise EmbeddingShardError(
                "%s %r: non-integral row ids" % (what, self.key))
        lo, hi = int(ids64.min()), int(ids64.max())
        if lo < 0 or hi >= self.rows:
            profiler.embedding_record(oov_errors=1)
            raise EmbeddingShardError(
                "%s %r: row ids out of vocabulary: [%d, %d] vs %d "
                "rows (ids are validated at the client — fix the id "
                "producer; the table never clamps)"
                % (what, self.key, lo, hi, self.rows))
        return ids64

    # -- read path -----------------------------------------------------------
    def pull(self, ids):
        """Rows for (possibly repeated) global ids. Returns
        ``(unique_ids, inverse, vectors)`` with
        ``vectors[inverse].reshape(ids.shape + (dim,))`` the per-id
        lookup; with dedup off (the naive baseline) ``unique_ids`` is
        the flattened request itself and ``inverse`` the identity."""
        t0 = time.perf_counter()
        flat = self._check_ids(ids, "pull")
        if self.dedup:
            uniq, inverse = np.unique(flat, return_inverse=True)
        else:
            uniq, inverse = flat, np.arange(flat.size, dtype=np.int64)
        vecs = np.empty((uniq.size, self.dim), self.dtype)
        nbytes = {}
        if uniq.size:
            if self.dedup:
                groups = self.sharding.group(uniq)

                def _fetch(s, sel, loc):
                    srv = self.server_of(s)
                    moved = 0
                    for ofs in range(0, loc.size, self.pull_batch):
                        block = self._kv.row_pull(
                            srv, self._sub_keys[s],
                            loc[ofs:ofs + self.pull_batch])
                        # disjoint slices of vecs: safe to fill
                        # concurrently
                        vecs[sel[ofs:ofs + self.pull_batch]] = block
                        moved += int(block.nbytes)
                    return s, moved

                if len(groups) > 1:
                    # the per-shard frames are independent RPCs to
                    # DIFFERENT sockets: fetch them concurrently so
                    # read latency stays ~1 RTT instead of scaling
                    # linearly with server count (the read-side mirror
                    # of the push path's per-shard sender threads)
                    for s, moved in self._pool().map(
                            lambda g: _fetch(*g), groups):
                        nbytes[s] = nbytes.get(s, 0) + moved
                else:
                    for g in groups:
                        s, moved = _fetch(*g)
                        nbytes[s] = nbytes.get(s, 0) + moved
            else:
                # the naive per-id baseline MXNET_EMBED_DEDUP=0 exists
                # to measure against: one RPC per requested id
                shards, locals_ = self.sharding.shard_and_local(uniq)
                for i in range(uniq.size):
                    s = int(shards[i])
                    block = self._kv.row_pull(
                        self.server_of(s), self._sub_keys[s],
                        locals_[i:i + 1])
                    vecs[i] = block[0]
                    nbytes[s] = nbytes.get(s, 0) + int(block.nbytes)
        profiler.embedding_record(
            pulls=1, ids_requested=int(flat.size),
            unique_ids=int(uniq.size), rows_pulled=int(uniq.size),
            pull_seconds=time.perf_counter() - t0, shard_bytes=nbytes,
            pull_latencies=[time.perf_counter() - t0])
        return uniq, inverse, vecs

    def lookup(self, ids):
        """Per-id vectors in request shape + (dim,) — the serving-path
        convenience over :meth:`pull`."""
        ids_arr = np.asarray(ids)
        uniq, inverse, vecs = self.pull(ids_arr)
        return vecs[inverse].reshape(tuple(ids_arr.shape) + (self.dim,))

    # -- write path ----------------------------------------------------------
    def push(self, ids, grads, priority=0):
        """Push per-row gradients for global ids (duplicates combine
        client-side by summation — the scatter-add the server would
        otherwise repeat) as async row scatters, one frame per touched
        shard, on the kvstore sender pipeline. With ``wire='2bit'``
        the row block quantizes through the PR 4 packed two-bit
        quantizer, with a per-row error-feedback residual held here
        (memory grows with the rows THIS worker touches — the table's
        working set, not its vocabulary)."""
        t0 = time.perf_counter()
        flat = self._check_ids(ids, "push")
        grads = np.ascontiguousarray(
            np.asarray(grads, self.dtype)).reshape(flat.size, self.dim)
        if flat.size == 0:
            return
        uniq, inverse = np.unique(flat, return_inverse=True)
        if uniq.size != flat.size:
            agg = np.zeros((uniq.size, self.dim), self.dtype)
            np.add.at(agg, inverse, grads)
            grads = agg
        nbytes = {}
        for s, sel, loc in self.sharding.group(uniq):
            block = grads[sel]
            compressed = None
            if self.wire == "2bit":
                block, compressed = self._compress_rows(uniq[sel], block)
            else:
                # the fancy-index slice above is already a private
                # copy this table owns: mark it read-only so row_push
                # skips its defensive pipeline snapshot (one copy, not
                # two, per pushed shard block)
                block.flags.writeable = False
            self._kv.row_push(self.server_of(s), self._sub_keys[s],
                              loc, block, priority=priority,
                              compressed=compressed)
            nbytes[s] = nbytes.get(s, 0) + int(
                compressed[0].nbytes if compressed else block.nbytes)
        profiler.embedding_record(
            pushes=1, rows_pushed=int(uniq.size),
            push_seconds=time.perf_counter() - t0, shard_bytes=nbytes,
            push_latencies=[time.perf_counter() - t0])

    def _compress_rows(self, global_ids, block):
        """2-bit wire treatment of one shard's row block: per-row
        error-feedback residuals keyed by GLOBAL id (rows migrate
        between push rounds' shard groupings only if the topology
        changes, which resets the table anyway)."""
        res = np.zeros_like(block)
        for i, gid in enumerate(global_ids):
            r = self._residuals.get(int(gid))
            if r is not None:
                res[i] = r
        packed, new_res = two_bit_quantize(block, res, self.threshold)
        for i, gid in enumerate(global_ids):
            self._residuals[int(gid)] = new_res[i]
        return block, (packed, self.threshold)

    def reset_residuals(self):
        """Drop the 2-bit error-feedback residuals (the rollback rule:
        accumulated error refers to pre-rollback weights)."""
        self._residuals = {}

    # -- checkpoint / introspection -----------------------------------------
    def snapshot(self):
        """{sub_key: full sub-table numpy array} — the quiesced rank-0
        read of the checkpoint choreography (each sub-key is a plain
        dense key; the pull drains this client's pipeline first)."""
        self._kv.wait_outstanding()
        out = {}
        for s in range(self.num_shards):
            k = self._sub_keys[s]
            wire = self._kv._rpc_idx(self.server_of(s), "pull", k)
            from ..kvstore_server import _arr_from_wire

            out[k] = np.asarray(_arr_from_wire(wire))
        return out

    def as_dense(self):
        """The full logical (rows, dim) table reassembled from the
        shard snapshots — tests and small-table exports only."""
        snap = self.snapshot()
        dense = np.empty((self.rows, self.dim), self.dtype)
        for s in range(self.num_shards):
            dense[self.sharding.global_ids(s)] = snap[self._sub_keys[s]]
        return dense

    def server_memory(self):
        """Per-server measured table+optimizer bytes (rank order)."""
        return self._kv.server_memory()


def _arr_to_wire_np(a):
    from ..kvstore_server import _arr_to_wire

    return _arr_to_wire(np.ascontiguousarray(a))
