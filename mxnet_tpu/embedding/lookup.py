"""Embedding lookup serving (ISSUE 14): sharded lookup + dense tower.

The inference half of the sharded-embedding workload, and the next
tenant of the serving fleet (PR 11): requests carry raw id arrays, the
:class:`EmbeddingTowerPredictor` pulls the deduplicated rows from the
kvstore-sharded table and feeds the gathered feature block through a
dense-tower :class:`~mxnet_tpu.serving.AOTPredictor` (the nncase
heterogeneous-placement split — the memory-bound gather stays on the
value servers, the compute-dense tower runs through the AOT serving
path). :class:`EmbeddingLookupServer` hosts it behind the standard
dynamic-batching :class:`ModelServer` and registers with the tracker
under the fleet's slot-free ``replica`` role, so :class:`FleetRouter`
discovers, load-balances, drains and fails over lookup replicas
exactly like any other serving replica.
"""
from __future__ import annotations

import numpy as np

from ..serving.broker import ModelServer
from ..serving.fleet import ReplicaServer
from ..serving.predictor import ServingError
from .table import ShardedEmbeddingTable

__all__ = ["EmbeddingTowerPredictor", "EmbeddingLookupServer"]


class EmbeddingTowerPredictor:
    """AOTPredictor-shaped adapter: id inputs -> sharded row pulls ->
    dense tower.

    ``tables`` is an ordered ``{input_name: ShardedEmbeddingTable}``
    mapping — requests carry one int id array per table, the looked-up
    vectors concatenate feature-wise (two-tower/MF serving shape) and
    feed the tower's single data input. Implements the predictor duck
    interface the broker's :class:`_ModelWorker` batches against
    (ladder / pick_bucket / data_names / _normalize / run_bucket /
    swap_params), so dynamic batching, padding, the executable LRU and
    hot swap all apply unchanged. Batch padding rows carry id 0 — a
    real row, pulled and discarded with the pad slice (never an OOV
    probe)."""

    def __init__(self, tables, tower):
        if not tables or not isinstance(tables, dict):
            raise ServingError(
                "EmbeddingTowerPredictor: tables must be a non-empty "
                "{input_name: ShardedEmbeddingTable} dict")
        for name, t in tables.items():
            if not isinstance(t, ShardedEmbeddingTable):
                raise ServingError(
                    "EmbeddingTowerPredictor: table %r is %r, not a "
                    "ShardedEmbeddingTable" % (name, type(t).__name__))
        self._tables = dict(tables)
        self._names = list(tables)
        self._tower = tower
        if len(tower.data_names) != 1:
            raise ServingError(
                "EmbeddingTowerPredictor: the dense tower must take "
                "ONE feature input, has %s" % tower.data_names)
        self._tower_input = tower.data_names[0]
        feat = sum(t.dim for t in self._tables.values())
        want = tower._data_shapes[self._tower_input]
        if len(want) != 2 or int(want[1]) != feat:
            raise ServingError(
                "EmbeddingTowerPredictor: tower input %r expects "
                "shape (n, %s) but the tables concatenate to %d "
                "features" % (self._tower_input, want[1:], feat))

    # -- predictor duck interface (broker.py _ModelWorker) -------------------
    @property
    def ladder(self):
        return self._tower.ladder

    @property
    def max_bucket(self):
        return self._tower.max_bucket

    @property
    def data_names(self):
        return list(self._names)

    @property
    def output_names(self):
        return self._tower.output_names

    def pick_bucket(self, rows):
        return self._tower.pick_bucket(rows)

    def _normalize(self, inputs):
        if not isinstance(inputs, dict):
            if len(self._names) != 1:
                raise ServingError(
                    "lookup model has id inputs %s: pass a "
                    "{name: id array} dict" % self._names)
            inputs = {self._names[0]: inputs}
        unknown = sorted(set(inputs) - set(self._names))
        missing = sorted(set(self._names) - set(inputs))
        if unknown or missing:
            raise ServingError(
                "bad request inputs: unknown %s, missing %s (id "
                "inputs: %s)" % (unknown, missing, self._names))
        out, rows = {}, None
        for name in self._names:
            v = np.asarray(inputs[name])
            if hasattr(inputs[name], "asnumpy"):
                v = inputs[name].asnumpy()
            # accept 1-D ids or a column/row vector of them: flatten
            # when at most one axis is non-unit. np.squeeze would
            # collapse a batch-of-one column vector (1, 1) to 0-d and
            # reject the same format that works at batch >= 2.
            if sum(1 for d in v.shape if d != 1) <= 1 and v.size:
                v = v.reshape(-1)
            if v.ndim != 1:
                raise ServingError(
                    "id input %r must be a 1-D id array, got shape %s"
                    % (name, tuple(np.asarray(inputs[name]).shape)))
            table = self._tables[name]
            # typed validation in the SUBMITTING thread (the satellite
            # contract): an out-of-vocab id fails the caller before
            # the request ever occupies queue space
            v = table._check_ids(v, "lookup")
            if rows is None:
                rows = int(v.shape[0])
            elif int(v.shape[0]) != rows:
                raise ServingError(
                    "id inputs disagree on the batch dim (%d vs %d)"
                    % (rows, int(v.shape[0])))
            out[name] = v
        if rows is None or rows < 1:
            raise ServingError("lookup request needs >= 1 id")
        return out, rows

    def run_bucket(self, inputs, bucket):
        feats = np.concatenate(
            [self._tables[n].lookup(inputs[n]) for n in self._names],
            axis=1)
        return self._tower.run_bucket({self._tower_input: feats}, bucket)

    def predict(self, inputs):
        """Synchronous single-request path (pads to the nearest
        bucket like AOTPredictor.predict)."""
        inputs, rows = self._normalize(inputs)
        bucket = self.pick_bucket(rows)
        if rows != bucket:
            inputs = {n: np.concatenate(
                [v, np.zeros((bucket - rows,), v.dtype)])
                for n, v in inputs.items()}
        outs = self.run_bucket(inputs, bucket)
        return [o[:rows] if o.ndim and o.shape[0] == bucket else o
                for o in outs]

    def swap_params(self, arg_params=None, aux_params=None,
                    allow_extra=False):
        """Hot-swap the TOWER weights (embedding rows update live
        through the training push path — there is nothing to swap
        table-side)."""
        return self._tower.swap_params(arg_params, aux_params,
                                       allow_extra=allow_extra)


class EmbeddingLookupServer:
    """A fleet-ready lookup replica: ModelServer hosting one
    :class:`EmbeddingTowerPredictor`, fronted by a
    :class:`~mxnet_tpu.serving.fleet.ReplicaServer` (tracker-registered
    ``replica`` role when ``tracker_uri`` is given, so FleetRouter
    routes/drains/fails over it like any serving replica)."""

    def __init__(self, name, tables, tower, ladder=None,
                 tracker_uri=None, host="127.0.0.1", port=0, rank=None,
                 **server_kwargs):
        predictor = EmbeddingTowerPredictor(tables, tower)
        self._server = ModelServer(ladder=ladder or tower.ladder,
                                   **server_kwargs)
        self._server.add_model(name, predictor=predictor)
        self.name = name
        self.predictor = predictor
        self.replica = ReplicaServer(self._server,
                                     tracker_uri=tracker_uri,
                                     host=host, port=port, rank=rank)
        self.addr = self.replica.addr

    def serve_in_background(self):
        return self.replica.serve_in_background()

    def predict(self, inputs, timeout=None):
        """Local synchronous predict through the batching server."""
        return self._server.predict(self.name, inputs, timeout=timeout)

    def shutdown(self):
        self.replica.shutdown()

    def __enter__(self):
        self.serve_in_background()
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False
