"""Row sharding for server-sharded embedding tables (ISSUE 14).

A table of ``rows`` rows splits into ``num_shards`` dense sub-tables,
one per shard, with sub-key ``<key>@embshard<s>`` living on server
``s % num_servers`` (the suffix rule defined once in
``kvstore_server.embedding_shard_rank``). The assignment of ROW ->
shard is a stable multiplicative-hash permutation followed by
contiguous range splitting:

    perm(r)  = (r * A) mod rows        # A coprime with rows -> bijection
    shard(r) = the range of ``zero_slice_sizes(rows, num_shards)``
               that perm(r) falls in
    local(r) = perm(r) - range_start(shard(r))

Why this shape and not the crc32 key hash (PR 2) applied per row: the
local index must be O(1)-derivable from the global row id alone — a
hash with no inverse would force every client to hold a rows-sized
permutation table, which defeats the point of sharding tables too
large for one host. The multiplicative permutation (Knuth hashing) is
a stable hash in the sense that matters here: deterministic across
processes and incarnations (no per-interpreter salt), and it stripes
CONSECUTIVE ids across shards — under a frequency-sorted vocabulary
(zipfian head at low ids, the recommender norm) the hot head lands
uniformly on every server instead of saturating shard 0 the way
contiguous range sharding would.

Reusing ``zero_slice_sizes`` (PR 7) for the range split keeps the
per-shard size rule identical to the value-sharded slices: the first
``rows % num_shards`` shards get one extra row.
"""
from __future__ import annotations

import math

import numpy as np

from ..base import MXNetError
from ..kvstore_server import (embedding_shard_rank, embedding_sub_key,
                              zero_slice_sizes)

__all__ = ["RowSharding", "embedding_shard_rank", "embedding_sub_key"]

#: Knuth's multiplicative-hash constant (2^32 / golden ratio); the
#: actual multiplier is derived from it per table size so it is always
#: coprime with ``rows`` (a non-coprime multiplier would collapse the
#: permutation)
_KNUTH = 2654435761


def _multiplier(rows):
    """The smallest A >= (Knuth mod rows) with gcd(A, rows) == 1 —
    deterministic per table size, so every client and every restored
    server computes the identical permutation."""
    a = _KNUTH % rows
    if a < 2:
        a = min(2, rows)  # rows 1/2: identity-ish, still coprime
    while math.gcd(a, rows) != 1:
        a += 1
    return a % rows if rows > 1 else 1


class RowSharding:
    """The one row->shard/local mapping, shared by the client's
    routing, checkpoint reassembly, and tests."""

    def __init__(self, rows, num_shards):
        rows = int(rows)
        num_shards = int(num_shards)
        if rows < 1:
            raise MXNetError("RowSharding: rows must be >= 1, got %d"
                             % rows)
        if rows > np.iinfo(np.int32).max:
            raise MXNetError(
                "RowSharding: %d rows exceeds the int32 id wire format "
                "(2^31-1 rows)" % rows)
        if not 1 <= num_shards <= rows:
            raise MXNetError(
                "RowSharding: num_shards must be in [1, rows=%d], got "
                "%d" % (rows, num_shards))
        self.rows = rows
        self.num_shards = num_shards
        self.multiplier = _multiplier(rows)
        self.sizes = zero_slice_sizes(rows, num_shards)
        self._bounds = np.cumsum([0] + self.sizes).astype(np.int64)

    def perm(self, ids):
        """The stable hash permutation of global row ids (int64 in,
        int64 out; rows < 2^31 keeps the product inside int64)."""
        ids = np.asarray(ids, np.int64)
        return (ids * self.multiplier) % self.rows

    def shard_and_local(self, ids):
        """Vectorized (shard index, local row index) for global ids.
        Callers validate the id range FIRST (the table raises the
        typed EmbeddingShardError); this is pure math."""
        p = self.perm(ids)
        shards = np.searchsorted(self._bounds, p, side="right") - 1
        return shards.astype(np.int64), p - self._bounds[shards]

    def shard_rows(self, shard):
        """Row count of one shard's dense sub-table."""
        return self.sizes[int(shard)]

    def group(self, ids):
        """Group global ids by shard: ``[(shard, sel, local_ids)]``
        for every NON-EMPTY shard, where ``sel`` indexes back into
        ``ids`` and ``local_ids[i]`` is the sub-table row of
        ``ids[sel[i]]``. THE one grouping routine shared by the pull
        and push paths (they must slice identically or reads and
        writes silently diverge)."""
        shards, locals_ = self.shard_and_local(ids)
        order = np.argsort(shards, kind="stable")
        bounds = np.searchsorted(shards[order],
                                 np.arange(self.num_shards + 1))
        out = []
        for s in range(self.num_shards):
            sel = order[bounds[s]:bounds[s + 1]]
            if sel.size:
                out.append((s, sel, locals_[sel]))
        return out

    def sub_keys(self, key):
        """All sub-table keys of ``key``, in shard order."""
        return [embedding_sub_key(key, s) for s in range(self.num_shards)]

    def global_ids(self, shard):
        """The global row ids stored in ``shard``, in LOCAL order —
        the inverse mapping (O(rows/num_shards) memory; used by
        checkpoint reassembly and tests, never the hot path). Solves
        perm(r) = p for each local slot p via the modular inverse of
        the multiplier."""
        shard = int(shard)
        lo = int(self._bounds[shard])
        hi = int(self._bounds[shard + 1])
        p = np.arange(lo, hi, dtype=np.int64)
        inv = pow(self.multiplier, -1, self.rows) if self.rows > 1 else 1
        return (p * inv) % self.rows
