"""Coordinated checkpoints for sharded embedding tables (ISSUE 14).

The PR 3 elastic machinery already restores a respawned server's key
shard from the newest committed checkpoint — but it had only ever seen
dense module parameters. This helper runs the SAME three-named-barrier
choreography as ``callback.elastic_checkpoint`` over sharded tables:
each sub-table is snapshot under the quiesced window and committed as
an ordinary ``arg:<key>@embshard<s>`` weight, and the server-side
optimizer state (which includes the sub-keys automatically — they are
plain keys in each server's updater) rides the existing
``save_optimizer_states`` wire plumbing. A respawned server then
restores exactly its suffix-routed sub-keys through
``KVStoreServer.restore_from_checkpoint`` — no new restore path.
"""
from __future__ import annotations

import numpy as np

__all__ = ["elastic_table_checkpoint"]


def elastic_table_checkpoint(manager, tables, kv, state_fn=None,
                             extra_weights_fn=None):
    """``fn(epoch)`` running the coordinated checkpoint choreography
    for ``tables`` (a list of :class:`ShardedEmbeddingTable` /
    :class:`SparseEmbedding` — blocks are unwrapped) on the dist_async
    kvstore ``kv``. Call it at every epoch end from EVERY worker
    (``manager.due`` gates the period). ``extra_weights_fn() ->
    {prefixed_name: numpy}`` lets the caller fold dense params into
    the same commit."""
    rank = kv.rank

    def _default_state():
        return {"numpy_rng": np.random.get_state()}

    state_fn = state_fn or _default_state
    resolved = [getattr(t, "table", t) for t in tables]

    def _sync(epoch, phase):
        kv.barrier("embed-ckpt-%d-%s" % (epoch, phase))

    def _checkpoint(epoch):
        if not manager.due(epoch):
            return None
        if rank == 0:
            manager.begin(epoch)
        _sync(epoch, "stage")                 # A: staging dir exists
        state = dict(state_fn())
        state.setdefault("epoch", epoch)
        manager.write_worker_state(epoch, rank, state)
        _sync(epoch, "progress")              # B: all progress staged
        if rank == 0:
            # quiesced window: every other worker is parked in barrier
            # C, and snapshot()/save_optimizer_states drain this
            # client's own pipeline — no push lands between the
            # sub-table reads and the commit
            weights = {}
            for t in resolved:
                for sub_key, arr in t.snapshot().items():
                    weights["arg:%s" % sub_key] = arr
            if extra_weights_fn is not None:
                weights.update(extra_weights_fn())
            kv.save_optimizer_states(
                manager.staged_optimizer_states_path(epoch))
            manager.commit(epoch, weights=weights,
                           optimizer_config=kv.get_optimizer_config(),
                           num_workers=kv.num_workers)
        _sync(epoch, "commit")                # C: commit visible
        return epoch

    return _checkpoint
