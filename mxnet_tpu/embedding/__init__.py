"""Sharded embedding subsystem (ISSUE 14): recommendation-scale tables
too large for any chip, row-sharded across the dist_async
KVStoreServers.

- :class:`ShardedEmbeddingTable` — the client data plane: stable-hash
  row sharding (``sharding.RowSharding``), deduplicated ``row_pull``
  reads, async row-scatter pushes on the PR 4 sender pipeline,
  optional 2-bit wire compression, per-server memory ~1/num_servers.
- :class:`SparseEmbedding` — the Gluon block: pulls exactly the rows a
  batch touches, autograd accumulates their gradients, ``step()``
  pushes them back for the server-side lazy sparse optimizer.
- :class:`EmbeddingLookupServer` / :class:`EmbeddingTowerPredictor` —
  the serving half: sharded lookup + dense tower through AOTPredictor,
  registered as a fleet ``replica`` role (PR 11 discovery/routing/
  drain apply unchanged).
- :func:`elastic_table_checkpoint` — the PR 3 coordinated-checkpoint
  choreography over sharded tables; a respawned server restores its
  suffix-routed sub-keys through the existing elastic path.
- Typed failures raise :class:`EmbeddingShardError` at the client —
  out-of-vocabulary ids are never clamped and never surface
  server-side only.
"""
from .sharding import (  # noqa: F401
    RowSharding,
    embedding_shard_rank,
    embedding_sub_key,
)
from .table import (  # noqa: F401
    EmbeddingShardError,
    ShardedEmbeddingTable,
)
from .block import SparseEmbedding  # noqa: F401
from .lookup import (  # noqa: F401
    EmbeddingLookupServer,
    EmbeddingTowerPredictor,
)
from .checkpoint import elastic_table_checkpoint  # noqa: F401
