"""Data iterators.

Reference counterpart: ``python/mxnet/io.py`` (954 LoC: DataIter/DataBatch/
DataDesc ABC, NDArrayIter, ResizeIter, PrefetchingIter) + the C++ iterator
registry (src/io/ — MNISTIter, CSVIter, ImageRecordIter…, SURVEY §2.7).
TPU-native design: host-side pipelines produce numpy batches; device
transfer happens once per batch (the reference's pinned-memory staging is
jax.device_put). Background prefetch uses a thread (the dmlc::ThreadedIter
analogue) so decode overlaps device compute.
"""
from __future__ import annotations

import gzip
import os
import struct
import threading
import time
from collections import OrderedDict, namedtuple

import numpy as np

from .base import MXNetError
from .context import cpu
from .ndarray import ndarray as nd
from .ndarray.ndarray import NDArray

DataDesc = namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])


def _data_desc(name, shape, dtype=np.float32, layout="NCHW"):
    return DataDesc(name, tuple(shape), dtype, layout)


# make DataDesc constructible with defaults like the reference class
class DataDesc(DataDesc):  # noqa: F811
    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), dtype, layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")

    @staticmethod
    def get_list(shapes, types):
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict[x[0]]) for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]


class DataBatch:
    """One batch (ref: io.py DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None, bucket_key=None,
                 provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        label_shapes = [l.shape for l in self.label] if self.label else None
        return "{}: data shapes: {} label shapes: {}".format(
            self.__class__.__name__, data_shapes, label_shapes
        )


class DataIter:
    """Iterator base (ref: io.py DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(
                data=self.getdata(), label=self.getlabel(), pad=self.getpad(), index=self.getindex()
            )
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        pass

    def getdata(self):
        pass

    def getlabel(self):
        pass

    def getindex(self):
        return None

    def getpad(self):
        pass


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches per epoch
    (ref: io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background-thread prefetch (ref: io.py PrefetchingIter; the python
    face of the C++ iter_prefetcher.h).

    Lifecycle (ISSUE 5 satellite): the reference shut the threads down
    only from ``__del__``, which leaks the daemon workers whenever
    iteration stops early and the iterator stays referenced. Explicit
    :meth:`close` (also a context manager) joins them deterministically;
    ``reset()`` keeps working after ``StopIteration`` and restarts the
    epoch cleanly."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self._closed = False
        self.current_batch = [None for _ in range(self.n_iter)]
        self.next_batch = [None for _ in range(self.n_iter)]

        def prefetch_func(self, i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    self.next_batch[i] = self.iters[i].next()
                except StopIteration:
                    self.next_batch[i] = None
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            threading.Thread(target=prefetch_func, args=[self, i]) for i in range(self.n_iter)
        ]
        for thread in self.prefetch_threads:
            thread.daemon = True
            thread.start()

    def close(self):
        """Join the prefetch threads and close the source iterators that
        support close(). Idempotent; the iterator is unusable after."""
        if self._closed:
            return
        self._closed = True
        self.started = False
        # a worker mid-fetch clears data_taken when its next() returns,
        # erasing a single set() — keep re-signalling until each thread
        # observes started=False. Bounded: a worker wedged inside the
        # source iterator's next() is a daemon and is abandoned.
        deadline = time.monotonic() + 5.0
        for e, thread in zip(self.data_taken, self.prefetch_threads):
            while thread.is_alive() and time.monotonic() < deadline:
                e.set()
                thread.join(timeout=0.05)
        for it in self.iters:
            inner_close = getattr(it, "close", None)
            if callable(inner_close):
                inner_close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum(
            [
                [
                    DataDesc(r[x.name], x.shape, x.dtype)
                    if isinstance(x, DataDesc)
                    else DataDesc(r[x[0]], x[1])
                    for x in i.provide_data
                ]
                for r, i in zip(self.rename_data, self.iters)
            ],
            [],
        )

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum(
            [
                [
                    DataDesc(r[x.name], x.shape, x.dtype)
                    if isinstance(x, DataDesc)
                    else DataDesc(r[x[0]], x[1])
                    for x in i.provide_label
                ]
                for r, i in zip(self.rename_label, self.iters)
            ],
            [],
        )

    def reset(self):
        if self._closed:
            raise MXNetError("PrefetchingIter: iterator is closed")
        # after StopIteration data_ready is already set (next_batch is
        # None), so this wait returns immediately and the epoch restarts
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        if self._closed:
            raise MXNetError("PrefetchingIter: iterator is closed")
        for e in self.data_ready:
            e.wait()
        if self.next_batch[0] is None:
            for i in self.next_batch:
                assert i is None, "Number of entry mismatches between iterators"
            return False
        for batch in self.next_batch:
            assert batch.pad == self.next_batch[0].pad, "Different pad values in the iterators"
        self.current_batch = DataBatch(
            sum([batch.data for batch in self.next_batch], []),
            sum([batch.label for batch in self.next_batch], []),
            self.next_batch[0].pad,
            self.next_batch[0].index,
            provide_data=self.provide_data,
            provide_label=self.provide_label,
        )
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


def _init_data(data, allow_empty, default_name):
    """Normalize input data to list of (name, numpy) (ref: io.py _init_data)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = OrderedDict([(default_name, data[0])])
        else:
            data = OrderedDict([("_%d_%s" % (i, default_name), d) for i, d in enumerate(data)])
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of them or dict with them as values")
    for k, v in data.items():
        if not isinstance(v, NDArray):
            if not isinstance(v, (np.ndarray, list, tuple)):
                raise TypeError("Invalid type '%s' for %s" % (type(v), k))
            data[k] = nd.array(v)
    return list(data.items())


class NDArrayIter(DataIter):
    """In-memory iterator with pad/shuffle/discard (ref: io.py NDArrayIter)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)

        self.idx = np.arange(self.data[0][1].shape[0])
        if shuffle:
            np.random.shuffle(self.idx)
            self.data = [(k, nd.array(v.asnumpy()[self.idx], ctx=v.ctx)) for k, v in self.data]
            self.label = [(k, nd.array(v.asnumpy()[self.idx], ctx=v.ctx)) for k, v in self.label]

        if last_batch_handle == "discard":
            new_n = self.data[0][1].shape[0] - self.data[0][1].shape[0] % batch_size
            self.idx = self.idx[:new_n]

        self.data_list = [x[1] for x in self.data] + [x[1] for x in self.label]
        self.num_source = len(self.data_list)
        self.num_data = self.idx.shape[0]
        assert self.num_data >= batch_size, "batch_size needs to be smaller than data size."
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.last_batch_handle = last_batch_handle
        # tail-batch staging (ISSUE 5 satellite): host numpy mirrors of
        # each source plus one preallocated wraparound buffer per source,
        # filled in place — the reference re-materialized BOTH full
        # source arrays and concatenated fresh numpy per padded batch
        self._np_cache = {}
        self._tail_bufs = {}

    @property
    def provide_data(self):
        return [
            DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])), v.dtype)
            for k, v in self.data
        ]

    @property
    def provide_label(self):
        return [
            DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])), v.dtype)
            for k, v in self.label
        ]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if self.iter_next():
            return DataBatch(
                data=self.getdata(), label=self.getlabel(), pad=self.getpad(), index=None
            )
        raise StopIteration

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            # zero-copy fast path (ISSUE 5 satellite): aligned batches are
            # views into the source arrays — no per-batch copy on the feed
            # path (shuffle already rematerialized its own arrays at
            # __init__, so views stay consistent across epochs)
            return [x[1][self.cursor : self.cursor + self.batch_size]
                    for x in data_source]
        pad = self.batch_size - self.num_data + self.cursor
        head = self.num_data - self.cursor
        out = []
        for name, arr in data_source:
            src = self._np_cache.get(name)
            if src is None:
                src = self._np_cache[name] = np.asarray(arr._data())
            buf = self._tail_bufs.get(name)
            if buf is None:
                buf = self._tail_bufs[name] = np.empty(
                    (self.batch_size,) + src.shape[1:], src.dtype)
            np.copyto(buf[:head], src[self.cursor:])
            np.copyto(buf[head:], src[:pad])
            # hand device_put a private copy: some backends alias the
            # host buffer (or read it asynchronously), and the staging
            # buffer is overwritten on the next epoch's tail while the
            # previous batch may still be referenced downstream
            out.append(nd.array(buf.copy()))
        return out

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class MNISTIter(DataIter):
    """MNIST reader (ref: src/io/iter_mnist.cc:260 — same file format, host
    numpy decode instead of C++)."""

    def __init__(self, image="train-images-idx3-ubyte", label="train-labels-idx1-ubyte",
                 batch_size=128, shuffle=True, flat=False, silent=False, seed=0,
                 input_shape=None, **kwargs):
        super().__init__(batch_size)
        imgs = self._read_images(image)
        labels = self._read_labels(label)
        if shuffle:
            rng = np.random.RandomState(seed or 0)
            order = rng.permutation(len(imgs))
            imgs, labels = imgs[order], labels[order]
        imgs = imgs.astype(np.float32) / 255.0
        if flat:
            imgs = imgs.reshape(len(imgs), -1)
        else:
            imgs = imgs.reshape(len(imgs), 1, 28, 28)
        self._iter = NDArrayIter(imgs, labels.astype(np.float32), batch_size=batch_size,
                                 last_batch_handle="discard")

    @staticmethod
    def _open(path):
        if path.endswith(".gz") or (not os.path.exists(path) and os.path.exists(path + ".gz")):
            p = path if path.endswith(".gz") else path + ".gz"
            return gzip.open(p, "rb")
        return open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise MXNetError("bad MNIST image magic %d" % magic)
            return np.frombuffer(f.read(num * rows * cols), dtype=np.uint8).reshape(num, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, num = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise MXNetError("bad MNIST label magic %d" % magic)
            return np.frombuffer(f.read(num), dtype=np.uint8)

    @property
    def provide_data(self):
        return self._iter.provide_data

    @property
    def provide_label(self):
        return self._iter.provide_label

    def reset(self):
        self._iter.reset()

    def next(self):
        return self._iter.next()


class CSVIter(DataIter):
    """CSV reader (ref: src/io/iter_csv.cc:151)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32, ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32, ndmin=2)
            label = label.reshape((-1,) + tuple(label_shape))
            if label.shape[-1] == 1 and len(label_shape) == 1 and label_shape[0] == 1:
                label = label.reshape(-1)
        else:
            label = np.zeros((data.shape[0],), dtype=np.float32)
        self._iter = NDArrayIter(
            data, label, batch_size=batch_size,
            last_batch_handle="pad" if round_batch else "discard",
            label_name="label",
        )

    @property
    def provide_data(self):
        return self._iter.provide_data

    @property
    def provide_label(self):
        return self._iter.provide_label

    def reset(self):
        self._iter.reset()

    def next(self):
        return self._iter.next()


def ImageRecordIter(**kwargs):
    """RecordIO image pipeline (ref: src/io/iter_image_recordio_2.cc:724).
    Implemented over the python recordio reader + image module."""
    from .image.recordio_iter import ImageRecordIterImpl

    return ImageRecordIterImpl(**kwargs)


def ImageRecordUInt8Iter(**kwargs):
    from .image.recordio_iter import ImageRecordIterImpl

    return ImageRecordIterImpl(dtype="uint8", **kwargs)


def ImageDetRecordIter(path_imgrec, data_shape=(3, 300, 300), batch_size=1,
                       path_imgidx=None, shuffle=False, mean_r=0.0, mean_g=0.0,
                       mean_b=0.0, std_r=1.0, std_g=1.0, std_b=1.0,
                       rand_crop_prob=0.0, rand_pad_prob=0.0, rand_mirror_prob=0.0,
                       min_object_covered=0.1, min_eject_coverage=0.3,
                       max_attempts=50, pad_val=127, resize_mode="force",
                       label_pad_width=0, data_name="data", label_name="label",
                       **kwargs):
    """Detection RecordIO pipeline (ref: src/io/iter_image_det_recordio.cc:582
    ImageDetRecordIter + image_det_aug_default.cc): recordio decode →
    bbox-aware augment → force-resize → padded (batch, max_obj, width)
    labels, with background prefetch."""
    import numpy as np

    from .image.detection import ImageDetIter
    from .image.recordio_iter import mean_std_arrays

    if kwargs:
        raise MXNetError("ImageDetRecordIter: unknown parameters %r"
                         % sorted(kwargs))
    if resize_mode != "force":
        raise MXNetError("ImageDetRecordIter: only resize_mode='force' is "
                         "implemented (got %r)" % resize_mode)
    mean, std = mean_std_arrays(mean_r, mean_g, mean_b, std_r, std_g, std_b)
    inner = ImageDetIter(
        batch_size=batch_size, data_shape=tuple(data_shape),
        path_imgrec=path_imgrec, path_imgidx=path_imgidx, shuffle=shuffle,
        rand_crop=rand_crop_prob, rand_pad=rand_pad_prob,
        rand_mirror=rand_mirror_prob, mean=mean, std=std,
        min_object_covered=min_object_covered,
        min_eject_coverage=min_eject_coverage, max_attempts=max_attempts,
        pad_val=(pad_val,) * 3 if np.isscalar(pad_val) else tuple(pad_val),
        data_name=data_name, label_name=label_name,
    )
    if label_pad_width:
        width = inner.object_width
        # pad up only; reshape() rejects shrinking below the dataset extent
        objs = max(inner.max_objects, label_pad_width // width)
        inner.reshape(label_shape=(objs, width))
    return PrefetchingIter(inner)


class LibSVMIter(DataIter):
    """Sparse libsvm reader (ref: src/io/iter_libsvm.cc:200). Loads to a
    dense batch (TPU has no native sparse); CSR surface comes from
    ndarray.sparse."""

    def __init__(self, data_libsvm, data_shape, label_libsvm=None, batch_size=1, **kwargs):
        super().__init__(batch_size)
        feat_dim = int(np.prod(data_shape))
        rows = []
        labels = []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.strip().split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                row = np.zeros(feat_dim, dtype=np.float32)
                for kv in parts[1:]:
                    k, v = kv.split(":")
                    row[int(k)] = float(v)
                rows.append(row)
        data = np.stack(rows).reshape((-1,) + tuple(data_shape))
        self._iter = NDArrayIter(data, np.asarray(labels, dtype=np.float32),
                                 batch_size=batch_size, last_batch_handle="discard",
                                 label_name="label")

    @property
    def provide_data(self):
        return self._iter.provide_data

    @property
    def provide_label(self):
        return self._iter.provide_label

    def reset(self):
        self._iter.reset()

    def next(self):
        return self._iter.next()
