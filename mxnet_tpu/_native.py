"""Loader for the native host runtime (src/ → libmxtpu_runtime.so).

Reference counterpart: ``python/mxnet/base.py _load_lib`` loading
libmxnet.so via ctypes. The library is built from ``src/`` on demand
(first import) with the baked-in g++ toolchain; set
``MXNET_TPU_NO_NATIVE=1`` to force the pure-Python fallbacks.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LIB = None
_LOCK = threading.Lock()
_SRC_FILES = ("common.cc", "engine.cc", "storage.cc", "recordio.cc",
              "mxtpu_runtime.h")


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lib_path():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lib", "libmxtpu_runtime.so")


def _needs_build(lib, srcdir):
    if not os.path.exists(lib):
        return True
    lib_mtime = os.path.getmtime(lib)
    return any(
        os.path.getmtime(os.path.join(srcdir, f)) > lib_mtime
        for f in _SRC_FILES if os.path.exists(os.path.join(srcdir, f))
    )


def _build():
    srcdir = os.path.join(_repo_root(), "src")
    lib = _lib_path()
    if not os.path.isdir(srcdir):
        return None  # installed without sources; need a prebuilt lib
    if _needs_build(lib, srcdir):
        os.makedirs(os.path.dirname(lib), exist_ok=True)
        # Sweep temp files orphaned by builders killed mid-make (their
        # finally never ran). Only files older than 10 min are removed so
        # a concurrent live build's temp is never yanked out from under
        # its os.replace.
        import glob
        import time

        for stale in glob.glob(lib + ".tmp.*"):
            try:
                if time.time() - os.path.getmtime(stale) > 600:
                    os.remove(stale)
            except OSError:
                pass
        # Build to a per-process temp name and rename into place atomically:
        # tools/launch.py spawns N workers that may build concurrently, and
        # a reader must never dlopen a partially written .so.
        tmp = "%s.tmp.%d" % (lib, os.getpid())
        try:
            # single source of truth for flags: src/Makefile
            subprocess.run(["make", "-C", srcdir, "OUT=%s" % tmp],
                           check=True, capture_output=True)
            os.replace(tmp, lib)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
    return lib


def _declare(lib):
    c = ctypes.c_void_p
    lib.MXTGetLastError.restype = ctypes.c_char_p
    lib.MXTEngineCreate.restype = c
    lib.MXTEngineCreate.argtypes = [ctypes.c_int]
    lib.MXTEngineFree.argtypes = [c]
    lib.MXTEngineNewVar.restype = ctypes.c_int64
    lib.MXTEngineNewVar.argtypes = [c]
    lib.MXTEnginePush.restype = ctypes.c_int
    lib.MXTEnginePush.argtypes = [
        c, ENGINE_FN, c,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int]
    lib.MXTEngineWaitForVar.restype = ctypes.c_int
    lib.MXTEngineWaitForVar.argtypes = [c, ctypes.c_int64]
    lib.MXTEngineWaitAll.restype = ctypes.c_int
    lib.MXTEngineWaitAll.argtypes = [c]
    lib.MXTEngineStats.argtypes = [c, ctypes.POINTER(ctypes.c_int64),
                                   ctypes.POINTER(ctypes.c_int64)]

    lib.MXTStoragePoolCreate.restype = c
    lib.MXTStoragePoolCreate.argtypes = [ctypes.c_size_t]
    lib.MXTStoragePoolFree.argtypes = [c]
    lib.MXTStorageAlloc.restype = c
    lib.MXTStorageAlloc.argtypes = [c, ctypes.c_size_t]
    lib.MXTStorageRelease.argtypes = [c, c, ctypes.c_size_t]
    lib.MXTStoragePoolStats.argtypes = [c] + [ctypes.POINTER(ctypes.c_int64)] * 4
    lib.MXTStoragePoolDrain.argtypes = [c]

    lib.MXTRecordIOWriterCreate.restype = c
    lib.MXTRecordIOWriterCreate.argtypes = [ctypes.c_char_p]
    lib.MXTRecordIOWriterWrite.restype = ctypes.c_int
    lib.MXTRecordIOWriterWrite.argtypes = [c, ctypes.c_char_p, ctypes.c_size_t]
    lib.MXTRecordIOWriterTell.restype = ctypes.c_int64
    lib.MXTRecordIOWriterTell.argtypes = [c]
    lib.MXTRecordIOWriterClose.restype = ctypes.c_int
    lib.MXTRecordIOWriterClose.argtypes = [c]
    lib.MXTRecordIOReaderCreate.restype = c
    lib.MXTRecordIOReaderCreate.argtypes = [ctypes.c_char_p]
    lib.MXTRecordIOReaderNext.restype = ctypes.c_int
    lib.MXTRecordIOReaderNext.argtypes = [
        c, ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_size_t)]
    lib.MXTRecordIOReaderSeek.restype = ctypes.c_int
    lib.MXTRecordIOReaderSeek.argtypes = [c, ctypes.c_int64]
    lib.MXTRecordIOReaderTell.restype = ctypes.c_int64
    lib.MXTRecordIOReaderTell.argtypes = [c]
    lib.MXTRecordIOReaderClose.restype = ctypes.c_int
    lib.MXTRecordIOReaderClose.argtypes = [c]
    return lib


ENGINE_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


def get_lib():
    """The loaded native library, or None (disabled / build failed)."""
    global _LIB
    if _LIB is not None:
        return _LIB or None
    with _LOCK:
        if _LIB is not None:
            return _LIB or None
        if os.environ.get("MXNET_TPU_NO_NATIVE", "0") == "1":
            _LIB = False
            return None
        try:
            lib = _build()
            if lib is None:
                _LIB = False
                return None
            _LIB = _declare(ctypes.CDLL(lib))
        except (OSError, subprocess.CalledProcessError):
            _LIB = False
            return None
    return _LIB or None


def last_error():
    lib = get_lib()
    if lib is None:
        return ""
    return (lib.MXTGetLastError() or b"").decode()
