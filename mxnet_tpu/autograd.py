"""Imperative autograd: tape recording + reverse-mode backward.

Reference counterpart: ``src/imperative/imperative.cc`` (MarkVariables :112,
RecordOp :182, Backward :357) and ``python/mxnet/autograd.py``. TPU-native
design: the tape records (op, attrs, input values); backward computes
per-node cotangents with ``jax.vjp`` of the registered pure function — the
whole of pass::Gradient plus the backward executor collapses into JAX's VJP
machinery. Thread-local is_recording/is_training flags mirror
``Imperative::is_recording_``/``is_train_`` (imperative.cc:25-29).
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

import jax
import numpy as np

from .base import MXNetError

_STATE = threading.local()


def _st():
    if not hasattr(_STATE, "recording"):
        _STATE.recording = False
        _STATE.training = False
    return _STATE


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(is_rec):
    prev = _st().recording
    _st().recording = bool(is_rec)
    return prev


def set_training(train_mode):
    prev = _st().training
    _st().training = bool(train_mode)
    return prev


@contextmanager
def record(train_mode=True):
    """Scope: record ops for autograd (ref: python/mxnet/autograd.py record)."""
    prev_rec = set_recording(True)
    prev_train = set_training(train_mode)
    try:
        yield
    finally:
        set_recording(prev_rec)
        set_training(prev_train)


@contextmanager
def pause(train_mode=False):
    prev_rec = set_recording(False)
    prev_train = set_training(train_mode)
    try:
        yield
    finally:
        set_recording(prev_rec)
        set_training(prev_train)


@contextmanager
def train_mode():
    prev = set_training(True)
    try:
        yield
    finally:
        set_training(prev)


@contextmanager
def predict_mode():
    prev = set_training(False)
    try:
        yield
    finally:
        set_training(prev)


class TapeNode:
    """One recorded op application (the AGInfo/nnvm-Node analogue)."""

    __slots__ = (
        "op",
        "attrs",
        "inputs",
        "input_values",
        "n_outputs",
        "rng_key",
        "saved",
        "custom",
        "freed",
    )

    def __init__(self, op, attrs, inputs, input_values, n_outputs, rng_key=None, custom=None):
        self.op = op
        self.attrs = attrs
        self.inputs = inputs  # list of NDArray (keeps them alive for backward)
        self.input_values = input_values  # raw jax arrays (None for missing optionals)
        self.n_outputs = n_outputs
        self.rng_key = rng_key
        self.custom = custom  # optional CustomFunction providing backward
        self.saved = None
        self.freed = False  # set when backward(retain_graph=False) guts it


class GradEntry:
    """Autograd metadata stamped on an NDArray (the ``entry_`` analogue,
    ref include/mxnet/ndarray.h:98)."""

    __slots__ = ("node", "index", "is_variable", "grad", "grad_req")

    def __init__(self, node=None, index=0):
        self.node = node
        self.index = index
        self.is_variable = False
        self.grad = None  # NDArray buffer for marked variables
        self.grad_req = "write"


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers (ref: Imperative::MarkVariables imperative.cc:112)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, grad, req in zip(variables, gradients, grad_reqs):
        entry = GradEntry()
        entry.is_variable = True
        entry.grad = grad
        entry.grad_req = req
        var._grad_entry = entry


def record_op(op, attrs, inputs, outputs, input_values, rng_key=None, custom=None):
    """Stamp a TapeNode onto outputs (ref: Imperative::RecordOp imperative.cc:182)."""
    node = TapeNode(op, attrs, list(inputs), list(input_values), len(outputs), rng_key, custom)
    for i, out in enumerate(outputs):
        out._grad_entry = GradEntry(node, i)
    return node


def _topo_order(head_arrays):
    """Reverse-topological node order from head output arrays."""
    visited = set()
    order = []

    def visit(node):
        if node is None or id(node) in visited:
            return
        visited.add(id(node))
        for inp in node.inputs:
            e = getattr(inp, "_grad_entry", None)
            if e is not None and e.node is not None:
                visit(e.node)
        order.append(node)

    for arr in head_arrays:
        e = getattr(arr, "_grad_entry", None)
        if e is not None and e.node is not None:
            visit(e.node)
    return order[::-1]  # heads first


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Run reverse-mode on recorded tape (ref: Imperative::Backward
    imperative.cc:357-470).

    heads: list of NDArray outputs; head_grads: matching cotangents or None
    (ones for scalars/any shape, matching reference behavior).
    """
    import jax.numpy as jnp

    from .ndarray.ndarray import NDArray, _wrap_result

    heads = [heads] if not isinstance(heads, (list, tuple)) else list(heads)
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]

    # cotangent store: id(node) -> [cotangent per output]
    cotangents = {}
    var_accum = {}  # id(entry) -> [entry, running sum]

    def acc_var(entry, ct):
        slot = var_accum.get(id(entry))
        if slot is None:
            var_accum[id(entry)] = [entry, ct]
        else:
            slot[1] = slot[1] + ct

    for arr, hg in zip(heads, head_grads):
        e = getattr(arr, "_grad_entry", None)
        if e is None:
            raise MXNetError("cannot differentiate: array is not in a recorded graph")
        g = hg._data() if hasattr(hg, "_data") else (
            jnp.ones_like(arr._data()) if hg is None else jnp.asarray(hg)
        )
        if e.node is None:
            # head is itself a marked variable
            acc_var(e, g)
            continue
        slot = cotangents.setdefault(id(e.node), [None] * e.node.n_outputs)
        slot[e.index] = g if slot[e.index] is None else slot[e.index] + g

    order = _topo_order(heads)
    for node in order:
        outs_ct = cotangents.pop(id(node), None)
        if outs_ct is None:
            continue
        in_cts = _node_vjp(node, outs_ct, train_mode)
        for inp, ct in zip(node.inputs, in_cts):
            if ct is None or inp is None:
                continue
            e = getattr(inp, "_grad_entry", None)
            if e is None:
                continue
            if e.node is not None:
                slot = cotangents.setdefault(id(e.node), [None] * e.node.n_outputs)
                slot[e.index] = ct if slot[e.index] is None else slot[e.index] + ct
            if e.is_variable:
                acc_var(e, ct)

    # apply accumulated grads to variable buffers per grad_req
    for entry, total in var_accum.values():
        buf = entry.grad
        if buf is None or entry.grad_req == "null":
            continue
        ct = total.astype(buf.dtype) if total.dtype != buf.dtype else total
        if entry.grad_req == "add":
            buf._rebind(buf._data() + ct)
        else:
            buf._rebind(ct)

    if not retain_graph:
        for node in order:
            node.inputs = []
            node.input_values = []
            node.saved = None
            node.freed = True
        for arr in heads:
            e = getattr(arr, "_grad_entry", None)
            if e is not None and not e.is_variable:
                arr._grad_entry = None


def _node_vjp(node, out_cotangents, train_mode):
    """Compute input cotangents for one tape node via jax.vjp."""
    import jax.numpy as jnp

    if node.custom is not None:
        return node.custom.backward_cotangents(node, out_cotangents)
    op = node.op
    if op.nondiff:
        return [None] * len(node.inputs)

    attrs = dict(node.attrs)
    if "__is_train__" in op.attr_defaults:
        attrs["__is_train__"] = train_mode

    vals = node.input_values
    present = [i for i, v in enumerate(vals) if v is not None]

    def fn(*arrays):
        full = list(vals)
        for i, a in zip(present, arrays):
            full[i] = a
        if op.needs_rng:
            return op.fn(node.rng_key, *full, **attrs)
        return op.fn(*full, **attrs)

    primals = [vals[i] for i in present]
    outs, vjp_fn = jax.vjp(fn, *primals)
    if not isinstance(outs, tuple):
        outs = (outs,)
    cts = []
    for i, o in enumerate(outs):
        given = out_cotangents[i] if i < len(out_cotangents) else None
        cts.append(given if given is not None else jnp.zeros_like(o))
    grads = vjp_fn(tuple(cts) if len(cts) > 1 else cts[0])
    full_grads = [None] * len(vals)
    for i, g in zip(present, grads):
        full_grads[i] = g
    return full_grads


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False, train_mode=True):
    """Compute and return grads of heads wrt variables without touching .grad
    (ref: python/mxnet/autograd.py grad())."""
    from .ndarray import ndarray as _nd

    variables = [variables] if not isinstance(variables, (list, tuple)) else list(variables)
    saved = [(getattr(v, "_grad_entry", None)) for v in variables]
    bufs = [_nd.zeros(v.shape, ctx=v.ctx, dtype=v.dtype) for v in variables]
    # temporarily mark
    for v, b, old in zip(variables, bufs, saved):
        entry = GradEntry(old.node if old else None, old.index if old else 0)
        entry.is_variable = True
        entry.grad = b
        entry.grad_req = "add"
        v._grad_entry = entry
    try:
        backward(heads, head_grads, retain_graph=bool(retain_graph or create_graph), train_mode=train_mode)
    finally:
        for v, old in zip(variables, saved):
            v._grad_entry = old
    return bufs


def get_symbol(x):
    """Symbolize the recorded imperative graph reaching ``x`` (ref:
    MXAutogradGetSymbol, c_api.h:792 / Imperative::GetGraph).

    Leaf arrays (inputs and marked variables) become variables named
    ``var0, var1, ...`` in first-use order; recorded ops re-compose as
    symbol nodes with their recorded attrs. Graphs containing a Python
    ``autograd.Function`` node cannot be symbolized (the reference has
    the same limitation for its CachedOp-less custom functions)."""
    from .symbol.symbol import Symbol, _Node, _infer_arity

    entry = getattr(x, "_grad_entry", None)
    if entry is None or entry.node is None:
        raise MXNetError(
            "autograd.get_symbol: array is not the output of a recorded op")
    node_memo = {}
    var_memo = {}
    counter = [0]

    def entry_for_array(arr):
        e = getattr(arr, "_grad_entry", None)
        if e is not None and e.node is not None:
            return (build(e.node), e.index)
        key = id(arr)
        if key not in var_memo:
            var_memo[key] = _Node(None, {}, [], "var%d" % counter[0])
            counter[0] += 1
        return (var_memo[key], 0)

    def build(tnode):
        if id(tnode) in node_memo:
            return node_memo[id(tnode)]
        if tnode.op is None:
            raise MXNetError(
                "autograd.get_symbol: graph contains a Python "
                "autograd.Function node; only operator graphs symbolize")
        if tnode.freed:
            raise MXNetError(
                "autograd.get_symbol: graph was freed by backward(); "
                "pass retain_graph=True to keep it symbolizable")
        attrs = {k: v for k, v in tnode.attrs.items()
                 if not k.startswith("__")}
        # omitted trailing optional inputs (recorded as None) drop, the
        # same convention as create_symbol; a non-trailing hole cannot
        # be represented as a graph node
        arrays = list(tnode.inputs)
        while arrays and arrays[-1] is None:
            arrays.pop()
        if any(a is None for a in arrays):
            raise MXNetError(
                "autograd.get_symbol: op %s was recorded with a "
                "non-trailing missing optional input" % tnode.op.name)
        inputs = [entry_for_array(a) for a in arrays]
        n = _Node(tnode.op, attrs, inputs,
                  "%s%d" % (tnode.op.name.lstrip("_").lower(),
                            len(node_memo)),
                  arity=_infer_arity(tnode.op, len(inputs)))
        node_memo[id(tnode)] = n
        return n

    head = build(entry.node)
    return Symbol([(head, entry.index)])


class Function:
    """User-defined differentiable function (ref: python/mxnet/autograd.py
    Function). Subclass and implement forward/backward on NDArrays."""

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved or ()

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *out_grads):
        raise NotImplementedError

    def backward_cotangents(self, node, out_cotangents):
        import jax.numpy as jnp

        from .ndarray.ndarray import _wrap_raw

        wrapped = []
        for i, ct in enumerate(out_cotangents):
            if ct is None:
                ct = jnp.zeros_like(node.saved[i])
            wrapped.append(_wrap_raw(ct))
        with pause():
            in_grads = self.backward(*wrapped)
        if not isinstance(in_grads, (list, tuple)):
            in_grads = [in_grads]
        return [g._data() if g is not None else None for g in in_grads]

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray

        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)
        if is_recording():
            node = record_op(
                None, {}, list(inputs), outs,
                [i._data() for i in inputs], custom=self,
            )
            node.saved = [o._data() for o in outs]
        return outputs if single else outs
