"""KVStore parameter server: a real server-side-optimizer tier, plus
the serverless-parity shim.

Reference counterpart: ``python/mxnet/kvstore_server.py`` (the server
main loop driven by DMLC_ROLE=server) and ``kvstore_dist_server.h``
(merge buffers + server-executed optimizer, :113-500).

Two tiers, chosen by configuration:

1. **Serverless (TPU default).** Aggregation is an XLA all-reduce over
   the device mesh and the optimizer runs replicated on workers (see
   kvstore.DistKVStore, parallel/spmd.py zero=True). A process started
   with DMLC_ROLE=server/scheduler and no server opt-in exits 0 so
   reference launch scripts keep working — the jax coordinator (spawned
   inside worker 0) already plays the scheduler's rendezvous role.

2. **Real server (``MXNET_KVSTORE_SERVER=1``).** ``KVStoreServer``
   holds the weights, applies pushes through a server-side optimizer
   (exactly the reference's dist_async contract: each worker's push is
   applied when it arrives — no global synchronisation — and pulls
   return the freshest weights), and answers pulls/barriers over a
   length-prefixed TCP protocol. ``kvstore.create('dist_async')``
   connects to it when ``MXNET_PS_SERVER_URI`` is set (see
   ``ServerKVStore``). This is the behavioral equivalent of the
   reference's server-side-optimizer mode, runnable on CPU hosts.

Protocol: 4-byte big-endian length + payload. Payloads are tuples
``(op, key, meta, raw_bytes)`` encoded with pickle but decoded by a
restricted unpickler — arrays travel as (dtype, shape, bytes), never
as pickled objects, and the unpickler refuses every global lookup.
Like the reference's ps-lite transport this is an in-cluster protocol
with no auth; do not expose the port beyond the job.
"""
from __future__ import annotations

import heapq
import os
import pickle
import re
import socket
import sys
import threading
import time
import uuid
import warnings
import zlib

import numpy as np

from . import chaos
from . import config
from . import kvstore
from . import profiler
from .base import MXNetError
from .checkpoint import atomic_write_bytes
from .kvstore import (two_bit_dequantize, two_bit_quantize,
                      validate_compression_params)


# ---------------------------------------------------------------------------
# wire helpers — the framing + restricted unpickler are SHARED with the
# tracker protocol (tracker.py is stdlib-only, so this import is
# cycle-free): one hardening surface, not two drifting copies
# ---------------------------------------------------------------------------
from .tracker import (_SafeUnpickler, _pack, _recv_exact,  # noqa: F401
                      _recv_msg, _send_msg, _unpack,
                      env_nonneg_int, env_positive_float)


def shard_key(key, num_shards):
    """key -> shard index; stable across processes AND incarnations
    (builtin hash is salted per-interpreter, crc32 is not). Shared by
    the client's routing and a respawned server's checkpoint restore —
    one definition, or the two would drift and a restored server would
    load the wrong keys."""
    if num_shards <= 1:
        return 0
    return zlib.crc32(str(key).encode()) % num_shards


# ---------------------------------------------------------------------------
# Embedding row-sharding (ISSUE 14): a sharded embedding table lives as
# one dense SUB-TABLE per row shard, named ``<key>@embshard<s>`` and
# stored on server ``s % num_servers`` — the routing is purely
# client-side like the ZeRO value-sharded slices below, but keyed by a
# suffix rule instead of the crc32 key hash so a respawned server can
# tell exactly which sub-keys are its own. ONE definition of that rule,
# shared by the client's routing (embedding/table.py) and
# ``restore_from_checkpoint`` — or a restored server would load the
# wrong sub-tables.
# ---------------------------------------------------------------------------
_EMBED_SHARD_RE = re.compile(r"@embshard(\d+)$")


def embedding_sub_key(key, shard):
    """The dense sub-table key holding row shard ``shard`` of the
    sharded embedding table ``key``."""
    return "%s@embshard%d" % (key, int(shard))


def embedding_shard_rank(key):
    """The row-shard index encoded in an embedding sub-key, or None
    for ordinary keys. Sub-key ``s`` lives on server ``s % num_servers``
    (the one routing rule, shared with embedding/table.py)."""
    m = _EMBED_SHARD_RE.search(str(key))
    return int(m.group(1)) if m else None


# ---------------------------------------------------------------------------
# ZeRO value-sharding (ISSUE 7 dist_async mirror): with
# MXNET_TPU_ZERO_SERVER=1 each large dense key's VALUE — weights AND the
# per-key optimizer state that shadows them — is sliced contiguously
# across ALL servers instead of living whole on its crc32 shard, so
# per-server HBM/host memory scales 1/num_servers (the on-mesh fused
# tier's reduce-scatter→update→allgather, mirrored as scatter-push/
# gather-pull; also the reference's BIGARRAY server-sharding, ps-lite).
# The routing is purely client-side: servers store and update their
# slice like any other key (the optimizer update is elementwise), so the
# server protocol is unchanged. The rule must be deterministic and
# shared by every client AND a restoring server — one definition here.
# ---------------------------------------------------------------------------
def zero_slice_sizes(size, num_shards):
    """Contiguous per-server slice lengths of a flattened value
    (np.array_split layout: the first ``size % n`` slices get one
    extra element)."""
    base, extra = divmod(int(size), int(num_shards))
    return [base + (1 if i < extra else 0) for i in range(num_shards)]


def zero_value_sharded(arr, num_shards, min_size):
    """True iff this (key's) dense array value-shards across servers:
    floating dense, at least min_size (and num_shards) elements."""
    return (num_shards > 1 and getattr(arr, "ndim", 0) >= 1
            and np.issubdtype(arr.dtype, np.floating)
            and arr.size >= max(int(min_size), num_shards))


def zero_slice_pytree(state, sizes, idx):
    """Server ``idx``'s slice of one key's state pytree: every ndarray
    leaf of the full flattened size slices to the contiguous range the
    ``sizes`` table assigns it; list/tuple nodes recurse; scalars/None
    (identical on every server) replicate. THE one split routine —
    shared by the client's load-time re-split and a respawned server's
    checkpoint restore, or the two would drift leaf-handling and
    desynchronize routing from recovery."""
    bounds = np.cumsum([0] + list(sizes))
    total = int(bounds[-1])

    def part(x):
        if isinstance(x, np.ndarray) and x.size == total:
            return np.ascontiguousarray(
                x.reshape(-1)[bounds[idx]:bounds[idx + 1]])
        if isinstance(x, (list, tuple)):
            return type(x)(part(i) for i in x)
        return x

    return part(state)


class _RPCTransportError(Exception):
    """Transport-level RPC failure (reset, timeout, injected drop) —
    retriable, unlike an ('err', ...) reply which means the server saw
    the request and rejected it."""


#: arrays at or above this many bytes travel as pickle-5 out-of-band
#: buffers (tracker._send_msg extended framing): the sender writes the
#: array's own memory to the socket — no tobytes()/pickle copy — and
#: the receiver deserializes a writable view of its recv buffer
_OOB_MIN_BYTES = 2048


def _arr_to_wire(a, zero_copy=False):
    a = np.ascontiguousarray(a)
    if zero_copy and a.nbytes >= _OOB_MIN_BYTES:
        # caller contract: ``a`` is a stable snapshot this side owns
        # (never a buffer the caller may mutate before the send lands)
        return (str(a.dtype), a.shape, pickle.PickleBuffer(a))
    return (str(a.dtype), a.shape, a.tobytes())


def _arr_from_wire(w):
    dtype, shape, raw = w
    arr = np.frombuffer(raw, dtype=dtype).reshape(shape)
    # out-of-band frames land in bytearrays we own: the view is already
    # writable and private — only inline (bytes-backed) payloads copy
    return arr if arr.flags.writeable else arr.copy()


#: compressed-push wire tag; never collides with a numpy dtype name
_2BIT_TAG = "2bit"


def _grad_to_wire(arr, compressed=None):
    """Dense gradient -> wire entry. ``compressed`` is the
    (packed, threshold) pair from two_bit_quantize; None ships raw."""
    if compressed is None:
        return _arr_to_wire(arr, zero_copy=True)
    packed, threshold = compressed
    payload = pickle.PickleBuffer(packed) \
        if packed.nbytes >= _OOB_MIN_BYTES else packed.tobytes()
    return (_2BIT_TAG, str(arr.dtype), tuple(arr.shape), float(threshold),
            payload)


#: row-scatter push wire tag (ISSUE 14): a sparse gradient for a
#: handful of rows of a stored dense (sub-)table — the ids ride as a
#: plain int64 wire array, the values block as a dense (or 2-bit
#: compressed) gradient entry. Riding the SAME push op means the whole
#: PR 4 data plane — per-shard sender threads, priority ordering,
#: coalesced push_multi frames, (cid, seq) dedupe under retry — applies
#: to embedding scatters with zero new protocol machinery.
_ROW_TAG = "rows"


class _RowScatter:
    """Decoded row-scatter push: ``values[i]`` is the gradient of row
    ``ids[i]`` of the stored table."""

    __slots__ = ("ids", "values")

    def __init__(self, ids, values):
        self.ids = ids
        self.values = values


def _rows_to_wire(ids, values, compressed=None):
    """(local row ids, per-row gradient block) -> wire entry."""
    return (_ROW_TAG,
            _arr_to_wire(np.ascontiguousarray(ids, dtype=np.int64)),
            _grad_to_wire(values, compressed))


def _grad_from_wire(w):
    """Wire entry -> dense gradient (dequantizing 2-bit payloads) or a
    :class:`_RowScatter` for row-granular embedding pushes."""
    if w and w[0] == _ROW_TAG:
        _tag, ids_w, vals_w = w
        return _RowScatter(_arr_from_wire(ids_w), _grad_from_wire(vals_w))
    if w and w[0] == _2BIT_TAG:
        _tag, dtype, shape, threshold, raw = w
        return two_bit_dequantize(raw, shape, dtype, threshold)
    return _arr_from_wire(w)


def _chaos_op(op):
    """Coalesced/multi-key frames answer to their base op's fault rules
    (rpc:drop@op=push must keep covering the pipelined client); the
    embedding row read answers to pull rules the same way."""
    return {"push_multi": "push", "pull_multi": "pull",
            "row_pull": "pull"}.get(op, op)


def _state_to_wire(v):
    """Optimizer-state pytree -> tagged plain data. Arrays travel as
    (dtype, shape, bytes) like every other tensor on this protocol —
    never as a pickle blob (``load_opt`` used to feed network bytes to
    ``pickle.loads`` via Updater.set_states, contradicting the module's
    no-globals guarantee)."""
    if v is None:
        return ("none",)
    if isinstance(v, (bool, int, float, str)):
        return ("py", v)
    if isinstance(v, (list, tuple)):
        tag = "list" if isinstance(v, list) else "tuple"
        return (tag, [_state_to_wire(i) for i in v])
    arr = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
    return ("nd",) + _arr_to_wire(arr)


def _state_from_wire(w):
    tag = w[0]
    if tag == "none":
        return None
    if tag == "py":
        return w[1]
    if tag == "list":
        return [_state_from_wire(i) for i in w[1]]
    if tag == "tuple":
        return tuple(_state_from_wire(i) for i in w[1])
    if tag == "nd":
        return _arr_from_wire(w[1:])
    raise ValueError("bad optimizer-state wire tag %r" % (tag,))


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------
class KVStoreServer:
    """Weights + server-side optimizer behind a TCP endpoint.

    Mirrors kvstore_dist_server.h semantics: ``init`` is first-writer-
    wins, each ``push`` is applied on arrival under the server's
    updater (optimizer state lives server-side, keyed like the
    reference's per-key store), ``pull`` returns the current weights,
    ``barrier`` blocks until every worker arrives. dist_async = push
    without waiting for the barrier.
    """

    def __init__(self, host="127.0.0.1", port=0, num_workers=1,
                 barrier_timeout=None, elastic=None):
        self._store = {}
        self._updater = None
        self._opt_config = None
        self._lock = threading.Lock()
        self._num_workers = num_workers
        self._barrier_cond = threading.Condition()
        # NAMED barrier rounds: state is per name, so two logically
        # different synchronization points (e.g. the checkpoint
        # choreography's stage/progress/commit phases) can never pair
        # with each other — without names, a worker respawned mid-
        # choreography would re-arrive at phase A and silently release
        # a survivor waiting in phase B
        self._barriers = {}         # name -> {"count": int, "gen": int}
        self._barrier_errors = {}   # (name, gen) -> abort message
        # push dedupe for idempotent client retries: client_id ->
        # highest applied per-shard sequence number (one int per live
        # worker; FIFO-capped so ancient clients cannot grow it)
        self._seen = {}
        self._seen_lock = threading.Lock()
        self._pushes_applied = 0
        self._rollback_gen = -1  # newest applied rollback generation
        if elastic is None:
            elastic = env_nonneg_int("MXNET_MAX_RESTARTS", 0) > 0
        #: elastic mode: a worker dying mid-barrier retracts its own
        #: arrival (its respawn re-arrives) instead of aborting the
        #: round for every survivor
        self._elastic = bool(elastic)
        if barrier_timeout is None:
            barrier_timeout = env_positive_float(
                "MXNET_KVSTORE_BARRIER_TIMEOUT", 120)
        self._barrier_timeout = float(barrier_timeout)
        self._conns = set()
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.addr = "%s:%d" % self._sock.getsockname()[:2]

    # -- op handlers --------------------------------------------------------
    def _apply_push(self, key, grad):
        with self._lock:
            if key not in self._store:
                raise KeyError("push before init: %r" % (key,))
            if isinstance(grad, _RowScatter):
                self._apply_row_scatter_locked(key, grad)
            elif self._updater is None:
                self._store[key] += grad
            else:
                from .ndarray import array

                w = array(self._store[key])
                self._updater(key, array(grad), w)
                self._store[key] = w.asnumpy()
            self._pushes_applied += 1
        # a server "step" for fault injection = one applied push
        # (server:R:crash@step=N); outside the lock so the injected
        # hard-exit never dies holding it
        chaos.tick_step()

    def _apply_row_scatter_locked(self, key, scatter):
        """Apply a row-granular gradient (ISSUE 14): the server-side
        optimizer runs its LAZY row-sparse update — only the pushed
        rows (and their rows of the dense optimizer state, which is
        sub-table-shaped and therefore 1/num_shards per server) move.
        Out-of-range ids are a protocol violation and error the whole
        push: the client validates against the table's vocabulary
        BEFORE routing (embedding/table.py raises the typed
        EmbeddingShardError), so reaching this guard means the
        client's sharding math and the stored sub-table disagree."""
        store = self._store[key]
        ids = np.asarray(scatter.ids, np.int64)
        vals = np.asarray(scatter.values)
        if vals.shape[:1] != ids.shape or \
                vals.shape[1:] != store.shape[1:]:
            raise ValueError(
                "row push shape mismatch for %r: %d ids, values %s vs "
                "stored rows of %s"
                % (key, ids.shape[0], vals.shape, store.shape[1:]))
        if ids.size and (ids.min() < 0 or ids.max() >= store.shape[0]):
            raise ValueError(
                "row push out of range for %r: ids [%d, %d] vs %d "
                "stored rows" % (key, int(ids.min()), int(ids.max()),
                                 store.shape[0]))
        if self._updater is None:
            np.add.at(store, ids, vals.astype(store.dtype, copy=False))
            return
        from .ndarray import array
        from .ndarray.sparse import RowSparseNDArray

        w = array(store)
        grad = RowSparseNDArray(array(vals), array(ids), w.shape)
        self._updater(key, grad, w)
        self._store[key] = w.asnumpy()

    #: per-client applied-seqno window: retries are immediate, so a
    #: never-applied seqno can only trail the newest applied one by the
    #: number of concurrently in-flight pushes — 128 is orders beyond it
    _SEEN_WINDOW = 128

    def _claim_push(self, meta):
        """Atomically claim this (client, seqno) push; False means it
        was already claimed — a retry after a lost reply, acked without
        re-applying. CLAIM-then-apply, not apply-then-record: a retry
        racing the original's still-queued apply must see the claim, or
        the same gradient lands twice. A SET of recently claimed seqnos
        (not a high-water mark): with concurrent pushers on one shard,
        a failed send's retry can legitimately arrive AFTER a higher
        seqno landed, and a high-water check would silently drop that
        never-applied gradient.

        Chosen tradeoff: at-most-once. A retry that races the
        original's still-queued apply is acked while the apply is in
        flight — the gradient still lands (moments later), which is
        within dist_async's ordering contract; the checkpoint snapshot
        may miss a push acked microseconds earlier, the same skew any
        asynchronous snapshot has. The alternative (record after
        apply) double-applies gradients under the same race, which
        corrupts training rather than merely reordering it."""
        if not meta:
            return True
        cid, seq = meta["cid"], meta["seq"]
        with self._seen_lock:
            entry = self._seen.get(cid)
            if entry is None:
                from collections import deque

                entry = self._seen[cid] = (set(), deque())
            claimed, order = entry
            if seq in claimed:
                return False
            claimed.add(seq)
            order.append(seq)
            while len(order) > self._SEEN_WINDOW:
                claimed.discard(order.popleft())
            while len(self._seen) > 4096:  # bound: dead clients age out
                self._seen.pop(next(iter(self._seen)))
            return True

    def _release_push(self, meta):
        """Undo a claim whose apply FAILED (err reply, not applied): a
        later retry of the same seqno must not be acked as done."""
        if not meta:
            return
        with self._seen_lock:
            entry = self._seen.get(meta["cid"])
            if entry is not None:
                entry[0].discard(meta["seq"])

    def _pull_wire(self, key):
        """Current weights as a wire entry. The snapshot copy happens
        under the lock (a concurrent push may mutate the stored array
        in place); the copy is what makes the out-of-band zero-copy
        send safe outside it."""
        with self._lock:
            if key not in self._store:
                raise KeyError("pull before init: %r" % (key,))
            snap = np.ascontiguousarray(self._store[key]).copy()
        return _arr_to_wire(snap, zero_copy=True)

    def _row_pull_wire(self, key, meta):
        """Selected rows of a stored dense (sub-)table as one wire
        entry (ISSUE 14): the embedding read path — the wire carries
        exactly the requested rows, never the whole table (the old
        dense-backed ``row_sparse_pull`` pulled the FULL value and
        took rows client-side). The gather-copy happens under the
        lock; the copy is what makes the zero-copy send safe outside
        it."""
        if not isinstance(meta, dict) or "ids" not in meta:
            raise ValueError("row_pull requires meta={'ids': wire}")
        ids = np.asarray(_arr_from_wire(meta["ids"]), np.int64)
        with self._lock:
            if key not in self._store:
                raise KeyError("row_pull before init: %r" % (key,))
            store = self._store[key]
            if ids.size and (ids.min() < 0
                             or ids.max() >= store.shape[0]):
                raise ValueError(
                    "row_pull out of range for %r: ids [%d, %d] vs %d "
                    "stored rows" % (key, int(ids.min()),
                                     int(ids.max()), store.shape[0]))
            snap = np.ascontiguousarray(store[ids])
        return _arr_to_wire(snap, zero_copy=True)

    def memory_bytes(self):
        """Measured bytes this server actually holds — the per-server
        1/num_servers scaling evidence (memoryStats acceptance,
        ISSUE 14): stored table bytes and optimizer-state bytes, split
        into embedding sub-tables (``@embshard`` keys) vs everything
        else."""
        def _state_bytes(v):
            if hasattr(v, "asnumpy"):
                return v.asnumpy().nbytes
            if isinstance(v, np.ndarray):
                return v.nbytes
            if isinstance(v, (list, tuple)):
                return sum(_state_bytes(i) for i in v)
            return 0

        with self._lock:
            out = {"keys": len(self._store), "store_bytes": 0,
                   "opt_bytes": 0, "embed_store_bytes": 0,
                   "embed_opt_bytes": 0}
            for k, v in self._store.items():
                out["store_bytes"] += int(v.nbytes)
                if embedding_shard_rank(k) is not None:
                    out["embed_store_bytes"] += int(v.nbytes)
            states = self._updater.states if self._updater is not None \
                else {}
            for k, v in states.items():
                b = int(_state_bytes(v))
                out["opt_bytes"] += b
                if embedding_shard_rank(k) is not None:
                    out["embed_opt_bytes"] += b
        return out

    def _set_optimizer(self, name, meta):
        from . import optimizer

        # meta is {"kwargs": ..., "extras": ...}; a bare kwargs dict
        # (older clients) is accepted as-is
        meta = meta or {}
        if "kwargs" in meta or "extras" in meta:
            kwargs = meta.get("kwargs") or {}
            extras = meta.get("extras") or {}
        else:
            kwargs, extras = meta, {}
        with self._lock:
            if self._opt_config is not None:
                # first-writer-wins, like init: every worker's
                # init_optimizer sends the config (module.py:349 has no
                # rank gate), and replacing the updater would wipe the
                # accumulated momentum/Adam state mid-training. A
                # *different* config is a real job misconfiguration —
                # EXCEPT the learning rate, the one hyperparameter that
                # is legitimately dynamic (the ISSUE 9 health guard
                # backs it off on rollback): a late-joining or
                # respawned worker re-sending the ORIGINAL lr must not
                # abort the job, and the server's current (possibly
                # backed-off) lr wins.
                def _sans_lr(cfg):
                    n, kw, ex = cfg
                    return (n, {k: v for k, v in kw.items()
                                if k != "learning_rate"}, ex)

                if _sans_lr(self._opt_config) != _sans_lr(
                        (name, kwargs, extras)):
                    raise ValueError(
                        "conflicting server optimizer: have %r, got %r"
                        % (self._opt_config, (name, kwargs, extras)))
                return
            opt = optimizer.create(name, **kwargs)
            self._apply_opt_extras(opt, extras)
            self._updater = optimizer.get_updater(opt)
            self._opt_config = (name, kwargs, extras)

    @staticmethod
    def _apply_opt_extras(opt, extras):
        """Install the non-scalar optimizer config the client serialized
        as plain wire data: per-parameter lr/wd multipliers, the
        index->name map, and a reconstructed lr scheduler."""
        if extras.get("idx2name"):
            opt.idx2name = dict(extras["idx2name"])
        if extras.get("lr_mult"):
            # direct assignment: the client already ran set_lr_mult's
            # normalization — re-running it here would double-apply
            opt.lr_mult = dict(extras["lr_mult"])
        if extras.get("wd_mult"):
            opt.wd_mult = dict(extras["wd_mult"])
        sched = extras.get("lr_scheduler")
        if sched:
            from . import lr_scheduler as lr_mod

            cls_name, skw = sched
            klass = getattr(lr_mod, cls_name, None)
            if klass is None or not (isinstance(klass, type)
                                     and issubclass(klass,
                                                    lr_mod.LRScheduler)):
                raise ValueError(
                    "set_optimizer: unknown lr_scheduler class %r"
                    % (cls_name,))
            opt.lr_scheduler = klass(**dict(skw))

    def _barrier_state(self, name):
        from .tracker import prune_barrier_names

        # the checkpoint choreography mints 3 fresh names per epoch:
        # bound the map like _seen/_barrier_errors (idle-aware shared
        # pruner — a just-aborted round's waiters must still find
        # their abort record)
        b = self._barriers.setdefault(name, {"count": 0, "gen": 0})
        b["ts"] = time.monotonic()
        prune_barrier_names(self._barriers, self._barrier_errors, name,
                            quiescent=lambda s: s["count"] == 0)
        return b

    def _abort_barrier_locked(self, name, msg):
        """Fail the in-flight barrier round: every waiter raises instead
        of spinning (round-6 fix for the permanent hang when a worker
        holding a pending arrival dies)."""
        b = self._barrier_state(name)
        if b["count"] == 0:
            return
        self._barrier_errors[(name, b["gen"])] = msg
        while len(self._barrier_errors) > 8:
            self._barrier_errors.pop(next(iter(self._barrier_errors)))
        b["gen"] += 1
        b["count"] = 0
        self._barrier_cond.notify_all()

    @staticmethod
    def _conn_closed(conn):
        """Non-consuming liveness probe of a waiter's own socket."""
        try:
            return conn.recv(1, socket.MSG_PEEK | socket.MSG_DONTWAIT) == b""
        except (BlockingIOError, InterruptedError):
            return False
        except OSError:
            return True

    def _barrier(self, conn=None, name=""):
        """Dead-worker handling: each waiter's handler thread probes its
        OWN socket (``_conn_closed``) every wait tick — a waiter whose
        worker died aborts the round for every survivor (or, in elastic
        mode, retracts its own arrival so the respawn re-arrives); a
        worker that never arrives is bounded by the overall timeout.
        Both reset the count, so later barriers start clean (the seed
        leaked the dead worker's +1 and every subsequent barrier
        deadlocked)."""
        with self._barrier_cond:
            b = self._barrier_state(name)
            gen = b["gen"]
            b["count"] += 1
            if b["count"] >= self._num_workers:
                b["count"] = 0
                b["gen"] += 1
                self._barrier_cond.notify_all()
                return
            deadline = time.monotonic() + self._barrier_timeout
            while b["gen"] == gen and not self._stop.is_set():
                if time.monotonic() >= deadline:
                    msg = ("barrier %stimed out after %.0fs (%d of %d "
                           "workers arrived)"
                           % ("%r " % name if name else "",
                              self._barrier_timeout, b["count"],
                              self._num_workers))
                    self._abort_barrier_locked(name, msg)
                    raise MXNetError(msg)
                if conn is not None and self._conn_closed(conn):
                    if self._elastic:
                        # this waiter's own worker died, but its rank
                        # will be respawned: retract the arrival so the
                        # respawn re-arrives, and leave the survivors
                        # waiting (bounded by the overall timeout) —
                        # "rejoin the barrier group instead of aborting
                        # the round" (ISSUE 3)
                        b["count"] = max(0, b["count"] - 1)
                        self._barrier_cond.notify_all()
                        raise ConnectionError(
                            "peer closed during barrier "
                            "(elastic: arrival retracted)")
                    # this waiter's own worker died mid-barrier
                    self._abort_barrier_locked(
                        name, "barrier aborted: a waiting worker "
                        "disconnected")
                    raise ConnectionError("peer closed during barrier")
                self._barrier_cond.wait(timeout=0.2)
            err = self._barrier_errors.get((name, gen))
            if err is not None:
                raise MXNetError(err)
            if self._stop.is_set() and b["gen"] == gen:
                raise MXNetError("barrier aborted: server stopped")

    def _dispatch(self, op, key, meta, wire, conn=None):
        """One op -> ('ok', payload). Raises on bad requests; _handle
        converts that to the protocol's ('err', text) reply."""
        if op == "init":
            with self._lock:
                self._store.setdefault(key, _arr_from_wire(wire))
            return None
        if op == "push":
            if not self._claim_push(meta):
                return None  # retried push: already claimed, ack only
            try:
                self._apply_push(key, _grad_from_wire(wire))
            except Exception:
                self._release_push(meta)
                raise
            return None
        if op == "push_multi":
            # one coalesced frame of small pushes (the reference's
            # 16-key push aggregation, model.py:106-124). Entries keep
            # their individual (cid, seq) pairs: a retry after a lost
            # reply re-offers every entry and the claim set acks the
            # already-applied ones without re-applying.
            for k, m, w in wire:
                if not self._claim_push(m):
                    continue
                try:
                    self._apply_push(k, _grad_from_wire(w))
                except Exception:
                    self._release_push(m)
                    raise
            return None
        if op == "pull":
            return self._pull_wire(key)
        if op == "pull_multi":
            if not isinstance(key, (list, tuple)):
                raise ValueError("pull_multi expects a key list")
            return [self._pull_wire(k) for k in key]
        if op == "row_pull":
            return self._row_pull_wire(key, meta)
        if op == "mem":
            return self.memory_bytes()
        if op == "set_optimizer":
            self._set_optimizer(key, meta)
            return None
        if op == "opt_config":
            # plain-data (name, kwargs, extras) so the checkpoint can
            # record it and a respawned server can rebuild its updater
            with self._lock:
                return self._opt_config
        if op == "num_workers":
            return self._num_workers
        if op == "barrier":
            self._barrier(conn, name=str(key or ""))
            return None
        if op == "rollback":
            return self._rollback(meta)
        if op == "save_opt":
            with self._lock:
                if self._updater is None:
                    raise ValueError("no server optimizer installed")
                return [(k, _state_to_wire(v)) for k, v in
                        self._updater.get_states_map().items()]
        if op == "load_opt":
            with self._lock:
                if self._updater is None:
                    raise ValueError("no server optimizer installed")
                if not isinstance(wire, (list, tuple)):
                    raise ValueError(
                        "load_opt expects [(key, state-wire)] pairs, got "
                        "%s (raw optimizer blobs are not accepted: the "
                        "server never unpickles network bytes)"
                        % type(wire).__name__)
                states = {k: _state_from_wire(w) for k, w in wire}
                self._updater.set_states_from_map(states)
            return None
        raise ValueError("unknown op %r" % (op,))

    def _handle(self, conn):
        try:
            while not self._stop.is_set():
                op, key, meta, wire = _recv_msg(conn)
                if chaos.rpc_fault(_chaos_op(op), side="server"):
                    # injected server-side drop: the op is NOT applied
                    # and the connection resets under the client
                    raise ConnectionError("chaos: server dropped %r" % op)
                if op == "stop":
                    _send_msg(conn, ("ok", None))
                    self.shutdown()
                    return
                try:
                    payload = self._dispatch(op, key, meta, wire, conn=conn)
                except (ConnectionError, OSError):
                    raise  # this conn's own peer vanished: no reply path
                except Exception as e:  # bad request: reply, keep serving
                    _send_msg(conn, ("err", "%s: %s"
                                     % (type(e).__name__, e)))
                    continue
                _send_msg(conn, ("ok", payload))
        except (ConnectionError, OSError):
            pass
        finally:
            self._conns.discard(conn)
            conn.close()

    def serve_forever(self):
        """Accept loop; returns after a client sends ``stop``."""
        self._sock.settimeout(0.5)
        threads = []
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            self._conns.add(conn)
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=2)

    def serve_in_background(self):
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def restore_from_checkpoint(self, ckpt, shard_rank=0, num_shards=1):
        """Preload this server's key shard from a committed checkpoint
        (the respawn path: a restarted server must hold its weights and
        optimizer state BEFORE the first retried push arrives, or the
        surviving workers' pushes hit 'push before init' / run without
        the momentum the checkpoint recorded). With
        ``MXNET_TPU_ZERO_SERVER=1`` (the env every node of the job
        shares), value-sharded keys restore exactly this server's flat
        SLICE of the full checkpointed arrays — the same deterministic
        split rule the clients route by. Returns the number of
        restored keys."""
        zero = (config.get_strict_bool("MXNET_TPU_ZERO_SERVER")
                and num_shards > 1)
        zero_min = config.get_nonneg_int("MXNET_TPU_ZERO_MIN_SIZE")
        restored = 0
        zsizes = {}  # key -> per-server slice table (value-sharded)
        weights = ckpt.weights()
        with self._lock:
            for name, arr in weights.items():
                if not name.startswith("arg:"):
                    continue  # aux state never lives on the server
                key = name[len("arg:"):]
                arr = np.asarray(arr)
                esr = embedding_shard_rank(key)
                if esr is not None:
                    # embedding sub-table: the suffix IS the routing
                    # rule (sub-key s lives on server s % num_servers)
                    # — the crc32 key hash below would scatter the
                    # sub-keys arbitrarily and a respawned server
                    # would restore someone else's rows
                    if esr % num_shards != shard_rank:
                        continue
                    self._store[key] = np.ascontiguousarray(arr).copy()
                    restored += 1
                    continue
                if zero and zero_value_sharded(arr, num_shards, zero_min):
                    sizes = zero_slice_sizes(arr.size, num_shards)
                    zsizes[key] = sizes
                    bounds = np.cumsum([0] + sizes)
                    flat = np.ascontiguousarray(arr).reshape(-1)
                    self._store[key] = flat[
                        bounds[shard_rank]:bounds[shard_rank + 1]].copy()
                    restored += 1
                    continue
                if shard_key(key, num_shards) != shard_rank:
                    continue
                self._store[key] = np.ascontiguousarray(arr).copy()
                restored += 1
        opt_cfg = ckpt.optimizer_config()
        if opt_cfg is not None:
            name, kwargs, extras = opt_cfg
            self._set_optimizer(name, {"kwargs": kwargs, "extras": extras})
        states_blob = ckpt.optimizer_states()
        if states_blob is not None and self._updater is not None:
            # the checkpoint file is a LOCAL trusted artifact (written
            # by rank 0 through save_optimizer_states); only this
            # server's shard of the merged map is installed —
            # value-sharded keys slice their full logical state arrays
            from .checkpoint import unwrap_states_map

            states_map = unwrap_states_map(pickle.loads(states_blob))
            mine = {}
            for k, v in states_map.items():
                esr = embedding_shard_rank(k)
                sizes = zsizes.get(k)
                if esr is not None:
                    if esr % num_shards == shard_rank:
                        mine[k] = v
                elif sizes is not None:
                    mine[k] = zero_slice_pytree(v, sizes, shard_rank)
                elif shard_key(k, num_shards) == shard_rank:
                    mine[k] = v
            with self._lock:
                self._updater.set_states_from_map(mine)
        return restored

    def _rollback(self, meta):
        """Coordinated health-guard rollback (ISSUE 9): reload THIS
        server's shard (weights + optimizer state) from the newest
        committed checkpoint and scale the server-side optimizer's
        learning rate (``meta["lr_scale"]``). The checkpoint directory
        comes from the server's OWN ``MXNET_CHECKPOINT_DIR`` — the RPC
        deliberately carries no path, so wire input can never choose
        which local file gets unpickled (checkpoint files stay LOCAL
        trusted artifacts). The restore itself is exactly the elastic
        respawn path (:meth:`restore_from_checkpoint`), run in place;
        HealthGuard only issues it inside a quiesced barrier window.

        Idempotence (what makes the op retry-safe): the restore is
        naturally idempotent, and the lr backoff — which is NOT — is
        deduped by ``meta["gen"]``, the guard's rollback count: a
        lost-reply retry carries the same generation and the scale is
        applied at most once per generation (the push-seqno pattern)."""
        meta = meta or {}
        ckpt_dir = os.environ.get("MXNET_CHECKPOINT_DIR")
        if not ckpt_dir:
            raise ValueError(
                "rollback: this server has no MXNET_CHECKPOINT_DIR — "
                "nothing committed to roll back to")
        from .checkpoint import CheckpointManager

        ck = CheckpointManager(ckpt_dir).latest()
        if ck is None:
            raise ValueError("rollback: no committed checkpoint under %s"
                             % ckpt_dir)
        shard_rank = env_nonneg_int("DMLC_SERVER_ID", 0)
        num_shards = max(env_nonneg_int("DMLC_NUM_SERVER", 1), 1)
        nkeys = self.restore_from_checkpoint(ck, shard_rank=shard_rank,
                                             num_shards=num_shards)
        scale = meta.get("lr_scale")
        gen = meta.get("gen")
        new_lr = None
        if scale is not None:
            scale = float(scale)
            if not 0.0 < scale <= 1.0:
                raise ValueError("rollback: lr_scale=%r must be in "
                                 "(0, 1]" % (scale,))
            with self._lock:
                if gen is not None:
                    gen = int(gen)
                    if gen <= self._rollback_gen:
                        # a retried (or replayed) generation: the
                        # backoff already landed — re-applying would
                        # square it
                        scale = None
                    else:
                        self._rollback_gen = gen
                if scale is not None and self._updater is not None:
                    opt = self._updater.optimizer
                    try:
                        opt.set_learning_rate(opt.lr * scale)
                        new_lr = opt.lr
                    except MXNetError as e:  # scheduler-driven lr
                        print("[lifecycle] rollback lr backoff skipped: "
                              "%s" % e, flush=True)
                    if new_lr is not None and self._opt_config is not None:
                        # keep the recorded config truthful: later
                        # checkpoints + respawned servers rebuild with
                        # the backed-off rate
                        n, kw, ex = self._opt_config
                        kw = dict(kw)
                        kw["learning_rate"] = new_lr
                        self._opt_config = (n, kw, ex)
        print("[lifecycle] event=rollback role=server rank=%d ckpt=%s "
              "keys=%d epoch=%d lr=%s"
              % (shard_rank, ck.path, nkeys, ck.epoch, new_lr), flush=True)
        return {"keys": int(nkeys), "epoch": int(ck.epoch), "lr": new_lr}

    def shutdown(self):
        self._stop.set()
        with self._barrier_cond:
            self._barrier_cond.notify_all()
        # unblock handler threads parked in recv so serve_forever's
        # joins return immediately (a stopped server must not make its
        # clients' next RPC hang until their own socket timeout)
        for conn in list(self._conns):
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------
class _PushFuture:
    """Completion handle for one asynchronously enqueued push: the
    engine-style future ``ServerKVStore.push`` returns immediately.
    ``wait()`` blocks until the sender thread acked (or exhausted the
    retry budget) and re-raises the failure."""

    __slots__ = ("_done", "error")

    def __init__(self):
        self._done = threading.Event()
        self.error = None

    def _finish(self, error=None):
        self.error = error
        self._done.set()

    def done(self):
        return self._done.is_set()

    def wait(self):
        self._done.wait()
        if self.error is not None:
            raise self.error


class _ShardSender:
    """One shard's sender thread (the async half of ISSUE 4): pushes
    enqueue here priority-ordered — higher priority first, the engine
    PushAsync convention; Module/Trainer push with ``priority=-index``
    so front layers (whose weights the next forward needs first) flush
    ahead — and the thread drains the queue into coalesced
    ``push_multi`` frames (up to ``max_keys``/``max_bytes`` per frame,
    the reference's 16-key push aggregation). Exactly ONE sender per
    shard: the per-shard push-seqno stream the server dedupes on stays
    strictly increasing in send order by construction."""

    def __init__(self, store, idx, max_keys=16, max_bytes=1 << 20,
                 start=True):
        self._store = store
        self._idx = idx
        self._max_keys = max(1, int(max_keys))
        self._max_bytes = max(1, int(max_bytes))
        self._cond = threading.Condition()
        self._heap = []         # (-priority, ticket, entry)
        self._ticket = 0
        self._inflight = 0      # queued + currently sending
        self._stopped = False
        self._thread = None
        if start:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="kvstore-send-%d" % idx)
            self._thread.start()

    def enqueue(self, entry, priority=0):
        with self._cond:
            if self._stopped:
                raise MXNetError(
                    "kvstore sender for shard %d is stopped" % self._idx)
            heapq.heappush(self._heap,
                           (-int(priority), self._ticket, entry))
            self._ticket += 1
            self._inflight += 1
            depth = self._inflight
            self._cond.notify()
        profiler.comm_record("push", inflight=depth)

    def _next_batch_locked(self):
        batch = [heapq.heappop(self._heap)[2]]
        nbytes = batch[0]["nbytes"]
        while (self._heap and len(batch) < self._max_keys
               and nbytes < self._max_bytes):
            entry = heapq.heappop(self._heap)[2]
            batch.append(entry)
            nbytes += entry["nbytes"]
        return batch

    def _run(self):
        while True:
            with self._cond:
                while not self._heap and not self._stopped:
                    self._cond.wait()
                if not self._heap:
                    return  # stopped and fully drained
                batch = self._next_batch_locked()
            err = None
            try:
                self._store._send_push_batch(self._idx, batch)
            except BaseException as e:
                err = e
            for entry in batch:
                entry["future"]._finish(err)
            if err is not None:
                self._store._note_async_error(err)
            with self._cond:
                self._inflight -= len(batch)
                self._cond.notify_all()

    def drain(self):
        """Block until the queue is empty and no frame is in flight."""
        with self._cond:
            while self._inflight:
                self._cond.wait()

    def stop(self):
        with self._cond:
            self._stopped = True
            self._cond.notify_all()


class ServerKVStore(kvstore.KVStore):
    """KVStore client speaking to KVStoreServer(s) (dist_async tier).

    Constructed by ``kvstore.create('dist_async')`` — either from a
    hand-set ``MXNET_PS_SERVER_URI`` or, in the scheduler topology, from
    the server URIs the tracker published at rendezvous (no env needed;
    see ``mxnet_tpu/tracker.py``). Subclasses :class:`kvstore.KVStore`
    (overriding every op with its RPC counterpart) so a preconstructed
    instance passes ``_create_kvstore``'s isinstance check and can be
    handed straight to ``Module.fit``/``init_optimizer`` like any other
    store. The optimizer runs SERVER-side (``set_optimizer``), so
    ``push`` sends raw gradients and ``pull`` returns updated weights —
    the reference's dist_async worker loop (kvstore_dist.h push/pull
    RPCs).

    With multiple servers, keys shard across them by a stable hash
    (the reference's ps-lite key-to-server assignment,
    kvstore_dist.h EncodeDefaultKey); every worker computes the same
    assignment, so per-key state lives on exactly one server.

    **Asynchronous pipelined data plane (ISSUE 4).** ``push`` enqueues
    onto the key's per-shard sender thread and returns immediately —
    priority-ordered (the engine PushAsync convention) and coalesced
    into multi-key frames — so layer N's gradient transfer overlaps
    layer N+1's backward and the other shards' RPCs. ``pull`` waits
    only on the futures of the keys it reads; ``barrier`` (and every
    state-moving op) drains the whole pipeline first, which is what
    keeps the PR 3 checkpoint/recovery choreography exact. Disable
    with ``MXNET_KVSTORE_PIPELINE=0`` (or ``pipeline=False``) for the
    strictly synchronous client. Retry/reconnect/seqno-dedupe behave
    identically in both modes: the single sender per shard preserves
    the strictly-increasing seqno stream the server dedupes on.
    """

    server_side = True  # Module: route updates through the server, not
    # the fused SPMD step (the server IS the update engine here)

    #: ops safe to retry over a fresh connection after a transport
    #: failure: pure reads, idempotent writes (init is first-writer-
    #: wins, set_optimizer is equality-checked, load_opt overwrites),
    #: and push — which carries a (client, seqno) pair the server
    #: dedupes on, so an applied-but-reply-lost push is acked, not
    #: double-applied. barrier/stop are deliberately NOT retried: a
    #: re-sent barrier arrival could double-count this worker.
    _RETRY_SAFE = frozenset((
        "init", "push", "push_multi", "pull", "pull_multi", "num_workers",
        "save_opt", "load_opt", "set_optimizer", "opt_config",
        # row_pull/mem are pure reads (ISSUE 14); row pushes ride the
        # ordinary push op and inherit its (cid, seq) dedupe
        "row_pull", "mem",
        # rollback is generation-deduped server-side (meta["gen"]), so a
        # lost-reply retry restores again (idempotent) without
        # re-applying the lr backoff
        "rollback"))

    def __init__(self, uri, kv_type="dist_async", tracker_client=None,
                 pipeline=None):
        super().__init__(kv_type)
        from . import tracker as _trk

        if isinstance(uri, str):
            uris = [u for u in uri.split(",") if u]
        else:
            uris = list(uri)
        if not uris:
            raise MXNetError("ServerKVStore: no server URIs")
        self._uris = uris
        self._socks = [_trk.connect_with_backoff(u, deadline=30.0)
                       for u in uris]
        self._wlocks = [threading.Lock() for _ in uris]
        self._tracker = tracker_client
        self._num_workers_cache = None
        # retry identity: a fresh uuid per client instance — dedupe
        # state must NOT survive a worker respawn (the respawn replays
        # from its checkpoint, its pushes are new work, not retries)
        self._client_id = uuid.uuid4().hex
        # per-shard sequence counters, advanced by _rpc_once under the
        # shard's send lock (sync path) or by the shard's single sender
        # thread (pipelined path): each server must observe ITS stream
        # of this client's pushes in strictly increasing send order
        self._push_seq = [0] * len(uris)
        self._rpc_retries = env_nonneg_int("MXNET_KVSTORE_RPC_RETRIES", 2)
        self._reconnect_deadline = env_positive_float(
            "MXNET_KVSTORE_RECONNECT_DEADLINE", 5)
        self._rediscover_timeout = env_positive_float(
            "MXNET_KVSTORE_REDISCOVER_TIMEOUT", 30)
        # -- async pipelined data plane (ISSUE 4 tentpole) ------------------
        if pipeline is None:
            raw = os.environ.get("MXNET_KVSTORE_PIPELINE")
            if raw in (None, ""):
                pipeline = True
            elif raw in ("0", "1"):
                pipeline = raw == "1"
            else:
                raise MXNetError(
                    "MXNET_KVSTORE_PIPELINE=%r must be 0 or 1" % raw)
        self._pipeline = bool(pipeline)
        self._coalesce_keys = env_nonneg_int(
            "MXNET_KVSTORE_COALESCE_KEYS", 16) or 1
        self._coalesce_bytes = env_nonneg_int(
            "MXNET_KVSTORE_COALESCE_BYTES", 1 << 20) or 1
        self._senders = {}            # shard idx -> _ShardSender (lazy)
        self._senders_lock = threading.Lock()
        self._key_pending = {}        # key -> [_PushFuture, ...]
        self._pending_lock = threading.Lock()
        self._async_error = None
        self._async_error_surfaced = False  # raised to the CALLER yet?
        self._residuals = {}          # key/(key, slice) -> ef residual
        self._closed = False
        # -- ZeRO value-sharding (ISSUE 7 mirror) ---------------------------
        # deliberately env-knob ONLY (no ctor override): the split rule
        # must be byte-identical on every client AND on a respawned
        # server's restore_from_checkpoint, and all of them read these
        # two knobs — a per-instance override would silently desync the
        # routing from recovery. Strictly validated even when inert (a
        # typo'd knob is a job misconfiguration, not a silent default).
        self._zero = (config.get_strict_bool("MXNET_TPU_ZERO_SERVER")
                      and len(self._socks) > 1)
        self._zero_min = config.get_nonneg_int("MXNET_TPU_ZERO_MIN_SIZE")
        self._zinfo = {}  # key -> (shape, dtype str, [per-server sizes])

    @property
    def num_workers(self):
        env = os.environ.get("MXNET_TPU_NUM_WORKERS",
                             os.environ.get("DMLC_NUM_WORKER"))
        if env is not None:
            return int(env)
        if self._num_workers_cache is None:
            # hand-set MXNET_PS_SERVER_URI with no DMLC env: the server
            # knows the worker count it gates barriers on — asking it
            # beats silently reporting 1
            self._num_workers_cache = int(self._rpc_idx(0, "num_workers"))
        return self._num_workers_cache

    @property
    def rank(self):
        if self._tracker is not None:
            return self._tracker.rank  # scheduler-assigned
        return int(os.environ.get("MXNET_TPU_WORKER_ID",
                                  os.environ.get("DMLC_RANK",
                                                 os.environ.get(
                                                     "DMLC_WORKER_ID",
                                                     "0"))))

    def num_dead_node(self, node_id=0, timeout=60):
        """Dead-peer count from the scheduler's heartbeat tracking
        (ref: kvstore.h:330-340); 0 when running without a tracker."""
        del node_id, timeout
        if self._tracker is None:
            return 0
        return self._tracker.num_dead_node()

    @property
    def num_servers(self):
        """Server (shard) count this client routes across."""
        return len(self._socks)

    def _shard(self, key):
        return shard_key(key, len(self._socks))

    def _rpc_once(self, idx, op, key, meta, wire, timeout):
        """One request/reply over the shard's current connection. A
        transport failure (reset, timeout, injected chaos drop) closes
        the connection — a late reply would otherwise be consumed as
        the NEXT op's reply — and raises _RPCTransportError; an
        ('err', ...) reply raises MXNetError (the server rejected the
        request: never retried)."""
        sock = None
        cop = _chaos_op(op)
        t0 = time.perf_counter()
        try:
            with self._wlocks[idx]:
                if op == "push" and meta is not None and "seq" not in meta:
                    # seqno allocated UNDER the shard's send lock, on
                    # the first attempt only (retries reuse it): if it
                    # were drawn outside, two threads could send their
                    # pushes in the opposite order and the server's
                    # dedupe would silently drop the lower seqno
                    meta["seq"] = self._push_seq[idx]
                    self._push_seq[idx] += 1
                sock = self._socks[idx]
                if chaos.rpc_fault(cop, phase="send"):
                    raise ConnectionResetError(
                        "chaos: dropped %r before send" % op)
                sock.settimeout(timeout)
                sent = _send_msg(sock, (op, key, meta, wire))
                if chaos.rpc_fault(cop, phase="reply"):
                    raise ConnectionResetError(
                        "chaos: dropped %r reply" % op)
                (status, payload), rcvd = _recv_msg(sock, with_size=True)
        except (socket.timeout, OSError, ConnectionError) as e:
            # close the CAPTURED socket, never the slot: a concurrent
            # thread's _reconnect may already have installed a fresh
            # one in self._socks[idx]
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            raise _RPCTransportError("%s: %s" % (type(e).__name__, e))
        profiler.comm_record(cop, wire_bytes=sent + rcvd,
                             seconds=time.perf_counter() - t0, count=1)
        if status != "ok":
            raise MXNetError("kvstore_server: %s" % (payload,))
        return payload

    def _reconnect(self, idx):
        """Fresh connection to shard ``idx``. When the old address is
        gone and a tracker is attached, re-discover the server list —
        a respawned server registered its NEW port with the scheduler
        (the takeover path in tracker.py), and get_server_uris blocks
        until every shard is alive again."""
        from . import tracker as _trk

        with self._wlocks[idx]:
            try:
                self._socks[idx].close()
            except OSError:
                pass
            try:
                self._socks[idx] = _trk.connect_with_backoff(
                    self._uris[idx], deadline=self._reconnect_deadline)
                return
            except _trk.TrackerError as e:
                if self._tracker is None:
                    raise _RPCTransportError(str(e))
            try:
                uris = self._tracker.get_server_uris(
                    timeout=self._rediscover_timeout)
            except _trk.TrackerError as e:
                raise _RPCTransportError("rediscovery failed: %s" % e)
            if len(uris) != len(self._uris):
                raise _RPCTransportError(
                    "rediscovery returned %d servers, expected %d"
                    % (len(uris), len(self._uris)))
            self._uris = list(uris)
            try:
                self._socks[idx] = _trk.connect_with_backoff(
                    self._uris[idx], deadline=self._reconnect_deadline)
            except _trk.TrackerError as e:
                raise _RPCTransportError(str(e))

    def _rpc_idx(self, idx, op, key=None, meta=None, wire=None,
                 timeout=60.0):
        """RPC with bounded retry (ISSUE 3 satellite): a transient
        connection reset during a retry-safe op reconnects — through
        tracker re-discovery when the shard was respawned on a new
        port — and re-sends the SAME request (same push seqno, so the
        server dedupes an already-applied one) instead of raising
        through Module.fit."""
        retries = self._rpc_retries if op in self._RETRY_SAFE else 0
        last = None
        for attempt in range(retries + 1):
            if attempt:
                try:
                    self._reconnect(idx)
                except _RPCTransportError as e:
                    last = e
                    continue
            try:
                return self._rpc_once(idx, op, key, meta, wire, timeout)
            except _RPCTransportError as e:
                last = e
        raise MXNetError(
            "kvstore_server rpc %r to shard %d (%s) failed after %d "
            "attempt(s): %s%s" % (
                op, idx, self._uris[idx], retries + 1, last,
                "" if retries else "; connection closed"))

    def _rpc(self, op, key=None, meta=None, wire=None):
        """Keyed data ops route to the key's shard; everything else
        goes to server 0 (single-server compatibility surface)."""
        if op in ("init", "push", "pull") and key is not None:
            return self._rpc_idx(self._shard(key), op, key, meta, wire)
        return self._rpc_idx(0, op, key, meta, wire)

    def _rpc_all(self, op, key=None, meta=None, wire=None, timeout=60.0):
        """Same op on every server, in rank order (deterministic across
        workers, so multi-server barriers cannot deadlock)."""
        return [self._rpc_idx(i, op, key, meta, wire, timeout=timeout)
                for i in range(len(self._socks))]

    @staticmethod
    def _np(value):
        return value.asnumpy() if hasattr(value, "asnumpy") \
            else np.asarray(value)

    def _merged(self, value):
        """A per-device list reduces to one array before crossing the
        wire (the local Comm::Reduce step of the reference worker)."""
        if isinstance(value, (list, tuple)):
            arrs = [self._np(v) for v in value]
            return arrs[0] if len(arrs) == 1 else np.sum(arrs, axis=0)
        return self._np(value)

    def init(self, key, value):
        for k, v in _iter_kv(key, value):
            arr = self._merged(v)
            if self._zero and zero_value_sharded(arr, len(self._socks),
                                                 self._zero_min):
                # value-sharded key: server i gets (and will forever
                # own) contiguous flat slice i — weights and the
                # optimizer state the updater grows for it both live
                # 1/num_servers per server. Every client computes the
                # same deterministic split, so the routing agrees.
                sizes = zero_slice_sizes(arr.size, len(self._socks))
                self._zinfo[k] = (tuple(arr.shape), str(arr.dtype), sizes)
                flat = np.ascontiguousarray(arr).reshape(-1)
                off = 0
                for idx, n in enumerate(sizes):
                    self._rpc_idx(idx, "init", k, None,
                                  _arr_to_wire(flat[off:off + n]))
                    off += n
                continue
            self._rpc("init", k, None, _arr_to_wire(arr))

    # -- async pipelined push/pull (ISSUE 4 tentpole) -----------------------
    def _check_async_error(self):
        err = self._async_error
        if err is not None:
            self._async_error_surfaced = True
            raise MXNetError(
                "kvstore: an earlier asynchronous push failed: %s" % err)

    def _note_async_error(self, err):
        if self._async_error is None:
            self._async_error = err

    def _sender(self, idx):
        with self._senders_lock:
            if self._closed:
                # close() stopped every existing sender; lazily spawning
                # a fresh one here would let a push on an untouched
                # shard burn the whole reconnect/retry budget against a
                # closed socket instead of failing fast like the shards
                # whose sender already existed
                raise MXNetError(
                    "kvstore is closed: its senders are stopped")
            sender = self._senders.get(idx)
            if sender is None:
                sender = self._senders[idx] = _ShardSender(
                    self, idx, max_keys=self._coalesce_keys,
                    max_bytes=self._coalesce_bytes)
            return sender

    def _send_push_batch(self, idx, batch):
        """Runs on shard ``idx``'s single sender thread: allocate the
        per-shard push seqnos in send order (the server's dedupe stream
        must be strictly increasing; retries reuse their seqno), then
        ONE rpc for the whole batch — a coalesced ``push_multi`` frame
        when more than one push was queued."""
        for entry in batch:
            if "seq" not in entry["meta"]:
                entry["meta"]["seq"] = self._push_seq[idx]
                self._push_seq[idx] += 1
        if len(batch) == 1:
            entry = batch[0]
            self._rpc_idx(idx, "push", entry["key"], entry["meta"],
                          entry["wire"])
        else:
            self._rpc_idx(idx, "push_multi", None, None,
                          [(e["key"], e["meta"], e["wire"])
                           for e in batch])

    def _wait_key(self, k):
        """Block on exactly the futures ``k`` depends on: the async
        pushes of this key. Other keys' RPCs keep flowing meanwhile —
        that is the pipeline."""
        with self._pending_lock:
            futs = self._key_pending.pop(k, ())
        for f in futs:
            try:
                f.wait()
            except BaseException:
                self._async_error_surfaced = True
                raise

    def wait_outstanding(self):
        """Drain the async pipeline: block until every enqueued push
        has been sent and acked (or failed its retry budget), then
        surface the first failure."""
        with self._senders_lock:
            senders = [self._senders[i] for i in sorted(self._senders)]
        for sender in senders:
            sender.drain()
        with self._pending_lock:
            pending, self._key_pending = self._key_pending, {}
        for futs in pending.values():
            for f in futs:
                try:
                    f.wait()
                except BaseException:
                    self._async_error_surfaced = True
                    raise
        self._check_async_error()

    def push(self, key, value, priority=0):
        """Enqueue onto the key's shard sender and return immediately
        (async engine semantics — the reference's PushAsync with its
        priority argument honored). The (cid, seq) pair still makes
        every push idempotent under retry: a reply lost in transit is
        re-sent with the SAME seqno and the server acks without
        re-applying. With compression configured, dense float grads
        quantize client-side (jitted, error-feedback residual) and only
        the ~16x-smaller packed payload crosses the wire; row-sparse
        values stay uncompressed (ref parity, kvstore_dist.h
        EncodeCompressedKey vs EncodeRowSparseKey)."""
        self._check_async_error()
        from .ndarray.sparse import RowSparseNDArray

        for k, v in _iter_kv(key, value):
            v0 = v[0] if isinstance(v, (list, tuple)) and len(v) else v
            arr = self._merged(v)
            is_rsp = isinstance(v0, RowSparseNDArray)
            zinfo = None if is_rsp else self._zinfo.get(k)
            profiler.comm_record("push", raw_bytes=int(arr.nbytes))
            if zinfo is not None:
                # scatter-push (the reduce-scatter mirror): slice i of
                # the flattened gradient goes to server i, which updates
                # its 1/num_servers weight+state slice on arrival. Each
                # slice keeps its own error-feedback residual — the
                # residual memory is 1/N per (client, server) pair too.
                _shape, _dt, sizes = zinfo
                flat = np.ascontiguousarray(np.asarray(arr)).reshape(-1)
                off = 0
                for idx, n in enumerate(sizes):
                    sl = flat[off:off + n]
                    off += n
                    compressed = None
                    if (self._compression_params is not None
                            and np.issubdtype(sl.dtype, np.floating)):
                        threshold = self._compression_params["threshold"]
                        packed, self._residuals[(k, idx)] = \
                            two_bit_quantize(
                                sl, self._residuals.get((k, idx)),
                                threshold)
                        compressed = (packed, threshold)
                    self._push_shard(idx, k, sl, compressed, priority)
                continue
            compressed = None
            if (self._compression_params is not None and not is_rsp
                    and np.issubdtype(arr.dtype, np.floating)):
                threshold = self._compression_params["threshold"]
                packed, self._residuals[k] = two_bit_quantize(
                    arr, self._residuals.get(k), threshold)
                compressed = (packed, threshold)
            self._push_shard(self._shard(k), k, arr, compressed, priority)

    def _push_shard(self, idx, k, arr, compressed, priority):
        """One key's (slice) push to one shard: synchronous RPC on the
        MXNET_KVSTORE_PIPELINE=0 fallback, else enqueued onto the
        shard's single sender thread."""
        if not self._pipeline:
            self._rpc_idx(idx, "push", k, {"cid": self._client_id},
                          _grad_to_wire(arr, compressed))
            return
        if compressed is None and arr.flags.writeable:
            # snapshot: the caller may overwrite its gradient
            # buffer before the sender thread ships it. Read-only
            # arrays (numpy views of immutable jax buffers — the
            # Module path) and packed payloads are already stable.
            arr = np.array(arr, copy=True)
        entry = {"key": k, "meta": {"cid": self._client_id},
                 "wire": _grad_to_wire(arr, compressed),
                 "nbytes": int(compressed[0].nbytes if compressed
                               else arr.nbytes),
                 "future": _PushFuture()}
        with self._pending_lock:
            self._key_pending.setdefault(k, []).append(entry["future"])
        try:
            self._sender(idx).enqueue(entry, priority)
        except BaseException as e:
            # a never-enqueued future must still complete, or a
            # later pull/wait on this key would block forever
            entry["future"]._finish(e)
            raise

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        from .base import MXNetError

        if out is None:
            raise MXNetError("kvstore.pull requires out=")
        self._check_async_error()
        pairs = list(_iter_kv(key, out))
        # wait only on the futures this pull depends on — the async
        # pushes of exactly these keys (layer N's weight pull overlaps
        # layer N+1's gradient RPCs and every other shard's traffic)
        for k, _o in pairs:
            self._wait_key(k)
        # per-shard request lists; value-sharded keys gather from EVERY
        # shard (the all-gather mirror) but still ride the same one
        # multi-key frame per shard as everything else
        reqs = [[] for _ in self._socks]
        seen = set()
        for k, _o in pairs:
            if k in seen:
                continue
            seen.add(k)
            if k in self._zinfo:
                for idx in range(len(self._socks)):
                    reqs[idx].append(k)
            else:
                reqs[self._shard(k)].append(k)
        fetched = {}  # (shard idx, key) -> array
        for idx, ks in enumerate(reqs):
            if not ks:
                continue
            if len(ks) == 1:
                wires = [self._rpc_idx(idx, "pull", ks[0])]
            else:
                # one multi-key frame per shard instead of a round
                # trip per key
                wires = self._rpc_idx(idx, "pull_multi", ks)
            for k, w in zip(ks, wires):
                fetched[(idx, k)] = _arr_from_wire(w)
        for k, o in pairs:
            if k in self._zinfo:
                shape, _dt, sizes = self._zinfo[k]
                arr = np.concatenate(
                    [fetched[(i, k)].reshape(-1)
                     for i in range(len(self._socks))]).reshape(shape)
            else:
                arr = fetched[(self._shard(k), k)]
            targets = o if isinstance(o, (list, tuple)) else [o]
            for t in targets:
                t[:] = arr

    def _pull_full(self, k):
        """One key's full current value (gathering value-sharded slices
        when needed) — the single-key read shared by row_sparse_pull."""
        if k not in self._zinfo:
            return _arr_from_wire(self._rpc("pull", k))
        shape, _dt, sizes = self._zinfo[k]
        parts = [
            _arr_from_wire(self._rpc_idx(idx, "pull", k)).reshape(-1)
            for idx in range(len(self._socks))]
        return np.concatenate(parts).reshape(shape)

    # lr schedulers representable as plain wire data: class name ->
    # (ctor_param, instance_attr) pairs (ref lr_scheduler.py signatures)
    _SCHED_WIRE = {
        "FactorScheduler": (("step", "step"), ("factor", "factor"),
                            ("stop_factor_lr", "stop_factor_lr"),
                            ("base_lr", "base_lr")),
        "MultiFactorScheduler": (("step", "step"), ("factor", "factor"),
                                 ("base_lr", "base_lr")),
        # base_lr maps from base_lr_orig: Optimizer.__init__ mutates
        # .base_lr to learning_rate, but PolyScheduler decays from the
        # ctor-time base_lr_orig snapshot — shipping the mutated value
        # would rebuild a schedule decaying from the wrong anchor
        "PolyScheduler": (("max_update", "max_update"), ("pwr", "power"),
                          ("base_lr", "base_lr_orig")),
        "LRScheduler": (("base_lr", "base_lr"),),
    }

    @classmethod
    def _opt_extras(cls, opt):
        """Serialize the non-scalar optimizer config that IS
        representable as plain wire data (lr_mult/wd_mult/idx2name and
        the stock lr schedulers); warn loudly about what is not
        (param_dict holds live Parameter objects, custom scheduler
        subclasses hold arbitrary state). These used to be silently
        dropped — the server then trained with the wrong per-parameter
        learning rates."""
        extras, dropped = {}, []
        if opt.idx2name:
            extras["idx2name"] = dict(opt.idx2name)
        if opt.lr_mult:
            extras["lr_mult"] = dict(opt.lr_mult)
        if opt.wd_mult:
            extras["wd_mult"] = dict(opt.wd_mult)
        if opt.lr_scheduler is not None:
            spec = cls._SCHED_WIRE.get(type(opt.lr_scheduler).__name__)
            if spec is not None and type(opt.lr_scheduler).__module__ \
                    .endswith("lr_scheduler"):
                extras["lr_scheduler"] = (
                    type(opt.lr_scheduler).__name__,
                    {ctor: getattr(opt.lr_scheduler, attr)
                     for ctor, attr in spec})
            else:
                dropped.append("lr_scheduler (%s is not a stock "
                               "mxnet_tpu.lr_scheduler class)"
                               % type(opt.lr_scheduler).__name__)
        if opt.param_dict:
            dropped.append("param_dict (live Parameter objects cannot "
                           "cross the data-only wire)")
        if dropped:
            warnings.warn(
                "ServerKVStore.set_optimizer: DROPPING %s — the "
                "server-side optimizer will run without it. Fold the "
                "equivalent config into lr_mult/wd_mult or a stock "
                "lr scheduler." % "; ".join(dropped), stacklevel=3)
        return extras

    def set_optimizer(self, optimizer_or_name, **kwargs):
        """Install the server-side optimizer on every server (ref: the
        worker sends its serialized optimizer to every server,
        kvstore.cc set_optimizer). Accepts a name + kwargs or an
        Optimizer instance — its scalar hyperparameters (matched
        against the subclass __init__ signature) travel, and so do
        lr_mult/wd_mult/idx2name and stock lr schedulers (as plain wire
        data). What cannot be represented (param_dict, custom scheduler
        classes) is dropped with a loud warning, never silently."""
        extras = {}
        if isinstance(optimizer_or_name, str):
            name, kw = optimizer_or_name, kwargs
        else:
            import inspect

            opt = optimizer_or_name
            name = type(opt).__name__.lower()
            kw = dict(kwargs)
            for klass in type(opt).__mro__:           # subclass kwargs ride
                if not hasattr(klass, "__init__"):    # **kwargs to the base
                    continue
                try:
                    params = inspect.signature(klass.__init__).parameters
                except (TypeError, ValueError):
                    continue
                for p in params:
                    attr = "lr" if p == "learning_rate" else p
                    if p in ("self", "args", "kwargs") \
                            or not hasattr(opt, attr):
                        continue
                    v = getattr(opt, attr)
                    if isinstance(v, (int, float, str, bool)):
                        kw.setdefault(p, v)
            extras = self._opt_extras(opt)
        self._rpc_all("set_optimizer", name,
                      {"kwargs": kw, "extras": extras})

    def set_updater(self, updater):
        """The optimizer runs SERVER-side on this tier; a client-side
        updater would never be consulted by push(). Fail fast instead
        of silently training with the wrong update rule (the base
        class would just store it)."""
        raise MXNetError(
            "ServerKVStore applies updates server-side: use "
            "set_optimizer(name, **kwargs), not a client updater")

    _set_updater = set_updater

    def set_gradient_compression(self, compression_params):
        """Wire-level 2-bit compression (ISSUE 4): dense float pushes
        quantize client-side with a persistent error-feedback residual
        (kvstore.two_bit_quantize, jitted), the packed payload crosses
        the wire tagged with dtype/shape/threshold, and the server
        dequantizes before applying its optimizer. Validation is loud:
        unknown keys and non-finite thresholds raise."""
        self._compression_params = validate_compression_params(
            compression_params)
        self._residuals = {}

    def comm_stats(self, reset=False):
        """Per-op comms counters for this process's data plane: raw vs
        wire bytes, RPC count/latency, max in-flight depth (the ISSUE 4
        observability surface; process-wide via mxnet_tpu.profiler)."""
        return profiler.comm_stats(reset=reset)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        """Server-side optimizer state -> local file (the
        update_on_kvstore branch of Module.save_optimizer_states,
        module.py:475). State crosses the wire as tagged plain data
        (_state_to_wire); the file keeps the reference's
        pickle-of-numpy-map format, so it interoperates with
        Updater.get_states checkpoints. With sharded servers the
        per-server maps of key-sharded keys are disjoint by
        construction and merge into one file; value-sharded (ZeRO)
        keys' per-server state SLICES are reassembled into the full
        logical arrays first, so the file is server-count independent —
        a reload under a different topology re-splits it."""
        self.wait_outstanding()
        per_server = [
            {k: _state_from_wire(w) for k, w in wire}
            for wire in self._rpc_all("save_opt")]
        states_map = {}
        zparts = {}
        for idx, smap in enumerate(per_server):
            for k, v in smap.items():
                if k in self._zinfo:
                    zparts.setdefault(k, {})[idx] = v
                else:
                    states_map[k] = v
        for k, parts in zparts.items():
            if len(parts) != len(per_server):
                warnings.warn(
                    "save_optimizer_states: value-sharded key %r has "
                    "state on %d of %d servers (no push reached the "
                    "others yet); skipping it" % (k, len(parts),
                                                  len(per_server)),
                    stacklevel=2)
                continue
            states_map[k] = self._zero_join_state(
                k, [parts[i] for i in range(len(per_server))])
        # tmp-fsync-rename (ISSUE 3 satellite): a crash mid-write must
        # never leave a torn file that load_optimizer_states half-parses
        atomic_write_bytes(fname, pickle.dumps(states_map, protocol=4))

    def _zero_join_state(self, k, parts):
        """Per-server state slices → one logical state pytree: array
        leaves concatenate in server order and reshape to the key's
        shape; scalar/None leaves (identical on every server) pass
        through from the first."""
        shape, _dt, _sizes = self._zinfo[k]
        total = 1
        for d in shape:
            total *= int(d)

        def join(*leaves):
            l0 = leaves[0]
            if isinstance(l0, np.ndarray):
                flat = np.concatenate(
                    [np.asarray(l).reshape(-1) for l in leaves])
                return flat.reshape(shape) if flat.size == total else flat
            if isinstance(l0, (list, tuple)):
                return type(l0)(join(*grp) for grp in zip(*leaves))
            return l0

        return join(*parts)

    def _zero_split_state(self, k, state):
        """One logical state pytree → per-server slices (the inverse of
        :meth:`_zero_join_state`, via the shared
        :func:`zero_slice_pytree` routine): full-size array leaves
        split by this topology's slice table — which is how a file
        saved under a DIFFERENT server count re-splits on load — and
        everything else replicates."""
        _shape, _dt, sizes = self._zinfo[k]
        return [zero_slice_pytree(state, sizes, idx)
                for idx in range(len(sizes))]

    def get_optimizer_config(self):
        """The server-side optimizer's plain-data config
        ``(name, kwargs, extras)`` (or None before set_optimizer) —
        recorded in checkpoints so a respawned server can rebuild its
        updater before the first retried push arrives."""
        return self._rpc_idx(0, "opt_config")

    def load_optimizer_states(self, fname):
        """Local file -> server-side optimizer state. The local
        checkpoint is unpickled HERE, client-side, with the same trust
        as any locally-loaded checkpoint file — what crosses the wire
        is the tagged plain-data encoding, which the server decodes
        without ever unpickling peer bytes."""
        from .checkpoint import unwrap_states_map

        self.wait_outstanding()
        with open(fname, "rb") as f:
            states_map = unwrap_states_map(pickle.loads(f.read()))
        by_server = [[] for _ in self._socks]
        for k, v in states_map.items():
            if k in self._zinfo:
                # value-sharded key: the file holds the full logical
                # state — re-split it for THIS topology's slice table
                # (server-count independence on reload)
                for idx, part in enumerate(self._zero_split_state(k, v)):
                    by_server[idx].append((k, _state_to_wire(part)))
            else:
                by_server[self._shard(k)].append((k, _state_to_wire(v)))
        for idx, pairs in enumerate(by_server):
            self._rpc_idx(idx, "load_opt", wire=pairs)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Dense-backed row_sparse_pull (the server stores dense
        weights): fetch the full value once, then materialize the
        requested rows per out, matching kvstore_local.h PullRowSparse
        semantics (unique-sorted ids)."""
        from .base import MXNetError

        if out is None or row_ids is None:
            raise MXNetError("row_sparse_pull requires out= and row_ids=")
        self._check_async_error()
        from .ndarray import ndarray as nd
        from .ndarray.sparse import RowSparseNDArray

        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        for k, o in _iter_kv(key, out):
            self._wait_key(k)  # this key's async pushes land first
            w = self._pull_full(k)
            targets = o if isinstance(o, (list, tuple)) else [o]
            # per-key broadcast: computed fresh inside the loop — the
            # old `rids = list(rids) * len(targets)` rebinding leaked a
            # grown list into every subsequent key's iteration
            if len(rids) == 1 and len(targets) > 1:
                key_rids = list(rids) * len(targets)
            else:
                key_rids = list(rids)
            for t, rid in zip(targets, key_rids):
                ids = np.unique(np.asarray(
                    rid.asnumpy() if hasattr(rid, "asnumpy") else rid,
                    np.int64))
                if ids.size and (ids[0] < 0 or ids[-1] >= w.shape[0]):
                    # clipping silently returned the LAST row's data for
                    # any out-of-range id — wrong values are worse than
                    # an error (kvstore_local.h asserts the same bound)
                    raise MXNetError(
                        "row_sparse_pull: row_ids out of range for key "
                        "%r: [%d, %d] vs %d rows"
                        % (k, int(ids[0]), int(ids[-1]), w.shape[0]))
                taken = nd.array(w[ids])
                if isinstance(t, RowSparseNDArray):
                    newo = RowSparseNDArray(taken, nd.array(ids),
                                            w.shape, ctx=t.ctx)
                    t._rebind_sparse(newo)
                else:
                    dense = np.zeros(w.shape, w.dtype)
                    dense[ids] = w[ids]
                    t[:] = dense

    # -- embedding row data plane (ISSUE 14) --------------------------------
    def row_pull(self, server_idx, key, ids):
        """Pull exactly the rows ``ids`` of the dense (sub-)table
        ``key`` stored on server ``server_idx``. Waits for this key's
        in-flight async pushes first (read-your-writes), then one
        row_pull RPC whose wire carries only the requested rows —
        never the whole table. Returns the ``(len(ids), ...)`` numpy
        block in request order. Range validation happens at the CALLER
        (embedding/table.py raises the typed EmbeddingShardError
        against the table's vocabulary before any routing); the server
        re-checks against its stored sub-table as defense in depth."""
        self._check_async_error()
        self._wait_key(key)
        ids = np.ascontiguousarray(np.asarray(ids), dtype=np.int64)
        wire = self._rpc_idx(int(server_idx), "row_pull", key,
                             {"ids": _arr_to_wire(ids)})
        return _arr_from_wire(wire)

    def row_push(self, server_idx, key, ids, values, priority=0,
                 compressed=None):
        """Push a row-granular gradient scatter for ``key`` on server
        ``server_idx`` — (local row ids, per-row value block) — on the
        SAME async per-shard sender pipeline as every dense push:
        priority-ordered, coalesced into push_multi frames, (cid, seq)
        deduped under retry, failures sticky until the next wait
        point. ``compressed`` is an optional ``(packed, threshold)``
        pair from two_bit_quantize applied to the value block."""
        self._check_async_error()
        ids = np.ascontiguousarray(np.asarray(ids), dtype=np.int64)
        values = np.asarray(values)
        if self._pipeline and compressed is None \
                and values.flags.writeable:
            # snapshot: the wire holds a zero-copy view and the caller
            # may reuse its gradient buffer before the sender ships it
            # (the _push_shard rule)
            values = np.array(values, copy=True)
        wire = _rows_to_wire(ids, values, compressed)
        nbytes = int(ids.nbytes) + int(
            compressed[0].nbytes if compressed else values.nbytes)
        profiler.comm_record("push", raw_bytes=int(ids.nbytes
                                                   + values.nbytes))
        if not self._pipeline:
            self._rpc_idx(int(server_idx), "push", key,
                          {"cid": self._client_id}, wire)
            return
        entry = {"key": key, "meta": {"cid": self._client_id},
                 "wire": wire, "nbytes": nbytes,
                 "future": _PushFuture()}
        with self._pending_lock:
            self._key_pending.setdefault(key, []).append(entry["future"])
        try:
            self._sender(int(server_idx)).enqueue(entry, priority)
        except BaseException as e:
            entry["future"]._finish(e)
            raise

    def server_memory(self):
        """Per-server measured memory ({keys, store_bytes, opt_bytes,
        embed_store_bytes, embed_opt_bytes} per server, in rank order)
        — the 1/num_servers acceptance evidence reads this surface."""
        self.wait_outstanding()
        return self._rpc_all("mem")

    def barrier(self, name=""):
        """Barrier across workers, held at every server in rank order
        (same visit order on every worker, so sharded barriers cannot
        interleave into a deadlock). The server aborts the round with
        an error — raised here — when a peer dies or its overall
        timeout (MXNET_KVSTORE_BARRIER_TIMEOUT) expires. ``name``
        scopes the round: arrivals at different names never pair (the
        checkpoint choreography names its three phases so a respawned
        worker replaying phase A cannot release a survivor's phase B).
        Drains the async pipeline first: a worker inside the barrier
        has NO push in flight (the checkpoint quiesce window and the
        PR 3 recovery invariants depend on exactly this)."""
        self.wait_outstanding()
        bt = env_positive_float("MXNET_KVSTORE_BARRIER_TIMEOUT", 120)
        self._rpc_all("barrier", key=name or None, timeout=bt + 30.0)

    def reset_gradient_residuals(self):
        """Drop this client's 2-bit error-feedback residuals. EVERY
        rank must call this across a rollback (HealthGuard does,
        inside the quiesced window): the accumulated error refers to
        pre-rollback weights, and a NaN-contaminated residual would
        otherwise quantize that rank's pushes to all-zero codes
        forever."""
        self.wait_outstanding()
        self._residuals = {}

    def rollback_servers(self, lr_scale=None, gen=None):
        """Tell EVERY server to reload its shard (weights + optimizer
        state) from the newest committed checkpoint in its own
        ``MXNET_CHECKPOINT_DIR`` and back off the server-side learning
        rate — the coordinated-rollback RPC of the ISSUE 9 health
        guard. Call only inside a quiesced barrier window (HealthGuard
        does); rank 0 issues it for the job, and every rank separately
        calls :meth:`reset_gradient_residuals`. ``gen`` (the guard's
        rollback count) makes the lr backoff retry-safe: the server
        applies it at most once per generation."""
        self.reset_gradient_residuals()
        meta = {}
        if lr_scale is not None:
            meta["lr_scale"] = float(lr_scale)
        if gen is not None:
            meta["gen"] = int(gen)
        infos = [i for i in self._rpc_all("rollback", meta=meta) if i]
        if not infos:
            raise MXNetError("rollback_servers: no server reported a "
                             "restore")
        return {"keys": sum(int(i.get("keys", 0)) for i in infos),
                "epoch": infos[0].get("epoch"),
                "lr": infos[0].get("lr")}

    def stop_server(self):
        self.wait_outstanding()
        self._rpc_all("stop")

    def close(self):
        surfaced = self._async_error_surfaced
        try:
            self.wait_outstanding()
        except Exception as e:
            # teardown must not raise — but a failure whose FIRST wait
            # point is close() would otherwise vanish with exit code 0
            # and silently lost gradients: make it loud
            if not surfaced:
                warnings.warn(
                    "kvstore close(): undelivered async push failure: "
                    "%s" % e, stacklevel=2)
        with self._senders_lock:
            self._closed = True
            for sender in self._senders.values():
                sender.stop()
        if self._tracker is not None:
            self._tracker.done()
        for sock in self._socks:
            try:
                sock.close()
            except OSError:
                pass


def _iter_kv(key, value):
    """Pair keys with values. A single key takes the WHOLE value (which
    may be a per-device list); a key list zips positionally."""
    if isinstance(key, (list, tuple)):
        for k, v in zip(key, value):
            yield str(k), v
    else:
        yield str(key), value


# ---------------------------------------------------------------------------
# entry point (DMLC_ROLE dispatch)
# ---------------------------------------------------------------------------
def _init_kvstore_server_module():
    role = os.environ.get("DMLC_ROLE", "worker").lower()
    if role not in ("server", "scheduler"):
        return
    from . import tracker as trk

    if role == "scheduler":
        # scheduler topology: run the tracker rendezvous loop (ref: the
        # dmlc tracker's scheduler node). Without the env contract the
        # shim exits 0 so reference launch scripts keep working.
        if trk.tracker_env_spec() is not None:
            sys.exit(trk.main())
        sys.exit(0)
    if os.environ.get("MXNET_KVSTORE_SERVER") == "1":
        spec = trk.tracker_env_spec()
        # multi-host topology (scheduler on another host): bind the
        # wildcard so remote workers can reach us, and advertise a
        # routable address — publishing the loopback bind would strand
        # every remote worker in connect retries
        multi_host = spec is not None and \
            spec[0].rsplit(":", 1)[0] not in ("127.0.0.1", "localhost")
        host = os.environ.get("MXNET_PS_BIND_HOST",
                              "" if multi_host else "127.0.0.1")
        # scheduler topology: DMLC_PS_ROOT_PORT is the SCHEDULER's port
        # (never bind it); manual MXNET_PS_SERVER_URI deployments keep
        # the pre-tracker fallback of binding the root port directly
        default_port = "0" if spec is not None \
            else os.environ.get("DMLC_PS_ROOT_PORT", "0")
        port = int(os.environ.get("MXNET_PS_BIND_PORT", default_port) or 0)
        nw = int(os.environ.get("MXNET_TPU_NUM_WORKERS",
                                os.environ.get("DMLC_NUM_WORKER", "1")))
        server = KVStoreServer(host=host, port=port, num_workers=nw)
        # elastic respawn + full-job restart (ISSUE 3): a server boots
        # from the latest checkpoint whenever one exists — BEFORE
        # registering with the scheduler, so workers re-discover the
        # URI only once the store already holds the restored weights +
        # optimizer state. Keyed on the DIRECTORY, not the restart
        # count: on a whole-job relaunch (DMLC_RESTART_COUNT resets to
        # 0) the workers resume at epoch N from the same directory, and
        # a server that started empty would let their init() install
        # fresh random weights under the resumed epoch counter.
        restart = trk.env_nonneg_int("DMLC_RESTART_COUNT", 0)
        ckpt_dir = os.environ.get("MXNET_CHECKPOINT_DIR")
        restored_from = None
        if ckpt_dir:
            from .checkpoint import CheckpointManager

            ck = CheckpointManager(ckpt_dir).latest()
            if ck is not None:
                # validated reads: a typo'd shard identity would
                # silently restore the WRONG shard (empty store ->
                # 'push before init' on every surviving worker)
                shard_rank = trk.env_nonneg_int("DMLC_SERVER_ID", 0)
                num_shards = max(
                    trk.env_nonneg_int("DMLC_NUM_SERVER", 1), 1)
                nkeys = server.restore_from_checkpoint(
                    ck, shard_rank=shard_rank, num_shards=num_shards)
                restored_from = ck.path
                print("[lifecycle] event=restored-from role=server "
                      "rank=%d ckpt=%s keys=%d epoch=%d"
                      % (shard_rank, ck.path, nkeys, ck.epoch), flush=True)
            elif restart > 0:
                print("kvstore_server: restart %d but no checkpoint in "
                      "%s; starting empty" % (restart, ckpt_dir),
                      flush=True)
        client = None
        if spec is not None:
            advertise = os.environ.get("MXNET_PS_ADVERTISE_HOST")
            if advertise is None and multi_host:
                # the outbound interface toward the scheduler is the
                # address workers can route back to (UDP connect does
                # no I/O — it only resolves the local endpoint)
                sched_host, sched_port = spec[0].rsplit(":", 1)
                probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                try:
                    probe.connect((sched_host, int(sched_port)))
                    advertise = probe.getsockname()[0]
                finally:
                    probe.close()
            bound_port = server.addr.rsplit(":", 1)[1]
            addr = "%s:%s" % (advertise, bound_port) if advertise \
                else server.addr
            # publish this server's URI to the scheduler; workers
            # discover it at kvstore.create('dist_async') rendezvous.
            # The scheduler's shutdown fan-out sends the 'stop' op
            # here once every worker reports done. A respawn registers
            # with its old rank (DMLC_SERVER_ID) + restart count, so
            # the scheduler swaps the dead node's URI for this one.
            server_rank = os.environ.get("DMLC_SERVER_ID")
            client = trk.TrackerClient(
                spec[0], "server", addr=addr,
                rank=int(server_rank) if server_rank is not None else None,
                restart_count=restart)
            if restored_from is not None:
                client.log_event("restored-from", role="server",
                                 rank=server_rank or "0",
                                 ckpt=restored_from)
        print("kvstore_server listening on %s" % server.addr, flush=True)
        server.serve_forever()
        if client is not None:
            client.done()  # graceful stop: log 'done', not 'dead'
            client.close()
        sys.exit(0)
    # serverless tier: nothing to run (see module docstring)
    sys.exit(0)


if __name__ == "__main__":
    _init_kvstore_server_module()
