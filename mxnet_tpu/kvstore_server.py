"""KVStore parameter server: a real server-side-optimizer tier, plus
the serverless-parity shim.

Reference counterpart: ``python/mxnet/kvstore_server.py`` (the server
main loop driven by DMLC_ROLE=server) and ``kvstore_dist_server.h``
(merge buffers + server-executed optimizer, :113-500).

Two tiers, chosen by configuration:

1. **Serverless (TPU default).** Aggregation is an XLA all-reduce over
   the device mesh and the optimizer runs replicated on workers (see
   kvstore.DistKVStore, parallel/spmd.py zero=True). A process started
   with DMLC_ROLE=server/scheduler and no server opt-in exits 0 so
   reference launch scripts keep working — the jax coordinator (spawned
   inside worker 0) already plays the scheduler's rendezvous role.

2. **Real server (``MXNET_KVSTORE_SERVER=1``).** ``KVStoreServer``
   holds the weights, applies pushes through a server-side optimizer
   (exactly the reference's dist_async contract: each worker's push is
   applied when it arrives — no global synchronisation — and pulls
   return the freshest weights), and answers pulls/barriers over a
   length-prefixed TCP protocol. ``kvstore.create('dist_async')``
   connects to it when ``MXNET_PS_SERVER_URI`` is set (see
   ``ServerKVStore``). This is the behavioral equivalent of the
   reference's server-side-optimizer mode, runnable on CPU hosts.

Protocol: 4-byte big-endian length + payload. Payloads are tuples
``(op, key, meta, raw_bytes)`` encoded with pickle but decoded by a
restricted unpickler — arrays travel as (dtype, shape, bytes), never
as pickled objects, and the unpickler refuses every global lookup.
Like the reference's ps-lite transport this is an in-cluster protocol
with no auth; do not expose the port beyond the job.
"""
from __future__ import annotations

import os
import pickle
import socket
import sys
import threading
import time
import warnings
import zlib

import numpy as np

from . import kvstore
from .base import MXNetError


# ---------------------------------------------------------------------------
# wire helpers — the framing + restricted unpickler are SHARED with the
# tracker protocol (tracker.py is stdlib-only, so this import is
# cycle-free): one hardening surface, not two drifting copies
# ---------------------------------------------------------------------------
from .tracker import (_SafeUnpickler, _pack, _recv_exact,  # noqa: F401
                      _recv_msg, _send_msg, _unpack)


def _arr_to_wire(a):
    a = np.ascontiguousarray(a)
    return (str(a.dtype), a.shape, a.tobytes())


def _arr_from_wire(w):
    dtype, shape, raw = w
    return np.frombuffer(raw, dtype=dtype).reshape(shape).copy()


def _state_to_wire(v):
    """Optimizer-state pytree -> tagged plain data. Arrays travel as
    (dtype, shape, bytes) like every other tensor on this protocol —
    never as a pickle blob (``load_opt`` used to feed network bytes to
    ``pickle.loads`` via Updater.set_states, contradicting the module's
    no-globals guarantee)."""
    if v is None:
        return ("none",)
    if isinstance(v, (bool, int, float, str)):
        return ("py", v)
    if isinstance(v, (list, tuple)):
        tag = "list" if isinstance(v, list) else "tuple"
        return (tag, [_state_to_wire(i) for i in v])
    arr = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
    return ("nd",) + _arr_to_wire(arr)


def _state_from_wire(w):
    tag = w[0]
    if tag == "none":
        return None
    if tag == "py":
        return w[1]
    if tag == "list":
        return [_state_from_wire(i) for i in w[1]]
    if tag == "tuple":
        return tuple(_state_from_wire(i) for i in w[1])
    if tag == "nd":
        return _arr_from_wire(w[1:])
    raise ValueError("bad optimizer-state wire tag %r" % (tag,))


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------
class KVStoreServer:
    """Weights + server-side optimizer behind a TCP endpoint.

    Mirrors kvstore_dist_server.h semantics: ``init`` is first-writer-
    wins, each ``push`` is applied on arrival under the server's
    updater (optimizer state lives server-side, keyed like the
    reference's per-key store), ``pull`` returns the current weights,
    ``barrier`` blocks until every worker arrives. dist_async = push
    without waiting for the barrier.
    """

    def __init__(self, host="127.0.0.1", port=0, num_workers=1,
                 barrier_timeout=None):
        self._store = {}
        self._updater = None
        self._opt_config = None
        self._lock = threading.Lock()
        self._num_workers = num_workers
        self._barrier_cond = threading.Condition()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_errors = {}   # gen -> abort message
        if barrier_timeout is None:
            barrier_timeout = float(os.environ.get(
                "MXNET_KVSTORE_BARRIER_TIMEOUT", "120"))
        self._barrier_timeout = float(barrier_timeout)
        self._conns = set()
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.addr = "%s:%d" % self._sock.getsockname()[:2]

    # -- op handlers --------------------------------------------------------
    def _apply_push(self, key, grad):
        with self._lock:
            if key not in self._store:
                raise KeyError("push before init: %r" % (key,))
            if self._updater is None:
                self._store[key] += grad
            else:
                from .ndarray import array

                w = array(self._store[key])
                self._updater(key, array(grad), w)
                self._store[key] = w.asnumpy()

    def _set_optimizer(self, name, meta):
        from . import optimizer

        # meta is {"kwargs": ..., "extras": ...}; a bare kwargs dict
        # (older clients) is accepted as-is
        meta = meta or {}
        if "kwargs" in meta or "extras" in meta:
            kwargs = meta.get("kwargs") or {}
            extras = meta.get("extras") or {}
        else:
            kwargs, extras = meta, {}
        with self._lock:
            if self._opt_config is not None:
                # first-writer-wins, like init: every worker's
                # init_optimizer sends the config (module.py:349 has no
                # rank gate), and replacing the updater would wipe the
                # accumulated momentum/Adam state mid-training. A
                # *different* config is a real job misconfiguration.
                if self._opt_config != (name, kwargs, extras):
                    raise ValueError(
                        "conflicting server optimizer: have %r, got %r"
                        % (self._opt_config, (name, kwargs, extras)))
                return
            opt = optimizer.create(name, **kwargs)
            self._apply_opt_extras(opt, extras)
            self._updater = optimizer.get_updater(opt)
            self._opt_config = (name, kwargs, extras)

    @staticmethod
    def _apply_opt_extras(opt, extras):
        """Install the non-scalar optimizer config the client serialized
        as plain wire data: per-parameter lr/wd multipliers, the
        index->name map, and a reconstructed lr scheduler."""
        if extras.get("idx2name"):
            opt.idx2name = dict(extras["idx2name"])
        if extras.get("lr_mult"):
            # direct assignment: the client already ran set_lr_mult's
            # normalization — re-running it here would double-apply
            opt.lr_mult = dict(extras["lr_mult"])
        if extras.get("wd_mult"):
            opt.wd_mult = dict(extras["wd_mult"])
        sched = extras.get("lr_scheduler")
        if sched:
            from . import lr_scheduler as lr_mod

            cls_name, skw = sched
            klass = getattr(lr_mod, cls_name, None)
            if klass is None or not (isinstance(klass, type)
                                     and issubclass(klass,
                                                    lr_mod.LRScheduler)):
                raise ValueError(
                    "set_optimizer: unknown lr_scheduler class %r"
                    % (cls_name,))
            opt.lr_scheduler = klass(**dict(skw))

    def _abort_barrier_locked(self, msg):
        """Fail the in-flight barrier round: every waiter raises instead
        of spinning (round-6 fix for the permanent hang when a worker
        holding a pending arrival dies)."""
        if self._barrier_count == 0:
            return
        self._barrier_errors[self._barrier_gen] = msg
        while len(self._barrier_errors) > 8:
            self._barrier_errors.pop(next(iter(self._barrier_errors)))
        self._barrier_gen += 1
        self._barrier_count = 0
        self._barrier_cond.notify_all()

    @staticmethod
    def _conn_closed(conn):
        """Non-consuming liveness probe of a waiter's own socket."""
        try:
            return conn.recv(1, socket.MSG_PEEK | socket.MSG_DONTWAIT) == b""
        except (BlockingIOError, InterruptedError):
            return False
        except OSError:
            return True

    def _barrier(self, conn=None):
        """Dead-worker handling: each waiter's handler thread probes its
        OWN socket (``_conn_closed``) every wait tick — a waiter whose
        worker died aborts the round for every survivor; a worker that
        never arrives is bounded by the overall timeout. Both reset the
        count, so later barriers start clean (the seed leaked the dead
        worker's +1 and every subsequent barrier deadlocked)."""
        with self._barrier_cond:
            gen = self._barrier_gen
            self._barrier_count += 1
            if self._barrier_count >= self._num_workers:
                self._barrier_count = 0
                self._barrier_gen += 1
                self._barrier_cond.notify_all()
                return
            deadline = time.monotonic() + self._barrier_timeout
            while self._barrier_gen == gen and not self._stop.is_set():
                if time.monotonic() >= deadline:
                    msg = ("barrier timed out after %.0fs (%d of %d "
                           "workers arrived)"
                           % (self._barrier_timeout, self._barrier_count,
                              self._num_workers))
                    self._abort_barrier_locked(msg)
                    raise MXNetError(msg)
                if conn is not None and self._conn_closed(conn):
                    # this waiter's own worker died mid-barrier
                    self._abort_barrier_locked(
                        "barrier aborted: a waiting worker "
                        "disconnected")
                    raise ConnectionError("peer closed during barrier")
                self._barrier_cond.wait(timeout=0.2)
            err = self._barrier_errors.get(gen)
            if err is not None:
                raise MXNetError(err)
            if self._stop.is_set() and self._barrier_gen == gen:
                raise MXNetError("barrier aborted: server stopped")

    def _dispatch(self, op, key, meta, wire, conn=None):
        """One op -> ('ok', payload). Raises on bad requests; _handle
        converts that to the protocol's ('err', text) reply."""
        if op == "init":
            with self._lock:
                self._store.setdefault(key, _arr_from_wire(wire))
            return None
        if op == "push":
            self._apply_push(key, _arr_from_wire(wire))
            return None
        if op == "pull":
            with self._lock:
                if key not in self._store:
                    raise KeyError("pull before init: %r" % (key,))
                return _arr_to_wire(self._store[key])
        if op == "set_optimizer":
            self._set_optimizer(key, meta)
            return None
        if op == "num_workers":
            return self._num_workers
        if op == "barrier":
            self._barrier(conn)
            return None
        if op == "save_opt":
            with self._lock:
                if self._updater is None:
                    raise ValueError("no server optimizer installed")
                return [(k, _state_to_wire(v)) for k, v in
                        self._updater.get_states_map().items()]
        if op == "load_opt":
            with self._lock:
                if self._updater is None:
                    raise ValueError("no server optimizer installed")
                if not isinstance(wire, (list, tuple)):
                    raise ValueError(
                        "load_opt expects [(key, state-wire)] pairs, got "
                        "%s (raw optimizer blobs are not accepted: the "
                        "server never unpickles network bytes)"
                        % type(wire).__name__)
                states = {k: _state_from_wire(w) for k, w in wire}
                self._updater.set_states_from_map(states)
            return None
        raise ValueError("unknown op %r" % (op,))

    def _handle(self, conn):
        try:
            while not self._stop.is_set():
                op, key, meta, wire = _recv_msg(conn)
                if op == "stop":
                    _send_msg(conn, ("ok", None))
                    self.shutdown()
                    return
                try:
                    payload = self._dispatch(op, key, meta, wire, conn=conn)
                except (ConnectionError, OSError):
                    raise  # this conn's own peer vanished: no reply path
                except Exception as e:  # bad request: reply, keep serving
                    _send_msg(conn, ("err", "%s: %s"
                                     % (type(e).__name__, e)))
                    continue
                _send_msg(conn, ("ok", payload))
        except (ConnectionError, OSError):
            pass
        finally:
            self._conns.discard(conn)
            conn.close()

    def serve_forever(self):
        """Accept loop; returns after a client sends ``stop``."""
        self._sock.settimeout(0.5)
        threads = []
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            self._conns.add(conn)
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=2)

    def serve_in_background(self):
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self):
        self._stop.set()
        with self._barrier_cond:
            self._barrier_cond.notify_all()
        # unblock handler threads parked in recv so serve_forever's
        # joins return immediately (a stopped server must not make its
        # clients' next RPC hang until their own socket timeout)
        for conn in list(self._conns):
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------
class ServerKVStore(kvstore.KVStore):
    """KVStore client speaking to KVStoreServer(s) (dist_async tier).

    Constructed by ``kvstore.create('dist_async')`` — either from a
    hand-set ``MXNET_PS_SERVER_URI`` or, in the scheduler topology, from
    the server URIs the tracker published at rendezvous (no env needed;
    see ``mxnet_tpu/tracker.py``). Subclasses :class:`kvstore.KVStore`
    (overriding every op with its RPC counterpart) so a preconstructed
    instance passes ``_create_kvstore``'s isinstance check and can be
    handed straight to ``Module.fit``/``init_optimizer`` like any other
    store. The optimizer runs SERVER-side (``set_optimizer``), so
    ``push`` sends raw gradients and ``pull`` returns updated weights —
    the reference's dist_async worker loop (kvstore_dist.h push/pull
    RPCs).

    With multiple servers, keys shard across them by a stable hash
    (the reference's ps-lite key-to-server assignment,
    kvstore_dist.h EncodeDefaultKey); every worker computes the same
    assignment, so per-key state lives on exactly one server.
    """

    server_side = True  # Module: route updates through the server, not
    # the fused SPMD step (the server IS the update engine here)

    def __init__(self, uri, kv_type="dist_async", tracker_client=None):
        super().__init__(kv_type)
        from . import tracker as _trk

        if isinstance(uri, str):
            uris = [u for u in uri.split(",") if u]
        else:
            uris = list(uri)
        if not uris:
            raise MXNetError("ServerKVStore: no server URIs")
        self._uris = uris
        self._socks = [_trk.connect_with_backoff(u, deadline=30.0)
                       for u in uris]
        self._wlocks = [threading.Lock() for _ in uris]
        self._tracker = tracker_client
        self._num_workers_cache = None

    @property
    def num_workers(self):
        env = os.environ.get("MXNET_TPU_NUM_WORKERS",
                             os.environ.get("DMLC_NUM_WORKER"))
        if env is not None:
            return int(env)
        if self._num_workers_cache is None:
            # hand-set MXNET_PS_SERVER_URI with no DMLC env: the server
            # knows the worker count it gates barriers on — asking it
            # beats silently reporting 1
            self._num_workers_cache = int(self._rpc_idx(0, "num_workers"))
        return self._num_workers_cache

    @property
    def rank(self):
        if self._tracker is not None:
            return self._tracker.rank  # scheduler-assigned
        return int(os.environ.get("MXNET_TPU_WORKER_ID",
                                  os.environ.get("DMLC_RANK",
                                                 os.environ.get(
                                                     "DMLC_WORKER_ID",
                                                     "0"))))

    def num_dead_node(self, node_id=0, timeout=60):
        """Dead-peer count from the scheduler's heartbeat tracking
        (ref: kvstore.h:330-340); 0 when running without a tracker."""
        del node_id, timeout
        if self._tracker is None:
            return 0
        return self._tracker.num_dead_node()

    def _shard(self, key):
        """key -> server index; stable across processes (builtin hash
        is salted per-interpreter, crc32 is not)."""
        if len(self._socks) == 1:
            return 0
        return zlib.crc32(str(key).encode()) % len(self._socks)

    def _rpc_idx(self, idx, op, key=None, meta=None, wire=None,
                 timeout=60.0):
        sock = self._socks[idx]
        try:
            with self._wlocks[idx]:
                sock.settimeout(timeout)
                _send_msg(sock, (op, key, meta, wire))
                status, payload = _recv_msg(sock)
        except (socket.timeout, OSError, ConnectionError) as e:
            # a timed-out request's reply would otherwise land unread
            # and be consumed as the NEXT op's reply — invalidate the
            # connection so later ops fail fast instead of desyncing
            try:
                sock.close()
            except OSError:
                pass
            raise MXNetError(
                "kvstore_server rpc %r to %s failed (%s: %s); "
                "connection closed" % (op, self._uris[idx],
                                       type(e).__name__, e))
        if status != "ok":
            raise MXNetError("kvstore_server: %s" % (payload,))
        return payload

    def _rpc(self, op, key=None, meta=None, wire=None):
        """Keyed data ops route to the key's shard; everything else
        goes to server 0 (single-server compatibility surface)."""
        if op in ("init", "push", "pull") and key is not None:
            return self._rpc_idx(self._shard(key), op, key, meta, wire)
        return self._rpc_idx(0, op, key, meta, wire)

    def _rpc_all(self, op, key=None, meta=None, wire=None, timeout=60.0):
        """Same op on every server, in rank order (deterministic across
        workers, so multi-server barriers cannot deadlock)."""
        return [self._rpc_idx(i, op, key, meta, wire, timeout=timeout)
                for i in range(len(self._socks))]

    @staticmethod
    def _np(value):
        return value.asnumpy() if hasattr(value, "asnumpy") \
            else np.asarray(value)

    def _merged(self, value):
        """A per-device list reduces to one array before crossing the
        wire (the local Comm::Reduce step of the reference worker)."""
        if isinstance(value, (list, tuple)):
            arrs = [self._np(v) for v in value]
            return arrs[0] if len(arrs) == 1 else np.sum(arrs, axis=0)
        return self._np(value)

    def init(self, key, value):
        for k, v in _iter_kv(key, value):
            self._rpc("init", k, None, _arr_to_wire(self._merged(v)))

    def push(self, key, value, priority=0):
        for k, v in _iter_kv(key, value):
            self._rpc("push", k, None, _arr_to_wire(self._merged(v)))

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        from .base import MXNetError

        if out is None:
            raise MXNetError("kvstore.pull requires out=")
        for k, o in _iter_kv(key, out):
            w = _arr_from_wire(self._rpc("pull", k))
            targets = o if isinstance(o, (list, tuple)) else [o]
            for t in targets:
                t[:] = w

    # lr schedulers representable as plain wire data: class name ->
    # (ctor_param, instance_attr) pairs (ref lr_scheduler.py signatures)
    _SCHED_WIRE = {
        "FactorScheduler": (("step", "step"), ("factor", "factor"),
                            ("stop_factor_lr", "stop_factor_lr"),
                            ("base_lr", "base_lr")),
        "MultiFactorScheduler": (("step", "step"), ("factor", "factor"),
                                 ("base_lr", "base_lr")),
        # base_lr maps from base_lr_orig: Optimizer.__init__ mutates
        # .base_lr to learning_rate, but PolyScheduler decays from the
        # ctor-time base_lr_orig snapshot — shipping the mutated value
        # would rebuild a schedule decaying from the wrong anchor
        "PolyScheduler": (("max_update", "max_update"), ("pwr", "power"),
                          ("base_lr", "base_lr_orig")),
        "LRScheduler": (("base_lr", "base_lr"),),
    }

    @classmethod
    def _opt_extras(cls, opt):
        """Serialize the non-scalar optimizer config that IS
        representable as plain wire data (lr_mult/wd_mult/idx2name and
        the stock lr schedulers); warn loudly about what is not
        (param_dict holds live Parameter objects, custom scheduler
        subclasses hold arbitrary state). These used to be silently
        dropped — the server then trained with the wrong per-parameter
        learning rates."""
        extras, dropped = {}, []
        if opt.idx2name:
            extras["idx2name"] = dict(opt.idx2name)
        if opt.lr_mult:
            extras["lr_mult"] = dict(opt.lr_mult)
        if opt.wd_mult:
            extras["wd_mult"] = dict(opt.wd_mult)
        if opt.lr_scheduler is not None:
            spec = cls._SCHED_WIRE.get(type(opt.lr_scheduler).__name__)
            if spec is not None and type(opt.lr_scheduler).__module__ \
                    .endswith("lr_scheduler"):
                extras["lr_scheduler"] = (
                    type(opt.lr_scheduler).__name__,
                    {ctor: getattr(opt.lr_scheduler, attr)
                     for ctor, attr in spec})
            else:
                dropped.append("lr_scheduler (%s is not a stock "
                               "mxnet_tpu.lr_scheduler class)"
                               % type(opt.lr_scheduler).__name__)
        if opt.param_dict:
            dropped.append("param_dict (live Parameter objects cannot "
                           "cross the data-only wire)")
        if dropped:
            warnings.warn(
                "ServerKVStore.set_optimizer: DROPPING %s — the "
                "server-side optimizer will run without it. Fold the "
                "equivalent config into lr_mult/wd_mult or a stock "
                "lr scheduler." % "; ".join(dropped), stacklevel=3)
        return extras

    def set_optimizer(self, optimizer_or_name, **kwargs):
        """Install the server-side optimizer on every server (ref: the
        worker sends its serialized optimizer to every server,
        kvstore.cc set_optimizer). Accepts a name + kwargs or an
        Optimizer instance — its scalar hyperparameters (matched
        against the subclass __init__ signature) travel, and so do
        lr_mult/wd_mult/idx2name and stock lr schedulers (as plain wire
        data). What cannot be represented (param_dict, custom scheduler
        classes) is dropped with a loud warning, never silently."""
        extras = {}
        if isinstance(optimizer_or_name, str):
            name, kw = optimizer_or_name, kwargs
        else:
            import inspect

            opt = optimizer_or_name
            name = type(opt).__name__.lower()
            kw = dict(kwargs)
            for klass in type(opt).__mro__:           # subclass kwargs ride
                if not hasattr(klass, "__init__"):    # **kwargs to the base
                    continue
                try:
                    params = inspect.signature(klass.__init__).parameters
                except (TypeError, ValueError):
                    continue
                for p in params:
                    attr = "lr" if p == "learning_rate" else p
                    if p in ("self", "args", "kwargs") \
                            or not hasattr(opt, attr):
                        continue
                    v = getattr(opt, attr)
                    if isinstance(v, (int, float, str, bool)):
                        kw.setdefault(p, v)
            extras = self._opt_extras(opt)
        self._rpc_all("set_optimizer", name,
                      {"kwargs": kw, "extras": extras})

    def set_updater(self, updater):
        """The optimizer runs SERVER-side on this tier; a client-side
        updater would never be consulted by push(). Fail fast instead
        of silently training with the wrong update rule (the base
        class would just store it)."""
        raise MXNetError(
            "ServerKVStore applies updates server-side: use "
            "set_optimizer(name, **kwargs), not a client updater")

    _set_updater = set_updater

    def set_gradient_compression(self, compression_params):
        from .base import MXNetError

        raise MXNetError("the server tier does not implement gradient "
                         "compression; use the serverless dist tiers")

    def save_optimizer_states(self, fname, dump_optimizer=False):
        """Server-side optimizer state -> local file (the
        update_on_kvstore branch of Module.save_optimizer_states,
        module.py:475). State crosses the wire as tagged plain data
        (_state_to_wire); the file keeps the reference's
        pickle-of-numpy-map format, so it interoperates with
        Updater.get_states checkpoints. With sharded servers the
        per-server maps are disjoint by construction (each key's state
        lives on its shard) and merge into one file."""
        states_map = {}
        for wire in self._rpc_all("save_opt"):
            states_map.update({k: _state_from_wire(w) for k, w in wire})
        with open(fname, "wb") as f:
            f.write(pickle.dumps(states_map, protocol=4))

    def load_optimizer_states(self, fname):
        """Local file -> server-side optimizer state. The local
        checkpoint is unpickled HERE, client-side, with the same trust
        as any locally-loaded checkpoint file — what crosses the wire
        is the tagged plain-data encoding, which the server decodes
        without ever unpickling peer bytes."""
        with open(fname, "rb") as f:
            states_map = pickle.loads(f.read())
        if isinstance(states_map, tuple) and len(states_map) == 2 \
                and isinstance(states_map[1], dict):
            states_map = states_map[0]  # (states, optimizer) dumps
        by_server = [[] for _ in self._socks]
        for k, v in states_map.items():
            by_server[self._shard(k)].append((k, _state_to_wire(v)))
        for idx, pairs in enumerate(by_server):
            self._rpc_idx(idx, "load_opt", wire=pairs)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Dense-backed row_sparse_pull (the server stores dense
        weights): fetch the full value once, then materialize the
        requested rows per out, matching kvstore_local.h PullRowSparse
        semantics (unique-sorted ids)."""
        from .base import MXNetError

        if out is None or row_ids is None:
            raise MXNetError("row_sparse_pull requires out= and row_ids=")
        from .ndarray import ndarray as nd
        from .ndarray.sparse import RowSparseNDArray

        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        for k, o in _iter_kv(key, out):
            w = _arr_from_wire(self._rpc("pull", k))
            targets = o if isinstance(o, (list, tuple)) else [o]
            # per-key broadcast: computed fresh inside the loop — the
            # old `rids = list(rids) * len(targets)` rebinding leaked a
            # grown list into every subsequent key's iteration
            if len(rids) == 1 and len(targets) > 1:
                key_rids = list(rids) * len(targets)
            else:
                key_rids = list(rids)
            for t, rid in zip(targets, key_rids):
                ids = np.unique(np.asarray(
                    rid.asnumpy() if hasattr(rid, "asnumpy") else rid,
                    np.int64))
                if ids.size and (ids[0] < 0 or ids[-1] >= w.shape[0]):
                    # clipping silently returned the LAST row's data for
                    # any out-of-range id — wrong values are worse than
                    # an error (kvstore_local.h asserts the same bound)
                    raise MXNetError(
                        "row_sparse_pull: row_ids out of range for key "
                        "%r: [%d, %d] vs %d rows"
                        % (k, int(ids[0]), int(ids[-1]), w.shape[0]))
                taken = nd.array(w[ids])
                if isinstance(t, RowSparseNDArray):
                    newo = RowSparseNDArray(taken, nd.array(ids),
                                            w.shape, ctx=t.ctx)
                    t._rebind_sparse(newo)
                else:
                    dense = np.zeros(w.shape, w.dtype)
                    dense[ids] = w[ids]
                    t[:] = dense

    def barrier(self):
        """Barrier across workers, held at every server in rank order
        (same visit order on every worker, so sharded barriers cannot
        interleave into a deadlock). The server aborts the round with
        an error — raised here — when a peer dies or its overall
        timeout (MXNET_KVSTORE_BARRIER_TIMEOUT) expires."""
        bt = float(os.environ.get("MXNET_KVSTORE_BARRIER_TIMEOUT", "120"))
        self._rpc_all("barrier", timeout=bt + 30.0)

    def stop_server(self):
        self._rpc_all("stop")

    def close(self):
        if self._tracker is not None:
            self._tracker.done()
        for sock in self._socks:
            try:
                sock.close()
            except OSError:
                pass


def _iter_kv(key, value):
    """Pair keys with values. A single key takes the WHOLE value (which
    may be a per-device list); a key list zips positionally."""
    if isinstance(key, (list, tuple)):
        for k, v in zip(key, value):
            yield str(k), v
    else:
        yield str(key), value


# ---------------------------------------------------------------------------
# entry point (DMLC_ROLE dispatch)
# ---------------------------------------------------------------------------
def _init_kvstore_server_module():
    role = os.environ.get("DMLC_ROLE", "worker").lower()
    if role not in ("server", "scheduler"):
        return
    from . import tracker as trk

    if role == "scheduler":
        # scheduler topology: run the tracker rendezvous loop (ref: the
        # dmlc tracker's scheduler node). Without the env contract the
        # shim exits 0 so reference launch scripts keep working.
        if trk.tracker_env_spec() is not None:
            sys.exit(trk.main())
        sys.exit(0)
    if os.environ.get("MXNET_KVSTORE_SERVER") == "1":
        spec = trk.tracker_env_spec()
        # multi-host topology (scheduler on another host): bind the
        # wildcard so remote workers can reach us, and advertise a
        # routable address — publishing the loopback bind would strand
        # every remote worker in connect retries
        multi_host = spec is not None and \
            spec[0].rsplit(":", 1)[0] not in ("127.0.0.1", "localhost")
        host = os.environ.get("MXNET_PS_BIND_HOST",
                              "" if multi_host else "127.0.0.1")
        # scheduler topology: DMLC_PS_ROOT_PORT is the SCHEDULER's port
        # (never bind it); manual MXNET_PS_SERVER_URI deployments keep
        # the pre-tracker fallback of binding the root port directly
        default_port = "0" if spec is not None \
            else os.environ.get("DMLC_PS_ROOT_PORT", "0")
        port = int(os.environ.get("MXNET_PS_BIND_PORT", default_port) or 0)
        nw = int(os.environ.get("MXNET_TPU_NUM_WORKERS",
                                os.environ.get("DMLC_NUM_WORKER", "1")))
        server = KVStoreServer(host=host, port=port, num_workers=nw)
        client = None
        if spec is not None:
            advertise = os.environ.get("MXNET_PS_ADVERTISE_HOST")
            if advertise is None and multi_host:
                # the outbound interface toward the scheduler is the
                # address workers can route back to (UDP connect does
                # no I/O — it only resolves the local endpoint)
                sched_host, sched_port = spec[0].rsplit(":", 1)
                probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                try:
                    probe.connect((sched_host, int(sched_port)))
                    advertise = probe.getsockname()[0]
                finally:
                    probe.close()
            bound_port = server.addr.rsplit(":", 1)[1]
            addr = "%s:%s" % (advertise, bound_port) if advertise \
                else server.addr
            # publish this server's URI to the scheduler; workers
            # discover it at kvstore.create('dist_async') rendezvous.
            # The scheduler's shutdown fan-out sends the 'stop' op
            # here once every worker reports done.
            client = trk.TrackerClient(spec[0], "server", addr=addr)
        print("kvstore_server listening on %s" % server.addr, flush=True)
        server.serve_forever()
        if client is not None:
            client.close()
        sys.exit(0)
    # serverless tier: nothing to run (see module docstring)
    sys.exit(0)


if __name__ == "__main__":
    _init_kvstore_server_module()
