"""KVStore server entry point — serverless-parity shim.

Reference counterpart: ``python/mxnet/kvstore_server.py`` (the server
main loop driven by DMLC_ROLE=server, executing optimizer updates on
sharded keys; kvstore_dist_server.h:113). The TPU backend has **no
server processes** — aggregation is an XLA all-reduce over the device
mesh and the optimizer runs replicated (or ZeRO-sharded) on workers
(see kvstore.DistKVStore, parallel/spmd.py zero=True).

This module keeps reference launch scripts working: a process started
with DMLC_ROLE=server or =scheduler exits immediately with success
(the jax coordinator, spawned inside worker 0's process, already plays
the scheduler's rendezvous role).
"""
from __future__ import annotations

import os
import sys


def _init_kvstore_server_module():
    role = os.environ.get("DMLC_ROLE", "worker").lower()
    if role in ("server", "scheduler"):
        # serverless backend: nothing to run (see module docstring)
        sys.exit(0)


if __name__ == "__main__":
    _init_kvstore_server_module()
