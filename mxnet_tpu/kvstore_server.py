"""KVStore parameter server: a real server-side-optimizer tier, plus
the serverless-parity shim.

Reference counterpart: ``python/mxnet/kvstore_server.py`` (the server
main loop driven by DMLC_ROLE=server) and ``kvstore_dist_server.h``
(merge buffers + server-executed optimizer, :113-500).

Two tiers, chosen by configuration:

1. **Serverless (TPU default).** Aggregation is an XLA all-reduce over
   the device mesh and the optimizer runs replicated on workers (see
   kvstore.DistKVStore, parallel/spmd.py zero=True). A process started
   with DMLC_ROLE=server/scheduler and no server opt-in exits 0 so
   reference launch scripts keep working — the jax coordinator (spawned
   inside worker 0) already plays the scheduler's rendezvous role.

2. **Real server (``MXNET_KVSTORE_SERVER=1``).** ``KVStoreServer``
   holds the weights, applies pushes through a server-side optimizer
   (exactly the reference's dist_async contract: each worker's push is
   applied when it arrives — no global synchronisation — and pulls
   return the freshest weights), and answers pulls/barriers over a
   length-prefixed TCP protocol. ``kvstore.create('dist_async')``
   connects to it when ``MXNET_PS_SERVER_URI`` is set (see
   ``ServerKVStore``). This is the behavioral equivalent of the
   reference's server-side-optimizer mode, runnable on CPU hosts.

Protocol: 4-byte big-endian length + payload. Payloads are tuples
``(op, key, meta, raw_bytes)`` encoded with pickle but decoded by a
restricted unpickler — arrays travel as (dtype, shape, bytes), never
as pickled objects, and the unpickler refuses every global lookup.
Like the reference's ps-lite transport this is an in-cluster protocol
with no auth; do not expose the port beyond the job.
"""
from __future__ import annotations

import io
import os
import pickle
import socket
import struct
import sys
import threading

import numpy as np

from . import kvstore
from .base import MXNetError


# ---------------------------------------------------------------------------
# wire helpers
# ---------------------------------------------------------------------------
class _SafeUnpickler(pickle.Unpickler):
    """Only plain data crosses the wire: refuse every global lookup."""

    def find_class(self, module, name):
        raise pickle.UnpicklingError(
            "kvstore_server protocol carries data only (%s.%s refused)"
            % (module, name))


def _pack(obj):
    return pickle.dumps(obj, protocol=4)


def _unpack(raw):
    return _SafeUnpickler(io.BytesIO(raw)).load()


def _send_msg(sock, obj):
    raw = _pack(obj)
    sock.sendall(struct.pack(">I", len(raw)) + raw)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("kvstore_server: peer closed")
        buf += chunk
    return buf


def _recv_msg(sock):
    (n,) = struct.unpack(">I", _recv_exact(sock, 4))
    return _unpack(_recv_exact(sock, n))


def _arr_to_wire(a):
    a = np.ascontiguousarray(a)
    return (str(a.dtype), a.shape, a.tobytes())


def _arr_from_wire(w):
    dtype, shape, raw = w
    return np.frombuffer(raw, dtype=dtype).reshape(shape).copy()


def _state_to_wire(v):
    """Optimizer-state pytree -> tagged plain data. Arrays travel as
    (dtype, shape, bytes) like every other tensor on this protocol —
    never as a pickle blob (``load_opt`` used to feed network bytes to
    ``pickle.loads`` via Updater.set_states, contradicting the module's
    no-globals guarantee)."""
    if v is None:
        return ("none",)
    if isinstance(v, (bool, int, float, str)):
        return ("py", v)
    if isinstance(v, (list, tuple)):
        tag = "list" if isinstance(v, list) else "tuple"
        return (tag, [_state_to_wire(i) for i in v])
    arr = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
    return ("nd",) + _arr_to_wire(arr)


def _state_from_wire(w):
    tag = w[0]
    if tag == "none":
        return None
    if tag == "py":
        return w[1]
    if tag == "list":
        return [_state_from_wire(i) for i in w[1]]
    if tag == "tuple":
        return tuple(_state_from_wire(i) for i in w[1])
    if tag == "nd":
        return _arr_from_wire(w[1:])
    raise ValueError("bad optimizer-state wire tag %r" % (tag,))


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------
class KVStoreServer:
    """Weights + server-side optimizer behind a TCP endpoint.

    Mirrors kvstore_dist_server.h semantics: ``init`` is first-writer-
    wins, each ``push`` is applied on arrival under the server's
    updater (optimizer state lives server-side, keyed like the
    reference's per-key store), ``pull`` returns the current weights,
    ``barrier`` blocks until every worker arrives. dist_async = push
    without waiting for the barrier.
    """

    def __init__(self, host="127.0.0.1", port=0, num_workers=1):
        self._store = {}
        self._updater = None
        self._opt_config = None
        self._lock = threading.Lock()
        self._num_workers = num_workers
        self._barrier_cond = threading.Condition()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.addr = "%s:%d" % self._sock.getsockname()[:2]

    # -- op handlers --------------------------------------------------------
    def _apply_push(self, key, grad):
        with self._lock:
            if key not in self._store:
                raise KeyError("push before init: %r" % (key,))
            if self._updater is None:
                self._store[key] += grad
            else:
                from .ndarray import array

                w = array(self._store[key])
                self._updater(key, array(grad), w)
                self._store[key] = w.asnumpy()

    def _set_optimizer(self, name, kwargs):
        from . import optimizer

        with self._lock:
            if self._opt_config is not None:
                # first-writer-wins, like init: every worker's
                # init_optimizer sends the config (module.py:349 has no
                # rank gate), and replacing the updater would wipe the
                # accumulated momentum/Adam state mid-training. A
                # *different* config is a real job misconfiguration.
                if self._opt_config != (name, kwargs):
                    raise ValueError(
                        "conflicting server optimizer: have %r, got %r"
                        % (self._opt_config, (name, kwargs)))
                return
            opt = optimizer.create(name, **kwargs)
            self._updater = optimizer.get_updater(opt)
            self._opt_config = (name, kwargs)

    def _barrier(self):
        with self._barrier_cond:
            gen = self._barrier_gen
            self._barrier_count += 1
            if self._barrier_count >= self._num_workers:
                self._barrier_count = 0
                self._barrier_gen += 1
                self._barrier_cond.notify_all()
                return
            while self._barrier_gen == gen and not self._stop.is_set():
                self._barrier_cond.wait(timeout=0.5)

    def _dispatch(self, op, key, meta, wire):
        """One op -> ('ok', payload). Raises on bad requests; _handle
        converts that to the protocol's ('err', text) reply."""
        if op == "init":
            with self._lock:
                self._store.setdefault(key, _arr_from_wire(wire))
            return None
        if op == "push":
            self._apply_push(key, _arr_from_wire(wire))
            return None
        if op == "pull":
            with self._lock:
                if key not in self._store:
                    raise KeyError("pull before init: %r" % (key,))
                return _arr_to_wire(self._store[key])
        if op == "set_optimizer":
            self._set_optimizer(key, meta)
            return None
        if op == "barrier":
            self._barrier()
            return None
        if op == "save_opt":
            with self._lock:
                if self._updater is None:
                    raise ValueError("no server optimizer installed")
                return [(k, _state_to_wire(v)) for k, v in
                        self._updater.get_states_map().items()]
        if op == "load_opt":
            with self._lock:
                if self._updater is None:
                    raise ValueError("no server optimizer installed")
                if not isinstance(wire, (list, tuple)):
                    raise ValueError(
                        "load_opt expects [(key, state-wire)] pairs, got "
                        "%s (raw optimizer blobs are not accepted: the "
                        "server never unpickles network bytes)"
                        % type(wire).__name__)
                states = {k: _state_from_wire(w) for k, w in wire}
                self._updater.set_states_from_map(states)
            return None
        raise ValueError("unknown op %r" % (op,))

    def _handle(self, conn):
        try:
            while not self._stop.is_set():
                op, key, meta, wire = _recv_msg(conn)
                if op == "stop":
                    _send_msg(conn, ("ok", None))
                    self.shutdown()
                    return
                try:
                    payload = self._dispatch(op, key, meta, wire)
                except Exception as e:  # bad request: reply, keep serving
                    _send_msg(conn, ("err", "%s: %s"
                                     % (type(e).__name__, e)))
                    continue
                _send_msg(conn, ("ok", payload))
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def serve_forever(self):
        """Accept loop; returns after a client sends ``stop``."""
        self._sock.settimeout(0.5)
        threads = []
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=2)

    def serve_in_background(self):
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self):
        self._stop.set()
        with self._barrier_cond:
            self._barrier_cond.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------
class ServerKVStore(kvstore.KVStore):
    """KVStore client speaking to a KVStoreServer (dist_async tier).

    Constructed by ``kvstore.create('dist_async')`` when
    ``MXNET_PS_SERVER_URI`` is set. Subclasses :class:`kvstore.KVStore`
    (overriding every op with its RPC counterpart) so a preconstructed
    instance passes ``_create_kvstore``'s isinstance check and can be
    handed straight to ``Module.fit``/``init_optimizer`` like any other
    store. The optimizer runs SERVER-side (``set_optimizer``), so
    ``push`` sends raw gradients and ``pull`` returns updated weights —
    the reference's dist_async worker loop (kvstore_dist.h push/pull
    RPCs).
    """

    server_side = True  # Module: route updates through the server, not
    # the fused SPMD step (the server IS the update engine here)

    def __init__(self, uri, kv_type="dist_async"):
        super().__init__(kv_type)
        host, port = uri.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=60)
        self._wlock = threading.Lock()

    @property
    def num_workers(self):
        return int(os.environ.get("MXNET_TPU_NUM_WORKERS",
                                  os.environ.get("DMLC_NUM_WORKER", "1")))

    @property
    def rank(self):
        return int(os.environ.get("MXNET_TPU_WORKER_ID",
                                  os.environ.get("DMLC_RANK", "0")))

    def _rpc(self, op, key=None, meta=None, wire=None):
        with self._wlock:
            _send_msg(self._sock, (op, key, meta, wire))
            status, payload = _recv_msg(self._sock)
        if status != "ok":
            from .base import MXNetError

            raise MXNetError("kvstore_server: %s" % (payload,))
        return payload

    @staticmethod
    def _np(value):
        return value.asnumpy() if hasattr(value, "asnumpy") \
            else np.asarray(value)

    def _merged(self, value):
        """A per-device list reduces to one array before crossing the
        wire (the local Comm::Reduce step of the reference worker)."""
        if isinstance(value, (list, tuple)):
            arrs = [self._np(v) for v in value]
            return arrs[0] if len(arrs) == 1 else np.sum(arrs, axis=0)
        return self._np(value)

    def init(self, key, value):
        for k, v in _iter_kv(key, value):
            self._rpc("init", k, None, _arr_to_wire(self._merged(v)))

    def push(self, key, value, priority=0):
        for k, v in _iter_kv(key, value):
            self._rpc("push", k, None, _arr_to_wire(self._merged(v)))

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        from .base import MXNetError

        if out is None:
            raise MXNetError("kvstore.pull requires out=")
        for k, o in _iter_kv(key, out):
            w = _arr_from_wire(self._rpc("pull", k))
            targets = o if isinstance(o, (list, tuple)) else [o]
            for t in targets:
                t[:] = w

    def set_optimizer(self, optimizer_or_name, **kwargs):
        """Install the server-side optimizer (ref: the worker sends its
        serialized optimizer to every server, kvstore.cc
        set_optimizer). Accepts a name + kwargs or an Optimizer
        instance — its scalar hyperparameters (matched against the
        subclass __init__ signature) travel; optimizer STATE lives only
        on the server, and non-scalar config (lr schedulers, param
        dicts) stays worker-side by design."""
        if isinstance(optimizer_or_name, str):
            name, kw = optimizer_or_name, kwargs
        else:
            import inspect

            opt = optimizer_or_name
            name = type(opt).__name__.lower()
            kw = dict(kwargs)
            for klass in type(opt).__mro__:           # subclass kwargs ride
                if not hasattr(klass, "__init__"):    # **kwargs to the base
                    continue
                try:
                    params = inspect.signature(klass.__init__).parameters
                except (TypeError, ValueError):
                    continue
                for p in params:
                    attr = "lr" if p == "learning_rate" else p
                    if p in ("self", "args", "kwargs") \
                            or not hasattr(opt, attr):
                        continue
                    v = getattr(opt, attr)
                    if isinstance(v, (int, float, str, bool)):
                        kw.setdefault(p, v)
        self._rpc("set_optimizer", name, kw)

    def set_updater(self, updater):
        """The optimizer runs SERVER-side on this tier; a client-side
        updater would never be consulted by push(). Fail fast instead
        of silently training with the wrong update rule (the base
        class would just store it)."""
        raise MXNetError(
            "ServerKVStore applies updates server-side: use "
            "set_optimizer(name, **kwargs), not a client updater")

    _set_updater = set_updater

    def set_gradient_compression(self, compression_params):
        from .base import MXNetError

        raise MXNetError("the server tier does not implement gradient "
                         "compression; use the serverless dist tiers")

    def save_optimizer_states(self, fname, dump_optimizer=False):
        """Server-side optimizer state -> local file (the
        update_on_kvstore branch of Module.save_optimizer_states,
        module.py:475). State crosses the wire as tagged plain data
        (_state_to_wire); the file keeps the reference's
        pickle-of-numpy-map format, so it interoperates with
        Updater.get_states checkpoints."""
        wire = self._rpc("save_opt")
        states_map = {k: _state_from_wire(w) for k, w in wire}
        with open(fname, "wb") as f:
            f.write(pickle.dumps(states_map, protocol=4))

    def load_optimizer_states(self, fname):
        """Local file -> server-side optimizer state. The local
        checkpoint is unpickled HERE, client-side, with the same trust
        as any locally-loaded checkpoint file — what crosses the wire
        is the tagged plain-data encoding, which the server decodes
        without ever unpickling peer bytes."""
        with open(fname, "rb") as f:
            states_map = pickle.loads(f.read())
        if isinstance(states_map, tuple) and len(states_map) == 2 \
                and isinstance(states_map[1], dict):
            states_map = states_map[0]  # (states, optimizer) dumps
        self._rpc("load_opt",
                  wire=[(k, _state_to_wire(v))
                        for k, v in states_map.items()])

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Dense-backed row_sparse_pull (the server stores dense
        weights): fetch the full value once, then materialize the
        requested rows per out, matching kvstore_local.h PullRowSparse
        semantics (unique-sorted ids)."""
        from .base import MXNetError

        if out is None or row_ids is None:
            raise MXNetError("row_sparse_pull requires out= and row_ids=")
        from .ndarray import ndarray as nd
        from .ndarray.sparse import RowSparseNDArray

        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        for k, o in _iter_kv(key, out):
            w = _arr_from_wire(self._rpc("pull", k))
            targets = o if isinstance(o, (list, tuple)) else [o]
            # per-key broadcast: computed fresh inside the loop — the
            # old `rids = list(rids) * len(targets)` rebinding leaked a
            # grown list into every subsequent key's iteration
            if len(rids) == 1 and len(targets) > 1:
                key_rids = list(rids) * len(targets)
            else:
                key_rids = list(rids)
            for t, rid in zip(targets, key_rids):
                ids = np.unique(np.asarray(
                    rid.asnumpy() if hasattr(rid, "asnumpy") else rid,
                    np.int64))
                if ids.size and (ids[0] < 0 or ids[-1] >= w.shape[0]):
                    # clipping silently returned the LAST row's data for
                    # any out-of-range id — wrong values are worse than
                    # an error (kvstore_local.h asserts the same bound)
                    raise MXNetError(
                        "row_sparse_pull: row_ids out of range for key "
                        "%r: [%d, %d] vs %d rows"
                        % (k, int(ids[0]), int(ids[-1]), w.shape[0]))
                taken = nd.array(w[ids])
                if isinstance(t, RowSparseNDArray):
                    newo = RowSparseNDArray(taken, nd.array(ids),
                                            w.shape, ctx=t.ctx)
                    t._rebind_sparse(newo)
                else:
                    dense = np.zeros(w.shape, w.dtype)
                    dense[ids] = w[ids]
                    t[:] = dense

    def barrier(self):
        self._rpc("barrier")

    def stop_server(self):
        self._rpc("stop")

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


def _iter_kv(key, value):
    """Pair keys with values. A single key takes the WHOLE value (which
    may be a per-device list); a key list zips positionally."""
    if isinstance(key, (list, tuple)):
        for k, v in zip(key, value):
            yield str(k), v
    else:
        yield str(key), value


# ---------------------------------------------------------------------------
# entry point (DMLC_ROLE dispatch)
# ---------------------------------------------------------------------------
def _init_kvstore_server_module():
    role = os.environ.get("DMLC_ROLE", "worker").lower()
    if role not in ("server", "scheduler"):
        return
    if role == "server" and os.environ.get("MXNET_KVSTORE_SERVER") == "1":
        host = os.environ.get("MXNET_PS_BIND_HOST", "127.0.0.1")
        port = int(os.environ.get("MXNET_PS_BIND_PORT",
                                  os.environ.get("DMLC_PS_ROOT_PORT", "0")))
        nw = int(os.environ.get("MXNET_TPU_NUM_WORKERS",
                                os.environ.get("DMLC_NUM_WORKER", "1")))
        server = KVStoreServer(host=host, port=port, num_workers=nw)
        print("kvstore_server listening on %s" % server.addr, flush=True)
        server.serve_forever()
        sys.exit(0)
    # serverless tier: nothing to run (see module docstring)
    sys.exit(0)


if __name__ == "__main__":
    _init_kvstore_server_module()
