"""Symbol — declarative graph composition.

Reference counterpart: ``python/mxnet/symbol/symbol.py`` over nnvm's
Graph/Node (SURVEY §2.2). TPU-native design: a Symbol is a DAG of python
nodes; ``bind`` hands the whole graph to the Executor which traces it into
ONE jitted XLA program (NNVM passes — PlanMemory, PlaceDevice, fusion — are
all performed by XLA). JSON save/load keeps the reference's file format
(``nodes``/``arg_nodes``/``heads``) so checkpoints interoperate
(ref: symbol.py:1187-1195 save, src/nnvm/legacy_json_util.cc).
"""
from __future__ import annotations

import json

from ..base import MXNetError, auto_name
from ..ops import registry as _reg

# ops whose trailing inputs are auxiliary states (not arguments):
# name -> set of input param names that are aux (ref: BatchNorm aux states)
_AUX_PARAMS = {
    "BatchNorm": {"moving_mean", "moving_var"},
    "BatchNorm_v1": {"moving_mean", "moving_var"},
    "batch_norm": {"moving_mean", "moving_var"},
}


class _Node:
    """One graph node: a variable (op is None) or an op application."""

    __slots__ = ("op", "attrs", "inputs", "name", "attr_dict", "_arity")

    def __init__(self, op, attrs, inputs, name, attr_dict=None, arity=None):
        self.op = op  # OpDef or None for variables
        self.attrs = attrs  # parsed op attrs
        self.inputs = inputs  # list[(node, out_index)]
        self.name = name
        self.attr_dict = attr_dict or {}  # user attrs (ctx_group, lr_mult, …)
        self._arity = arity  # input param names aligned with inputs

    def n_outputs(self):
        return 1 if self.op is None else self.op.n_outputs(self.attrs)

    def is_variable(self):
        return self.op is None


class Symbol:
    """An (ordered) set of output entries of a graph."""

    __slots__ = ("_entries",)

    def __init__(self, entries):
        self._entries = list(entries)  # list[(node, out_index)]

    # -- construction --------------------------------------------------------
    @property
    def name(self):
        if len(self._entries) == 1:
            return self._entries[0][0].name
        return None

    def __repr__(self):
        return "<Symbol %s>" % (self.name or "group [%d]" % len(self._entries))

    def __getitem__(self, index):
        if isinstance(index, str):
            for i, nm in enumerate(self.list_outputs()):
                if nm == index:
                    return Symbol([self._entries[i]])
            raise MXNetError("no output named %r" % index)
        entries = self._entries[index]
        if isinstance(index, slice):
            return Symbol(entries)
        return Symbol([entries])

    def __len__(self):
        return len(self._entries)

    def __iter__(self):
        for i in range(len(self._entries)):
            yield self[i]

    @property
    def outputs(self):
        return [self[i] for i in range(len(self._entries))]

    def get_internals(self):
        """Symbol grouping every internal output (ref: symbol.py get_internals)."""
        entries = []
        for node in self._topo():
            for i in range(node.n_outputs()):
                entries.append((node, i))
        return Symbol(entries)

    def get_children(self):
        node = self._entries[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    # -- attributes ----------------------------------------------------------
    def attr(self, key):
        return self._entries[0][0].attr_dict.get(key)

    def _set_attr(self, **kwargs):
        self._entries[0][0].attr_dict.update(kwargs)

    def attr_dict(self):
        out = {}
        for node in self._topo():
            d = dict(node.attr_dict)
            if node.op is not None:
                d.update({k: str(v) for k, v in node.attrs.items()})
            if d:
                out[node.name] = d
        return out

    def list_attr(self):
        node = self._entries[0][0]
        d = dict(node.attr_dict)
        if node.op is not None:
            d.update({k: str(v) for k, v in node.attrs.items()})
        return d

    # -- graph traversal -----------------------------------------------------
    def _topo(self):
        seen = set()
        order = []

        def visit(node):
            if id(node) in seen:
                return
            seen.add(id(node))
            for inp, _ in node.inputs:
                visit(inp)
            order.append(node)

        for node, _ in self._entries:
            visit(node)
        return order

    def _var_nodes(self):
        return [n for n in self._topo() if n.is_variable()]

    def data_dependent_nodes(self, dynamic_names):
        """Topo indices (into :meth:`_topo` order) of every node whose
        value depends on any variable named in ``dynamic_names``.

        The bind-time split behind serving constant folding
        (``mxnet_tpu/serving/predictor.py``): a node NOT in this set is
        a pure function of the remaining variables (the weights), so an
        AOT bind can evaluate it once per parameter swap instead of once
        per request."""
        dynamic_names = set(dynamic_names)
        nodes = self._topo()
        node_ids = {id(n): i for i, n in enumerate(nodes)}
        dep = set()
        for i, node in enumerate(nodes):
            if node.is_variable():
                if node.name in dynamic_names:
                    dep.add(i)
                continue
            if any(node_ids[id(inp)] in dep for inp, _ in node.inputs):
                dep.add(i)
        return dep

    def _aux_names_set(self):
        aux = []
        for node in self._topo():
            if node.op is None:
                continue
            aux_params = set(_AUX_PARAMS.get(node.op.name, ()))
            aux_params |= set(node.op.aux_state_outputs)
            if not aux_params or node._arity is None:
                continue
            for pname, (inode, _) in zip(node._arity, node.inputs):
                if pname in aux_params and inode.is_variable():
                    aux.append(inode.name)
        return set(aux)

    def list_arguments(self):
        aux = self._aux_names_set()
        return [n.name for n in self._var_nodes() if n.name not in aux]

    def list_auxiliary_states(self):
        aux = self._aux_names_set()
        return [n.name for n in self._var_nodes() if n.name in aux]

    def list_inputs(self):
        return [n.name for n in self._var_nodes()]

    def list_outputs(self):
        out = []
        for node, idx in self._entries:
            if node.is_variable():
                out.append(node.name)
            else:
                n_out = node.n_outputs()
                if n_out == 1:
                    out.append(node.name + "_output")
                else:
                    out.append("%s_output%d" % (node.name, idx))
        return out

    # -- composition ---------------------------------------------------------
    def __call__(self, *args, **kwargs):
        """Compose: substitute the graph's free variables
        (ref: symbol.py __call__/_compose)."""
        name = kwargs.pop("name", None)
        variables = self._var_nodes()
        mapping = {}
        if args:
            if len(args) > len(variables):
                raise MXNetError("too many positional args to compose")
            for var, sym in zip(variables, args):
                mapping[var.name] = sym
        for k, v in kwargs.items():
            mapping[k] = v
        return self._substitute(mapping)

    def _substitute(self, mapping):
        memo = {}

        def rebuild(node):
            if id(node) in memo:
                return memo[id(node)]
            if node.is_variable() and node.name in mapping:
                repl = mapping[node.name]
                ent = repl._entries[0]
                memo[id(node)] = ent
                return ent
            if node.is_variable():
                memo[id(node)] = (node, 0)
                return (node, 0)
            new_inputs = []
            for inp, idx in node.inputs:
                rn, ri = rebuild(inp)
                new_inputs.append((rn, idx if rn is inp else _remap_idx(idx, ri)))
            new_node = _Node(node.op, node.attrs, new_inputs, node.name, dict(node.attr_dict), node._arity)
            memo[id(node)] = (new_node, 0)
            return (new_node, 0)

        def _remap_idx(orig, repl):
            return repl if orig == 0 else orig

        entries = []
        for node, idx in self._entries:
            rn, ri = rebuild(node)
            entries.append((rn, idx if rn.n_outputs() > idx else ri))
        return Symbol(entries)

    # -- inference -----------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        from ..executor import infer_graph_shapes

        try:
            return infer_graph_shapes(self, kwargs, partial=False)
        except MXNetError:
            raise

    def infer_shape_partial(self, *args, **kwargs):
        from ..executor import infer_graph_shapes

        return infer_graph_shapes(self, kwargs, partial=True)

    def infer_type(self, *args, **kwargs):
        from ..executor import infer_graph_types

        return infer_graph_types(self, kwargs)

    # -- binding -------------------------------------------------------------
    def bind(self, ctx, args, args_grad=None, grad_req="write", aux_states=None,
             group2ctx=None, shared_exec=None):
        from ..executor import Executor

        return Executor(self, ctx, args, args_grad, grad_req, aux_states, group2ctx=group2ctx)

    def simple_bind(self, ctx, grad_req="write", type_dict=None, group2ctx=None,
                    shared_arg_names=None, shared_exec=None, shared_buffer=None, **kwargs):
        from ..executor import simple_bind

        return simple_bind(self, ctx, grad_req=grad_req, type_dict=type_dict,
                           shared_exec=shared_exec, group2ctx=group2ctx,
                           **kwargs)

    def eval(self, ctx=None, **kwargs):
        from ..context import current_context

        ctx = ctx or current_context()
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    # -- gradient ------------------------------------------------------------
    def gradient(self, wrt):
        raise MXNetError("symbol.gradient: use bind().backward instead")

    # -- serialization (reference JSON format) -------------------------------
    def tojson(self):
        nodes = self._topo()
        node_ids = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        arg_nodes = []
        for i, node in enumerate(nodes):
            if node.is_variable():
                arg_nodes.append(i)
                jnodes.append({"op": "null", "name": node.name, "inputs": []})
            else:
                jnodes.append(
                    {
                        "op": node.op.name,
                        "name": node.name,
                        "attrs": {k: str(v) for k, v in node.attrs.items()},
                        "inputs": [[node_ids[id(inp)], idx, 0] for inp, idx in node.inputs],
                    }
                )
            if node.attr_dict:
                jnodes[-1].setdefault("attrs", {}).update(
                    {k: str(v) for k, v in node.attr_dict.items()}
                )
        heads = [[node_ids[id(n)], idx, 0] for n, idx in self._entries]
        return json.dumps(
            {
                "nodes": jnodes,
                "arg_nodes": arg_nodes,
                "node_row_ptr": list(range(len(jnodes) + 1)),
                "heads": heads,
                "attrs": {"mxnet_version": ["int", 10000]},
            },
            indent=2,
        )

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # debug
    def debug_str(self):
        lines = []
        for node in self._topo():
            kind = "Variable" if node.is_variable() else node.op.name
            lines.append(
                "%s %s(%s)" % (kind, node.name, ", ".join(i.name for i, _ in node.inputs))
            )
        return "\n".join(lines)

    # -- NDArray-ish sugar on symbols ---------------------------------------
    def _apply(self, opname, other=None, scalar_op=None, reverse=False, **attrs):
        from .register import create_symbol

        if other is None:
            return create_symbol(_reg.get(opname), [self], attrs)
        if isinstance(other, Symbol):
            args = [other, self] if reverse else [self, other]
            return create_symbol(_reg.get(opname), args, attrs)
        args = [self]
        return create_symbol(_reg.get(scalar_op), args, {"scalar": float(other)})

    def __add__(self, other):
        return self._apply("broadcast_add", other, "_plus_scalar")

    def __radd__(self, other):
        return self._apply("broadcast_add", other, "_plus_scalar")

    def __sub__(self, other):
        return self._apply("broadcast_sub", other, "_minus_scalar")

    def __rsub__(self, other):
        return self._apply("broadcast_sub", other, "_rminus_scalar", reverse=True)

    def __mul__(self, other):
        return self._apply("broadcast_mul", other, "_mul_scalar")

    def __rmul__(self, other):
        return self._apply("broadcast_mul", other, "_mul_scalar")

    def __truediv__(self, other):
        return self._apply("broadcast_div", other, "_div_scalar")

    def __rtruediv__(self, other):
        return self._apply("broadcast_div", other, "_rdiv_scalar", reverse=True)

    def __pow__(self, other):
        return self._apply("broadcast_power", other, "_power_scalar")

    def __neg__(self):
        return self._apply("negative")

    def reshape(self, shape):
        return self._apply("Reshape", shape=tuple(shape))

    def sum(self, axis=None, keepdims=False):
        return self._apply("sum", axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._apply("mean", axis=axis, keepdims=keepdims)

    def astype(self, dtype):
        return self._apply("Cast", dtype=str(dtype))


def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
        init=None, stype=None, **kwargs):
    """Create a symbolic variable (ref: symbol.py var/Variable)."""
    from ..attribute import current_attrs

    attr_dict = current_attrs()  # active AttrScope attrs (explicit wins)
    attr_dict.update(attr or {})
    if shape is not None:
        attr_dict["__shape__"] = tuple(shape)
    if dtype is not None:
        attr_dict["__dtype__"] = str(dtype)
    if lr_mult is not None:
        attr_dict["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        attr_dict["__wd_mult__"] = wd_mult
    if init is not None:
        attr_dict["__init__"] = init if isinstance(init, str) else init.dumps()
    attr_dict.update(kwargs)
    node = _Node(None, {}, [], name, attr_dict)
    return Symbol([(node, 0)])


Variable = var


def Group(symbols):
    entries = []
    for s in symbols:
        entries.extend(s._entries)
    return Symbol(entries)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str):
    """Load symbol JSON — current format and the pre-NNVM legacy format
    (2-element input entries, ``param``/``attr`` keys; the reference's
    LoadLegacyJSON upgrade chain, src/nnvm/legacy_json_util.cc:30-116,
    fixture tests/python/unittest/save_000800.json)."""
    data = json.loads(json_str)
    jnodes = data["nodes"]
    # jmap[i] must stay aligned with the file's node indices — synthesized
    # aux variables are wired into inputs directly, never indexed
    jmap = []
    for jn in jnodes:
        # legacy files put user attrs under "attr", modern under "attrs"
        jattrs = jn.get("attrs", jn.get("attr", {}))
        if jn["op"] == "null":
            user = dict(jattrs)
            user.update(jn.get("param", {}))
            node = _Node(None, {}, [], jn["name"], user)
        else:
            op = _reg.get(jn["op"])
            raw_attrs = dict(jn.get("param", {}))
            raw_attrs.update(jattrs)
            user_attrs = {
                k: v for k, v in raw_attrs.items()
                if k.startswith("__") or k not in op.attr_defaults}
            op_attrs = {k: v for k, v in raw_attrs.items()
                        if not k.startswith("__") and k in op.attr_defaults}
            attrs = op.parse_attrs(op_attrs)
            inputs = [(jmap[e[0]], e[1]) for e in jn["inputs"]]
            # legacy upgrade: pre-NNVM graphs omit aux-state inputs
            # (BatchNorm moving_mean/var etc.) — synthesize the variables
            # exactly as the reference's legacy_op_util.cc adaptation does
            if not op.var_inputs:
                for aux_name in op.input_names[len(inputs):]:
                    if aux_name in ("moving_mean", "moving_var"):
                        aux_node = _Node(None, {}, [],
                                         "%s_%s" % (jn["name"], aux_name))
                        inputs.append((aux_node, 0))
            arity = _infer_arity(op, len(inputs))
            node = _Node(op, attrs, inputs, jn["name"], user_attrs, arity)
        jmap.append(node)
    heads = data.get("heads", [[len(jmap) - 1, 0, 0]])
    return Symbol([(jmap[h[0]], h[1]) for h in heads])


def _infer_arity(op, n_inputs):
    if op.var_inputs:
        return tuple("arg%d" % i for i in range(n_inputs))
    return op.input_names[:n_inputs]
