"""Sharded dataset service (ISSUE 17): exactly-once record IO.

Submodules: ``lease`` (stdlib-only shard-lease arithmetic, shared by
the tracker), ``writer`` (sharding record writer + manifest),
``service`` (lease-driven streams, decode pool, batch iterator),
``errors`` (typed DataPlaneError hierarchy). Exports resolve lazily
(PEP 562) so ``from .data.lease import ShardLeaseBook`` inside the
tracker never drags numpy/jax into its millisecond import budget.
"""
from __future__ import annotations

_EXPORTS = {
    "ShardLeaseBook": "lease",
    "LocalLeaseAuthority": "lease",
    "LeaseError": "lease",
    "DataPlaneError": "errors",
    "LeaseLostError": "errors",
    "CursorCorruptError": "errors",
    "ShardCorruptError": "errors",
    "ManifestCorruptError": "errors",
    "write_record_shards": "writer",
    "load_manifest": "writer",
    "manifest_path": "writer",
    "ShardedRecordStream": "service",
    "ShardedBatchIter": "service",
    "record_seed": "service",
    "decode_raw": "service",
    "decode_image_f32": "service",
    "iter_manifest_records": "service",
    "merge_ledgers": "service",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module("." + _EXPORTS[name], __name__)
        return getattr(mod, name)
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))
