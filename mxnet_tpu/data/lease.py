"""Shard-lease bookkeeping for the sharded dataset service (ISSUE 17).

One :class:`ShardLeaseBook` tracks one dataset's epoch state: which
worker rank holds which record-file shard, the within-shard resume
cursor (record index) each holder last committed, and which shards are
finished for the current epoch. The book is **pure state** — no
sockets, no threads, no clock of its own (callers pass ``now`` from
``time.monotonic()``) — so the exact same arithmetic runs embedded in
the tracker (``tracker.py`` data ops, under the tracker's condition
lock) and in-process behind :class:`LocalLeaseAuthority` for
single-worker jobs, benches and tests. A divergence between the
distributed and local lease semantics would make every local test a
lie about the fleet, so there is exactly one implementation.

Exactly-once-per-epoch contract:

- a shard is leased to at most one rank at a time; the lease carries
  the shard id and the resume cursor, and must be renewed (cursor
  commit) before ``ttl`` elapses or it returns to the pool;
- a dead/closed rank's unfinished shards return to the pool with
  their cursors intact (``release_owner``) — the next acquirer, the
  rank's own respawn or a survivor, resumes at the committed cursor;
- the epoch advances only when every shard was completed at exactly
  its record count, and rolling resets every cursor to zero.

This module is deliberately **stdlib-only** (no jax/numpy): the
tracker imports it lazily and must stay importable in milliseconds.
"""
from __future__ import annotations


class LeaseError(ValueError):
    """A lease op was structurally invalid (bad shard id, cursor out
    of range, cursor moving backwards, mismatched re-registration).
    The data-plane client wraps this into the typed
    ``DataPlaneError`` hierarchy at the reader."""


class ShardLeaseBook:
    """Per-dataset lease state machine. Not thread-safe: the embedding
    context (tracker / LocalLeaseAuthority) provides the lock."""

    def __init__(self, name, shard_records, ttl):
        if not isinstance(shard_records, (list, tuple)) or not shard_records:
            raise LeaseError(
                "dataset %r: shard_records must be a non-empty list of "
                "record counts, got %r" % (name, shard_records))
        counts = []
        for i, n in enumerate(shard_records):
            if isinstance(n, bool) or not isinstance(n, int) or n < 0:
                raise LeaseError(
                    "dataset %r: shard %d record count %r is not an "
                    "integer >= 0" % (name, i, n))
            counts.append(int(n))
        self.name = str(name)
        self.ttl = float(ttl)
        if not self.ttl > 0:
            raise LeaseError("dataset %r: lease ttl must be > 0, got %r"
                             % (name, ttl))
        self.epoch = 0
        self.rebalances = 0          # leases returned by death/close/TTL
        self.shards = [
            {"shard": i, "records": n, "cursor": 0, "owner": None,
             "deadline": 0.0, "done": False, "last_owner": None}
            for i, n in enumerate(counts)]

    # -- helpers -----------------------------------------------------------
    def record_counts(self):
        return [s["records"] for s in self.shards]

    def _shard(self, shard):
        if not isinstance(shard, int) or isinstance(shard, bool) \
                or not 0 <= shard < len(self.shards):
            raise LeaseError(
                "dataset %r: shard id %r out of range [0, %d)"
                % (self.name, shard, len(self.shards)))
        return self.shards[shard]

    def _check_cursor(self, s, cursor, op):
        if isinstance(cursor, bool) or not isinstance(cursor, int) \
                or cursor < 0 or cursor > s["records"]:
            raise LeaseError(
                "dataset %r shard %d: %s cursor %r out of range "
                "[0, %d]" % (self.name, s["shard"], op, cursor,
                             s["records"]))
        if cursor < s["cursor"]:
            raise LeaseError(
                "dataset %r shard %d: %s cursor %d moved backwards "
                "(committed %d) — a rewound cursor would re-consume "
                "records" % (self.name, s["shard"], op, cursor,
                             s["cursor"]))

    # -- ops ---------------------------------------------------------------
    def expire(self, now):
        """Return TTL-expired leases to the pool (cursors kept).
        Returns the released ``[{"shard", "rank", "cursor"}]``."""
        released = []
        for s in self.shards:
            if s["owner"] is not None and now > s["deadline"]:
                released.append({"shard": s["shard"], "rank": s["owner"],
                                 "cursor": s["cursor"]})
                s["last_owner"] = s["owner"]
                s["owner"] = None
                self.rebalances += 1
        return released

    def release_owner(self, rank, now):
        """A rank died / closed its stream: every shard it holds
        returns to the pool with its committed cursor — the rebalance
        the elastic-respawn story depends on. Returns the released
        ``[{"shard", "cursor"}]``."""
        released = []
        for s in self.shards:
            if s["owner"] == rank:
                released.append({"shard": s["shard"],
                                 "cursor": s["cursor"]})
                s["last_owner"] = rank
                s["owner"] = None
                self.rebalances += 1
        return released

    def acquire(self, rank, epoch, now):
        """One rank asks for work in ``epoch``. Replies (plain dict,
        wire-safe) with ``status`` one of:

        - ``lease``: shard id + resume cursor + record count; prefers
          the rank's own previous shards (a respawn resumes exactly
          where its predecessor committed), then the lowest free id;
        - ``epoch_done``: every shard completed for ``epoch`` — the
          caller moves to ``epoch + 1``;
        - ``wait``: free shards exhausted but peers still hold leases
          (retry shortly);
        - ``behind``: the book already rolled past ``epoch`` (the
          caller fast-forwards to the returned ``epoch``).
        """
        if isinstance(epoch, bool) or not isinstance(epoch, int) \
                or epoch < 0:
            raise LeaseError("dataset %r: epoch %r is not an integer >= 0"
                             % (self.name, epoch))
        self.expire(now)
        if epoch == self.epoch + 1 \
                and all(s["done"] for s in self.shards):
            self.epoch += 1
            for s in self.shards:
                s["cursor"] = 0
                s["owner"] = None
                s["deadline"] = 0.0
                s["done"] = False
        if epoch < self.epoch:
            return {"status": "behind", "epoch": self.epoch}
        if epoch > self.epoch:
            # asking for a future epoch while this one still runs
            return {"status": "wait", "epoch": self.epoch}
        free = [s for s in self.shards
                if not s["done"] and s["owner"] is None]
        if not free:
            if all(s["done"] for s in self.shards):
                return {"status": "epoch_done", "epoch": self.epoch}
            return {"status": "wait", "epoch": self.epoch}
        mine = [s for s in free if s["last_owner"] == rank]
        s = min(mine or free, key=lambda s: s["shard"])
        rebalanced = s["last_owner"] is not None \
            and s["last_owner"] != rank
        s["owner"] = rank
        s["deadline"] = now + self.ttl
        return {"status": "lease", "epoch": self.epoch,
                "shard": s["shard"], "cursor": s["cursor"],
                "records": s["records"], "rebalanced": rebalanced,
                "resumed": s["cursor"] > 0}

    def renew(self, rank, epoch, shard, cursor, now):
        """Commit a cursor and refresh the lease deadline. Returns
        ``{"ok": True, "cursor": c}`` or — when the lease was
        rebalanced away / the epoch rolled — ``{"ok": False, "lost":
        reason}`` so the holder can raise the typed lease-lost error
        (no string-matching on transport errors)."""
        s = self._shard(shard)
        if epoch != self.epoch:
            return {"ok": False,
                    "lost": "epoch rolled to %d (lease was for %d)"
                            % (self.epoch, epoch)}
        if s["owner"] != rank:
            return {"ok": False,
                    "lost": "shard %d is %s (lease holder is now %r)"
                            % (shard,
                               "done" if s["done"] else "rebalanced",
                               s["owner"])}
        self._check_cursor(s, cursor, "renew")
        s["cursor"] = cursor
        s["deadline"] = now + self.ttl
        return {"ok": True, "cursor": cursor}

    def complete(self, rank, epoch, shard, cursor, now):
        """Mark a shard finished for the epoch. The cursor must equal
        the shard's record count — completing early would silently
        skip the tail, the exact failure the exactly-once contract
        exists to prevent. Idempotent for the completing rank."""
        s = self._shard(shard)
        if epoch != self.epoch:
            return {"ok": False,
                    "lost": "epoch rolled to %d (completion was for %d)"
                            % (self.epoch, epoch)}
        if s["done"]:
            return {"ok": True, "epoch_done":
                    all(x["done"] for x in self.shards)}
        if s["owner"] != rank:
            return {"ok": False,
                    "lost": "shard %d rebalanced (holder is now %r)"
                            % (shard, s["owner"])}
        if cursor != s["records"]:
            raise LeaseError(
                "dataset %r shard %d: completed at cursor %d but the "
                "shard has %d records — refusing to mark a partially "
                "read shard done" % (self.name, shard, cursor,
                                     s["records"]))
        s["cursor"] = cursor
        s["done"] = True
        s["owner"] = None
        s["last_owner"] = rank
        return {"ok": True,
                "epoch_done": all(x["done"] for x in self.shards)}

    def snapshot(self):
        """Plain-data view (tests / the tracker's data_state op)."""
        return {"name": self.name, "epoch": self.epoch,
                "ttl": self.ttl, "rebalances": self.rebalances,
                "shards": [dict(s) for s in self.shards]}


class LocalLeaseAuthority:
    """In-process lease authority for jobs with no tracker topology
    (single worker, benches, unit tests): the same ShardLeaseBook
    arithmetic behind a thread lock and a real clock. Several streams
    may share one authority to exercise rebalance/handoff locally."""

    def __init__(self, ttl=None):
        import threading
        import time

        self._lock = threading.Lock()
        self._books = {}
        self._ttl = ttl
        self._clock = time.monotonic

    def _resolve_ttl(self):
        if self._ttl is not None:
            return float(self._ttl)
        from .. import config

        return config.get_positive_float("MXNET_DATA_LEASE_TTL")

    def data_init(self, name, shards):
        with self._lock:
            book = self._books.get(name)
            if book is None:
                book = ShardLeaseBook(name, list(shards),
                                      self._resolve_ttl())
                self._books[name] = book
            elif book.record_counts() != [int(n) for n in shards]:
                raise LeaseError(
                    "dataset %r already registered with different "
                    "shards (%r != %r)" % (name, book.record_counts(),
                                           list(shards)))
            return {"epoch": book.epoch, "shards": len(book.shards)}

    def _book(self, name):
        book = self._books.get(name)
        if book is None:
            raise LeaseError("dataset %r was never data_init'd" % name)
        return book

    def data_acquire(self, name, rank, epoch):
        with self._lock:
            return self._book(name).acquire(rank, epoch, self._clock())

    def data_renew(self, name, rank, epoch, shard, cursor):
        with self._lock:
            return self._book(name).renew(rank, epoch, shard, cursor,
                                          self._clock())

    def data_complete(self, name, rank, epoch, shard, cursor):
        with self._lock:
            return self._book(name).complete(rank, epoch, shard, cursor,
                                             self._clock())

    def data_release(self, name, rank):
        with self._lock:
            return self._book(name).release_owner(rank, self._clock())

    def data_state(self, name):
        with self._lock:
            return self._book(name).snapshot()
