"""Sharded dataset service: exactly-once record streams for the fleet.

The read path (:class:`ShardedRecordStream`) leases record-file shards
from an authority — the job tracker when a launch.py topology is
configured, an in-process :class:`~.lease.LocalLeaseAuthority`
otherwise — and streams decoded records with a per-record consumption
ledger. The ledger line is flushed **before** the cursor commit, and
every lease acquisition reconciles its resume cursor against
``max(tracker cursor, ledger max + 1)`` over *all* ledger files in the
shared ledger directory, so neither crash ordering (ledgered but not
committed / committed but not ledgered is impossible) nor
steal-by-survivor can double- or under-consume a record.

Decode runs off the training thread when ``MXNET_DATA_WORKERS`` > 0
(bounded process pool) and record seeds derive from
``(epoch, shard, record-index)`` in deterministic mode — never from
worker identity — so an elastically rebalanced shard decodes to the
exact bytes its original owner would have produced.

:class:`ShardedBatchIter` adapts the stream to the ``io.DataIter``
batch contract so it drops into ``parallel/feed.py``'s DeviceQueueIter
unchanged. Telemetry rides the profiler's ``ioStats`` family
(``profiler.io_record``) and dumps with ``dump_profile``.
"""
from __future__ import annotations

import glob
import logging
import os
import queue
import struct
import threading
import time

import numpy as np

from .. import recordio
from ..base import MXNetError
from .errors import (CursorCorruptError, LeaseLostError,
                     ManifestCorruptError, ShardCorruptError)  # noqa: F401
from .lease import LeaseError, LocalLeaseAuthority
from .writer import load_manifest

log = logging.getLogger("mxnet_tpu.data")

_ACQUIRE_RETRY = 0.05       # poll interval while peers hold all shards
_CHUNK_RECORDS = 64         # records per read/decode/ledger unit


# ---------------------------------------------------------------------------
# deterministic per-record seeding
# ---------------------------------------------------------------------------
def record_seed(epoch, shard, index, salt=0):
    """64-bit decode/augment seed from the record's *position*
    (epoch, shard, record index) — never the worker consuming it — so
    a shard rebalanced to a survivor mid-epoch decodes byte-identically
    to what its first owner would have produced (splitmix64 mix)."""
    x = ((epoch & 0xFFFF) << 48) ^ ((shard & 0xFFFF) << 32) \
        ^ (index & 0xFFFFFFFF) ^ ((salt & 0xFFFFFFFF) << 16)
    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    z = x
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return z ^ (z >> 31)


# ---------------------------------------------------------------------------
# decode functions (module-level: process-pool workers must import them)
# ---------------------------------------------------------------------------
def decode_raw(raw, seed):
    """Identity decode: the record's bytes, untouched."""
    return raw


def decode_image_f32(raw, seed, shape=(3, 32, 32)):
    """Bench/ResNet decode: ``<f label><uint8 pixels>`` record to a
    float32 CHW array in [0, 1] plus its label, with a seed-driven
    horizontal-flip augmentation (the determinism probe: flip choice
    must follow the record seed, not the decoding worker)."""
    n = int(np.prod(shape))
    if len(raw) != 4 + n:
        raise ValueError("image record is %d bytes, expected %d"
                         % (len(raw), 4 + n))
    (label,) = struct.unpack_from("<f", raw, 0)
    img = np.frombuffer(raw, dtype=np.uint8, count=n, offset=4)
    img = img.reshape(shape).astype(np.float32) / 255.0
    if seed & 1:
        img = img[..., ::-1].copy()
    return img, np.float32(label)


def _decode_chunk(decode, jobs):
    """Pool task: decode a chunk of (raw, seed) pairs in order."""
    return [decode(raw, seed) for raw, seed in jobs]


# ---------------------------------------------------------------------------
# lease-free direct read (eval passes, replay baselines)
# ---------------------------------------------------------------------------
def iter_manifest_records(manifest_path):
    """Yield every ``(shard, index, raw_bytes)`` of a dataset in shard
    order, without leases — for full-dataset eval and replay baselines
    where every worker intentionally reads everything."""
    manifest = load_manifest(manifest_path)
    root = os.path.dirname(os.fspath(manifest_path))
    for sid, entry in enumerate(manifest["shards"]):
        reader = _open_shard(manifest_path, root, entry)
        try:
            for idx in range(entry["records"]):
                raw = _read_next(reader, root, entry, idx)
                yield sid, idx, raw
        finally:
            reader.close()


def merge_ledgers(ledger_dir):
    """Consumption counts ``{(epoch, shard, index): n}`` merged over
    every ``*.ledger`` file in ``ledger_dir`` — the exactly-once
    evidence the chaos matrix asserts on (every n must be 1)."""
    counts = {}
    for path in sorted(glob.glob(os.path.join(os.fspath(ledger_dir),
                                              "*.ledger"))):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                epoch, shard, index = (int(x) for x in line.split("\t"))
                key = (epoch, shard, index)
                counts[key] = counts.get(key, 0) + 1
    return counts


def _ledger_resume_cursor(ledger_dir, epoch, shard):
    """Highest ledgered record index + 1 for (epoch, shard) across all
    ledger files, or 0 — the crash-safe floor for a resume cursor."""
    if not ledger_dir:
        return 0
    top = -1
    for path in glob.glob(os.path.join(os.fspath(ledger_dir), "*.ledger")):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    e, s, i = (int(x) for x in line.split("\t"))
                    if e == epoch and s == shard and i > top:
                        top = i
        except (OSError, ValueError) as exc:
            raise CursorCorruptError(
                "ledger %s is unreadable/garbled (%s) — refusing to "
                "guess a resume cursor" % (path, exc))
    return top + 1


def _open_shard(manifest_path, root, entry):
    path = os.path.join(root, entry["file"])
    try:
        reader = recordio.MXIndexedRecordIO(path + ".idx", path, "r")
    except (OSError, MXNetError) as exc:
        raise ShardCorruptError("record shard %s: cannot open (%s)"
                                % (path, exc))
    if len(reader.keys) != entry["records"]:
        reader.close()
        log.warning("record shard %s: index has %d entries, manifest "
                    "promises %d", path, len(reader.keys),
                    entry["records"])
        raise ShardCorruptError(
            "record shard %s: index has %d entries, manifest promises "
            "%d (truncated or stale .idx)"
            % (path, len(reader.keys), entry["records"]))
    return reader


def _read_next(reader, root, entry, index):
    """Read the record at ``index`` (reader already positioned there).
    The python recordio reader returns None at a short header — a
    truncated file looks like a clean EOF — so running out before the
    manifest's count is the truncation signal, and a garbage magic
    raises from the reader itself; both become ShardCorruptError."""
    path = os.path.join(root, entry["file"])
    try:
        raw = reader.read()
    except MXNetError as exc:
        log.warning("record shard %s: garbage at record %d (%s)",
                    path, index, exc)
        raise ShardCorruptError("record shard %s: garbage at record %d "
                                "(%s)" % (path, index, exc))
    if raw is None:
        log.warning("record shard %s: EOF at record %d of %d",
                    path, index, entry["records"])
        raise ShardCorruptError(
            "record shard %s: EOF at record %d but manifest promises "
            "%d records (truncated file)"
            % (path, index, entry["records"]))
    return raw


# ---------------------------------------------------------------------------
# the stream
# ---------------------------------------------------------------------------
class ShardedRecordStream:
    """Exactly-once record stream over one dataset's shards.

    ``epoch_records()`` yields ``(shard, index, decoded_record)`` for
    one full *pass* of this worker's share of the current epoch;
    ``self.epoch`` then points at the next epoch. ``rank`` identifies
    the consumer to the lease authority (defaults to the DMLC rank).
    """

    def __init__(self, manifest_path, lease_client=None, rank=None,
                 decode=None, ledger_dir=None, deterministic=None,
                 workers=None, prefetch=None, chunk=_CHUNK_RECORDS):
        from .. import config

        self._manifest_path = os.fspath(manifest_path)
        self._root = os.path.dirname(self._manifest_path)
        self._manifest = load_manifest(self._manifest_path)
        self.name = self._manifest["dataset"]
        self._decode = decode or decode_raw
        self._chunk = max(1, int(chunk))
        self._deterministic = config.get_strict_bool(
            "MXNET_DATA_DETERMINISTIC") if deterministic is None \
            else bool(deterministic)
        self._workers = config.get_nonneg_int("MXNET_DATA_WORKERS") \
            if workers is None else int(workers)
        self._prefetch = config.get_nonneg_int("MXNET_DATA_PREFETCH") \
            if prefetch is None else int(prefetch)
        self._pool = None
        self._thread = None
        self._stop = threading.Event()
        self._gen = None
        self._ledger_dir = os.fspath(ledger_dir) if ledger_dir else None
        self._ledger_file = None
        self._closed = False

        restart = 0
        if lease_client is not None:
            self._auth = lease_client
        else:
            from .. import tracker

            client = tracker.worker_client()
            if client is not None:
                self._auth = client
                if rank is None:
                    rank = client.rank
                restart = client.restart_count
            else:
                self._auth = LocalLeaseAuthority()
        self.rank = int(rank) if rank is not None else \
            int(os.environ.get("DMLC_WORKER_ID", "0") or 0)
        # the decode-seed salt outside deterministic mode: worker
        # identity, exactly what deterministic mode must NOT depend on
        self._salt = 0 if self._deterministic \
            else (self.rank << 8) ^ (restart + 1)

        counts = [s["records"] for s in self._manifest["shards"]]
        init = self._auth.data_init(self.name, counts)
        self.epoch = int(init.get("epoch", 0))
        if self._ledger_dir:
            os.makedirs(self._ledger_dir, exist_ok=True)

    # -- ledger ------------------------------------------------------------
    def _ledger(self):
        if self._ledger_file is None:
            path = os.path.join(
                self._ledger_dir,
                "rank%d-pid%d.ledger" % (self.rank, os.getpid()))
            self._ledger_file = open(path, "a")
        return self._ledger_file

    def _ledger_chunk(self, epoch, shard, start, count):
        if not self._ledger_dir:
            return
        f = self._ledger()
        for i in range(start, start + count):
            f.write("%d\t%d\t%d\n" % (epoch, shard, i))
        f.flush()

    # -- decode ------------------------------------------------------------
    def _decode_jobs(self, epoch, shard, start, raws):
        return [(raw, record_seed(epoch, shard, start + i,
                                  salt=self._salt))
                for i, raw in enumerate(raws)]

    def _decode_chunk(self, jobs):
        from .. import profiler

        t0 = time.monotonic()
        if self._workers > 0:
            if self._pool is None:
                import multiprocessing

                self._pool = multiprocessing.get_context("spawn").Pool(
                    self._workers)
            n = max(1, len(jobs) // self._workers)
            parts = [jobs[i:i + n] for i in range(0, len(jobs), n)]
            out = self._pool.starmap(
                _decode_chunk, [(self._decode, p) for p in parts])
            decoded = [rec for part in out for rec in part]
        else:
            decoded = _decode_chunk(self._decode, jobs)
        profiler.io_record(decode_tasks=len(jobs),
                           decode_seconds=time.monotonic() - t0)
        return decoded

    # -- lease RPC adapters (tracker client and local authority share
    # the explicit-rank signature) --------------------------------------
    def _acquire(self, epoch):
        return self._auth.data_acquire(self.name, self.rank, epoch)

    def _renew(self, epoch, shard, cursor):
        return self._auth.data_renew(self.name, self.rank, epoch,
                                     shard, cursor)

    def _complete(self, epoch, shard, cursor):
        return self._auth.data_complete(self.name, self.rank, epoch,
                                        shard, cursor)

    # -- producer ----------------------------------------------------------
    def _produce_epoch(self, epoch):
        """Yield markers for one epoch pass: ``("chunk", shard, start,
        decoded, nbytes)``, ``("eof", shard, records)``, a final
        ``("roll", next_epoch)``. Runs on the prefetch thread when
        prefetch > 0, inline otherwise."""
        from .. import profiler

        while not self._stop.is_set():
            try:
                got = self._acquire(epoch)
            except LeaseError as exc:
                raise CursorCorruptError(str(exc))
            status = got["status"]
            if status == "epoch_done":
                yield ("roll", epoch + 1)
                return
            if status == "behind":
                yield ("roll", got["epoch"])
                return
            if status == "wait":
                time.sleep(_ACQUIRE_RETRY)
                continue
            shard, records = got["shard"], got["records"]
            cursor = got["cursor"]
            profiler.io_record(
                leases=1,
                rebalanced_leases=1 if got.get("rebalanced") else 0)
            # crash-safe resume floor: anything any incarnation
            # ledgered for this (epoch, shard) is already consumed
            floor = _ledger_resume_cursor(self._ledger_dir, epoch, shard)
            if max(cursor, floor) > records:
                raise CursorCorruptError(
                    "dataset %s shard %d: resume cursor %d beyond %d "
                    "records" % (self.name, shard, max(cursor, floor),
                                 records))
            if floor > cursor:
                renewed = self._renew(epoch, shard, floor)
                if not renewed.get("ok"):
                    profiler.io_record(lease_lost=1)
                    raise LeaseLostError(
                        "dataset %s shard %d: %s"
                        % (self.name, shard, renewed.get("lost")))
                cursor = floor
            if got.get("resumed") or floor > 0:
                profiler.io_record(resumes=1,
                                   resume_cursors={shard: cursor})
            entry = self._manifest["shards"][shard]
            if cursor >= records:
                yield ("eof", shard, records)
                continue
            reader = _open_shard(self._manifest_path, self._root, entry)
            try:
                try:
                    reader.seek(reader.idx[cursor])
                except KeyError:
                    raise ShardCorruptError(
                        "record shard %s: no index entry for cursor %d"
                        % (entry["file"], cursor))
                while cursor < records and not self._stop.is_set():
                    count = min(self._chunk, records - cursor)
                    t0 = time.monotonic()
                    raws = [_read_next(reader, self._root, entry,
                                       cursor + i)
                            for i in range(count)]
                    nbytes = sum(len(r) for r in raws)
                    profiler.io_record(
                        records=count, bytes=nbytes,
                        read_seconds=time.monotonic() - t0)
                    decoded = self._decode_chunk(
                        self._decode_jobs(epoch, shard, cursor, raws))
                    yield ("chunk", shard, cursor, decoded, nbytes)
                    cursor += count
            finally:
                reader.close()
            if cursor >= records:
                yield ("eof", shard, records)

    # -- consumer ----------------------------------------------------------
    def _source(self, epoch):
        """The marker source for one pass: the producer drained through
        a bounded queue when prefetch > 0 (read/decode overlap the
        training step), the raw generator otherwise (honest sync)."""
        from .. import profiler

        gen = self._produce_epoch(epoch)
        if self._prefetch <= 0:
            self._gen = gen
            return gen

        q = queue.Queue(maxsize=self._prefetch)
        DONE, ERROR = object(), object()

        def put_until_stop(item):
            while not self._stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            try:
                q.put_nowait(item)   # best-effort after stop
            except queue.Full:
                pass
            return False

        def drain():
            try:
                for marker in gen:
                    if not put_until_stop(marker):
                        gen.close()
                        return
                put_until_stop(DONE)
            except BaseException as exc:  # surfaced on the consumer
                put_until_stop((ERROR, exc))

        self._thread = threading.Thread(target=drain, daemon=True,
                                        name="mxnet-data-prefetch")
        self._thread.start()

        def consume():
            while True:
                depth = q.qsize()
                profiler.io_record(
                    queue_depth=depth,
                    prefetch_hits=1 if depth > 0 else 0,
                    prefetch_misses=0 if depth > 0 else 1)
                marker = q.get()
                if marker is DONE:
                    return
                if isinstance(marker, tuple) and marker[0] is ERROR:
                    raise marker[1]
                yield marker

        return consume()

    def epoch_records(self):
        """One pass over this worker's share of epoch ``self.epoch``:
        yields ``(shard, index, decoded_record)``, ledgering and
        committing each chunk before handing it out. On return,
        ``self.epoch`` is the next epoch to consume."""
        from .. import profiler

        if self._closed:
            raise RuntimeError("stream %s is closed" % self.name)
        epoch = self.epoch
        source = self._source(epoch)
        try:
            for marker in source:
                kind = marker[0]
                if kind == "chunk":
                    _, shard, start, decoded, _nbytes = marker
                    self._ledger_chunk(epoch, shard, start,
                                       len(decoded))
                    renewed = self._renew(epoch, shard,
                                          start + len(decoded))
                    if not renewed.get("ok"):
                        profiler.io_record(lease_lost=1)
                        raise LeaseLostError(
                            "dataset %s shard %d: %s"
                            % (self.name, shard, renewed.get("lost")))
                    for i, rec in enumerate(decoded):
                        yield shard, start + i, rec
                elif kind == "eof":
                    _, shard, records = marker
                    done = self._complete(epoch, shard, records)
                    if done.get("ok"):
                        profiler.io_record(shards_done=1)
                elif kind == "roll":
                    # possibly PAST epoch+1: a pass that joined an
                    # already-finished epoch ("behind") yields nothing
                    # and leaves self.epoch at the fleet's epoch — the
                    # caller's `while stream.epoch < N` loop decides
                    # whether another pass happens (never a phantom
                    # epoch past the caller's horizon)
                    self.epoch = marker[1]
                    profiler.io_record(epochs=1)
        finally:
            self._join_producer()

    def _join_producer(self):
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=10.0)
            self._thread = None
            self._stop = threading.Event()
        if self._gen is not None:
            self._gen.close()
            self._gen = None

    def state(self):
        return self._auth.data_state(self.name)

    def close(self):
        """Release leases back to the pool (cursors intact) and tear
        down the prefetch thread / decode pool / ledger handle."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._join_producer()
        try:
            self._auth.data_release(self.name, self.rank)
        except (MXNetError, LeaseError, OSError):
            pass  # tracker gone at teardown must not mask the exit
        if self._pool is not None:
            self._pool.terminate()
            self._pool.join()
            self._pool = None
        if self._ledger_file is not None:
            self._ledger_file.close()
            self._ledger_file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# DataIter adapter
# ---------------------------------------------------------------------------
class ShardedBatchIter:
    """Batch iterator over a :class:`ShardedRecordStream` speaking the
    ``io.DataIter`` contract (next/reset/provide_data/provide_label/
    batch_size), so it feeds ``parallel/feed.py``'s DeviceQueueIter
    directly. Decoded records must be ``(data, label)`` pairs; batches
    span shard boundaries and the epoch's remainder (< batch_size) is
    dropped. Per-batch input wait (time blocked assembling the batch)
    feeds the ioStats p50/p99 reservoir.

    Once an epoch ends, next() keeps raising StopIteration until
    reset() (the DataIter contract); after reset() the next call opens
    the NEXT lease-book epoch. A read-ahead consumer (DeviceQueueIter)
    that resets after its final epoch may therefore lease a chunk of an
    epoch nobody trains — those records stay resumable at the
    committed cursor because that epoch never completes."""

    def __init__(self, stream, batch_size, data_shape, label_shape=(),
                 data_name="data", label_name="softmax_label",
                 dtype=np.float32, label_dtype=np.float32):
        from ..io import DataDesc

        self.stream = stream
        self.batch_size = int(batch_size)
        self.provide_data = [DataDesc(data_name,
                                      (self.batch_size,) + tuple(data_shape),
                                      dtype)]
        self.provide_label = [DataDesc(label_name,
                                       (self.batch_size,) + tuple(label_shape),
                                       label_dtype)]
        self._records = None
        self._exhausted = False

    def __iter__(self):
        return self

    def reset(self):
        self._records = None
        self._exhausted = False

    def next(self):
        from .. import profiler
        from ..io import DataBatch

        # DataIter contract: once an epoch ends, keep raising until
        # reset() — otherwise a read-ahead consumer (DeviceQueueIter)
        # would silently lease+ledger records of an epoch nobody runs
        if self._exhausted:
            raise StopIteration
        if self._records is None:
            self._records = self.stream.epoch_records()
        t0 = time.monotonic()
        data, label = [], []
        try:
            for _shard, _idx, rec in self._records:
                d, l = rec
                data.append(d)
                label.append(l)
                if len(data) == self.batch_size:
                    break
        except BaseException:
            self._records = None
            raise
        wait = time.monotonic() - t0
        if len(data) < self.batch_size:
            self._records = None
            self._exhausted = True
            raise StopIteration
        profiler.io_record(batches=1, wait_seconds=wait,
                           wait_latencies=[wait])
        return DataBatch(data=[np.stack(data)],
                         label=[np.asarray(label)],
                         pad=0,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def __next__(self):
        return self.next()

    def close(self):
        self.stream.close()
