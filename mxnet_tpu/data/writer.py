"""Sharding record writer + manifest loader for the dataset service.

``write_record_shards`` splits a record list into ``num_shards``
contiguous record files (``recordio.py`` packs with ``.idx`` sidecars
so a resume cursor seeks in O(1)) and publishes an atomic JSON
manifest next to them. ``load_manifest`` is the read side, with the
schedule-table 5-way corruption matrix: missing file, garbage JSON,
top level not an object, version mismatch, malformed shard entry —
each is logged and raised as :class:`ManifestCorruptError`, never
silently skipped.

Shard files are written to a tmp name and ``os.replace``d into place,
so concurrent *deterministic* writers (every worker of a launch.py
job producing the identical dataset, the recommender example does
this) race benignly: last rename wins with byte-identical content.
"""
from __future__ import annotations

import json
import logging
import os

from .. import recordio
from ..checkpoint import atomic_write_bytes
from .errors import ManifestCorruptError

log = logging.getLogger("mxnet_tpu.data")

MANIFEST_VERSION = 1


def manifest_path(out_dir, name):
    return os.path.join(os.fspath(out_dir), "%s.manifest.json" % name)


def write_record_shards(out_dir, name, records, num_shards=None):
    """Write ``records`` (a list of ``bytes``) as ``name-%05d-of-%05d.rec``
    shard files under ``out_dir`` plus the dataset manifest. Records are
    split into contiguous blocks so shard ``i`` holds a stable,
    reproducible slice; ``num_shards`` defaults to the
    ``MXNET_DATA_SHARDS`` knob, capped at ``len(records)`` so no shard
    is empty. Returns the manifest path."""
    from .. import config

    if num_shards is None:
        num_shards = config.get_positive_int("MXNET_DATA_SHARDS")
    if not records:
        raise ValueError("write_record_shards: dataset %r has no records"
                         % name)
    for i, rec in enumerate(records):
        if not isinstance(rec, (bytes, bytearray)):
            raise TypeError(
                "write_record_shards: record %d of dataset %r is %s, "
                "expected bytes" % (i, name, type(rec).__name__))
    num_shards = max(1, min(int(num_shards), len(records)))
    out_dir = os.fspath(out_dir)
    os.makedirs(out_dir, exist_ok=True)

    base, extra = divmod(len(records), num_shards)
    shards = []
    start = 0
    for i in range(num_shards):
        count = base + (1 if i < extra else 0)
        block = records[start:start + count]
        start += count
        fname = "%s-%05d-of-%05d.rec" % (name, i, num_shards)
        path = os.path.join(out_dir, fname)
        tmp_rec = path + ".tmp"
        tmp_idx = path + ".idx.tmp"
        writer = recordio.MXIndexedRecordIO(tmp_idx, tmp_rec, "w")
        try:
            for j, rec in enumerate(block):
                writer.write_idx(j, bytes(rec))
        finally:
            writer.close()
        os.replace(tmp_rec, path)
        os.replace(tmp_idx, path + ".idx")
        shards.append({"file": fname, "records": len(block),
                       "bytes": sum(len(r) for r in block)})

    manifest = {"version": MANIFEST_VERSION, "dataset": str(name),
                "shards": shards,
                "total_records": len(records)}
    mpath = manifest_path(out_dir, name)
    atomic_write_bytes(mpath, json.dumps(manifest, indent=1).encode("utf-8"))
    return mpath


def _corrupt(path, why):
    log.warning("data manifest %s: %s", path, why)
    raise ManifestCorruptError("data manifest %s: %s" % (path, why))


def load_manifest(path):
    """Read and validate a dataset manifest (the 5-way matrix)."""
    path = os.fspath(path)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        _corrupt(path, "unreadable (%s)" % e)
    try:
        manifest = json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        _corrupt(path, "not valid JSON (%s)" % e)
    if not isinstance(manifest, dict):
        _corrupt(path, "top level is %s, expected an object"
                 % type(manifest).__name__)
    if manifest.get("version") != MANIFEST_VERSION:
        _corrupt(path, "version %r != %d"
                 % (manifest.get("version"), MANIFEST_VERSION))
    shards = manifest.get("shards")
    if not isinstance(shards, list) or not shards:
        _corrupt(path, "shards is %r, expected a non-empty list" % (shards,))
    for i, s in enumerate(shards):
        if not isinstance(s, dict) \
                or not isinstance(s.get("file"), str) \
                or isinstance(s.get("records"), bool) \
                or not isinstance(s.get("records"), int) \
                or s["records"] < 0:
            _corrupt(path, "malformed shard entry %d: %r" % (i, s))
    if not isinstance(manifest.get("dataset"), str):
        _corrupt(path, "dataset name is %r, expected a string"
                 % (manifest.get("dataset"),))
    return manifest
