"""Typed failure hierarchy for the sharded dataset service (ISSUE 17).

Every data-plane failure the reader can hit mid-epoch maps to exactly
one of these classes, mirroring the schedule-table treatment in
``tune/table.py``: the reader logs what it saw and raises the typed
error — never a silent record skip (which would break the
exactly-once ledger) and never an untyped crash (which the elastic
supervisor could not tell apart from a training bug).

- :class:`LeaseLostError` — the tracker rebalanced this worker's
  shard lease away (TTL expiry or epoch roll). Honest and
  recoverable: a respawned worker re-acquires and resumes at the
  committed cursor.
- :class:`CursorCorruptError` — a resume cursor is out of range or
  moved backwards; reading from it would double- or under-consume.
- :class:`ShardCorruptError` — a record-shard file is truncated,
  has a garbage magic, or yields fewer records than its manifest
  entry promises.
- :class:`ManifestCorruptError` — the dataset manifest is missing,
  not JSON, the wrong shape, the wrong version, or has a malformed
  shard entry (the 5-way matrix).
"""
from __future__ import annotations

from ..base import MXNetError


class DataPlaneError(MXNetError):
    """Base class for all sharded-data-service failures."""


class LeaseLostError(DataPlaneError):
    """The shard lease was rebalanced away while this worker held it."""


class CursorCorruptError(DataPlaneError):
    """A within-shard resume cursor is out of range or went backwards."""


class ShardCorruptError(DataPlaneError):
    """A record-shard file is truncated or contains garbage records."""


class ManifestCorruptError(DataPlaneError):
    """The dataset manifest is unreadable, malformed, or mismatched."""
