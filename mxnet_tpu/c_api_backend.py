"""Python half of the general C API (src/c_api.cc → libmxtpu_c_api.so).

Reference counterpart: ``src/c_api/*.cc`` (3,502 LoC behind
``include/mxnet/c_api.h``'s 160 MXNET_DLL functions). Design mirrors the
predict ABI split: the C shared library owns the ABI and embeds CPython;
this module owns all behavior. Objects cross the boundary as owned
PyObject pointers; scalars/strings/shape buffers are marshalled by the
thin C layer.

The op-"creator" handles of the reference (AtomicSymbolCreator) are
realized as interned op-name strings — the registry is the single
source of truth, exactly as NNVM's Op* pointers were.
"""
from __future__ import annotations

import ctypes

import numpy as _np

from . import libinfo
from .base import MXNetError
from .context import Context
from .ndarray import ndarray as nd
from .ops import registry


# -- version / ops ----------------------------------------------------------
def version():
    # MXNET_VERSION convention: major*10000 + minor*100 + patch
    # (ref include/mxnet/base.h:112-116), so C consumers' threshold
    # checks against reference-style version numbers stay meaningful
    parts = (libinfo.__version__.split("-")[0].split(".") + ["0", "0"])[:3]
    major, minor, patch = (int("".join(ch for ch in p if ch.isdigit()) or 0)
                           for p in parts)
    return major * 10000 + minor * 100 + patch


def list_all_op_names():
    return registry.list_ops()


# -- NDArray ----------------------------------------------------------------
def ndarray_create(shape, dev_type, dev_id, delay_alloc, dtype_id):
    dtype = _DTYPE_FROM_ID[dtype_id]
    ctx = _ctx(dev_type, dev_id)
    del delay_alloc  # XLA allocates lazily anyway
    return nd.zeros(tuple(shape), ctx=ctx, dtype=dtype)


def ndarray_create_none():
    return nd.array(_np.zeros((0,), _np.float32))


def ndarray_shape(arr):
    return tuple(int(s) for s in arr.shape)


def ndarray_dtype_id(arr):
    return _DTYPE_TO_ID[_np.dtype(arr.dtype).name]


def ndarray_context(arr):
    c = arr.ctx
    return (_DEV_TYPE_TO_ID.get(c.device_type, 1), c.device_id)


def ndarray_sync_copy_from(arr, ptr, size):
    n = int(_np.prod(arr.shape)) if arr.shape else 1
    if size != n:
        raise MXNetError("SyncCopyFromCPU: expected %d elements, got %d"
                         % (n, size))
    name = _np.dtype(arr.dtype).name if arr.dtype != "bfloat16" else "bfloat16"
    if name == "bfloat16":
        # bf16 is reported to C as dtype id 2 (fp16): accept fp16 bits
        bits = _np.ctypeslib.as_array(
            ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint16)), shape=(n,))
        data = bits.copy().view(_np.float16).astype(_np.float32)
        arr[:] = nd.array(data.reshape(arr.shape), dtype="bfloat16")
        return
    ct = _np.ctypeslib.as_array(
        ctypes.cast(ptr, ctypes.POINTER(_CTYPE_FROM_NAME[name])),
        shape=(n,))
    data = ct.copy()
    if name == "float16":
        # the c_uint16 view carries raw fp16 bits: reinterpret, don't cast
        data = data.view(_np.float16)
    arr[:] = nd.array(data.reshape(arr.shape), dtype=arr.dtype)


def ndarray_sync_copy_to(arr, ptr, size):
    n = int(_np.prod(arr.shape)) if arr.shape else 1
    if size != n:
        raise MXNetError("SyncCopyToCPU: expected %d elements, got %d"
                         % (n, size))
    name = _np.dtype(arr.dtype).name if arr.dtype != "bfloat16" else "bfloat16"
    if name == "bfloat16":
        # deliver fp16 bit patterns, matching the reported dtype id 2
        flat = _np.asarray(arr.asnumpy(), _np.float32).reshape(-1)
        out = _np.ctypeslib.as_array(
            ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint16)), shape=(n,))
        out[:] = flat.astype(_np.float16).view(_np.uint16)
        return
    flat = _np.ascontiguousarray(arr.asnumpy()).reshape(-1)
    if name == "float16":
        flat = flat.view(_np.uint16)  # hand back raw fp16 bit patterns
    out = _np.ctypeslib.as_array(
        ctypes.cast(ptr, ctypes.POINTER(_CTYPE_FROM_NAME[name])),
        shape=(n,))
    out[:] = flat


def ndarray_slice(arr, begin, end):
    return arr[int(begin):int(end)]


def ndarray_reshape(arr, shape):
    return arr.reshape(tuple(int(s) for s in shape))


def ndarray_save(fname, arrays, keys):
    from .ndarray.utils import save

    if keys:
        save(fname, dict(zip(keys, arrays)))
    else:
        save(fname, list(arrays))


def ndarray_load(fname):
    from .ndarray.utils import load

    data = load(fname)
    if isinstance(data, dict):
        return list(data.keys()), list(data.values())
    return [], list(data)


def waitall():
    nd.waitall()


def random_seed(seed):
    from . import random as _rnd

    _rnd.seed(seed)


def imperative_invoke(op_name, inputs, keys, vals, outs=None):
    op = registry.get(op_name)
    attrs = op.parse_attrs(dict(zip(keys, vals)))
    out = nd.invoke(op, list(inputs), attrs, out=outs or None)
    return out if isinstance(out, list) else [out]


# -- Symbol -----------------------------------------------------------------
def symbol_create_from_json(json_str):
    from . import symbol as sym_mod

    return sym_mod.load_json(json_str)


def symbol_to_json(sym):
    return sym.tojson()


def symbol_create_variable(name):
    from . import symbol as sym_mod

    return sym_mod.var(name)


def symbol_create_atomic(op_name, keys, vals):
    """Partially-applied op: compose() binds its inputs (ref two-step
    MXSymbolCreateAtomicSymbol + MXSymbolCompose)."""
    return ("__atomic__", op_name, dict(zip(keys, vals)))


def symbol_compose(atom_or_sym, name, keys, args):
    if not (isinstance(atom_or_sym, tuple) and atom_or_sym[0] == "__atomic__"):
        raise MXNetError("compose expects an atomic symbol handle")
    _, op_name, attrs = atom_or_sym
    import mxnet_tpu.symbol as S

    op = registry.get(op_name)
    parsed = op.parse_attrs(attrs)
    fn = getattr(S, op_name)
    if keys:
        kwargs = dict(zip(keys, args))
        kwargs.update(parsed)
        return fn(name=name, **kwargs)
    return fn(*args, name=name, **parsed)


def symbol_list_arguments(sym):
    return sym.list_arguments()


def symbol_list_outputs(sym):
    return sym.list_outputs()


def symbol_list_aux(sym):
    return sym.list_auxiliary_states()


def symbol_copy(sym):
    import copy

    return copy.deepcopy(sym)


def symbol_get_attr(sym, key):
    v = sym.attr(key)
    return v


def symbol_set_attr(sym, key, value):
    sym._set_attr(**{key: value})


def symbol_infer_shape(sym, keys, ndims, data):
    """Returns (arg_shapes, out_shapes, aux_shapes, complete)."""
    kwargs = {}
    off = 0
    for k, nd_ in zip(keys, ndims):
        kwargs[k] = tuple(int(x) for x in data[off:off + nd_])
        off += nd_
    try:
        arg, out, aux = sym.infer_shape(**kwargs)
    except MXNetError:
        return None, None, None, 0
    if arg is None:
        return None, None, None, 0
    return ([tuple(s) for s in arg], [tuple(s) for s in out],
            [tuple(s) for s in aux], 1)


# -- Executor ---------------------------------------------------------------
def executor_bind(sym, dev_type, dev_id, args, grads, req_ids, aux):
    ctx = _ctx(dev_type, dev_id)
    arg_names = sym.list_arguments()
    req_names = {0: "null", 1: "write", 3: "add"}
    grad_dict = {n: g for n, g in zip(arg_names, grads) if g is not None}
    grad_req = {n: req_names.get(int(r), "write")
                for n, r in zip(arg_names, req_ids)}
    return sym.bind(ctx, list(args), args_grad=grad_dict or None,
                    grad_req=grad_req, aux_states=list(aux))


def executor_forward(exe, is_train):
    exe.forward(is_train=bool(is_train))


def executor_backward(exe, head_grads):
    exe.backward(list(head_grads) if head_grads else None)


def executor_outputs(exe):
    return list(exe.outputs)


# -- KVStore ----------------------------------------------------------------
def kvstore_create(kv_type):
    from . import kvstore as kv_mod

    return kv_mod.create(kv_type or "local")


def kvstore_init(kv, keys, vals):
    kv.init(list(keys), list(vals))


def kvstore_push(kv, keys, vals, priority):
    kv.push(list(keys), list(vals), priority=priority)


def kvstore_pull(kv, keys, outs, priority):
    kv.pull(list(keys), out=list(outs), priority=priority)


def kvstore_rank(kv):
    return int(kv.rank)


def kvstore_size(kv):
    return int(kv.num_workers)


def kvstore_barrier(kv):
    kv.barrier()


def kvstore_type(kv):
    return kv.type


# -- marshalling tables -----------------------------------------------------
_DTYPE_FROM_ID = {0: _np.float32, 1: _np.float64, 2: _np.float16,
                  3: _np.uint8, 4: _np.int32, 5: _np.int8, 6: _np.int64}
_DTYPE_TO_ID = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
                "int32": 4, "int8": 5, "int64": 6, "bfloat16": 2}
_CTYPE_FROM_NAME = {"float32": ctypes.c_float, "float64": ctypes.c_double,
                    "float16": ctypes.c_uint16, "uint8": ctypes.c_uint8,
                    "int32": ctypes.c_int32, "int8": ctypes.c_int8,
                    "int64": ctypes.c_int64}
_DEV_TYPE_TO_ID = {"cpu": 1, "gpu": 2, "tpu": 2, "cpu_pinned": 3}


def _ctx(dev_type, dev_id):
    name = {1: "cpu", 2: "tpu", 3: "cpu_pinned"}.get(int(dev_type), "cpu")
    return Context(name, int(dev_id))


# -- autograd (ref: MXAutograd*, c_api_ndarray.cc) ---------------------------
def autograd_set_is_recording(flag):
    from . import autograd

    return int(autograd.set_recording(bool(flag)))


def autograd_set_is_training(flag):
    from . import autograd

    return int(autograd.set_training(bool(flag)))


def autograd_is_recording():
    from . import autograd

    return int(autograd.is_recording())


def autograd_is_training():
    from . import autograd

    return int(autograd.is_training())


def autograd_mark_variables(variables, gradients, grad_reqs):
    from . import autograd

    autograd.mark_variables(list(variables), list(gradients),
                            [{0: "null", 1: "write", 3: "add"}.get(int(r), "write")
                             for r in grad_reqs])


def autograd_backward(heads, head_grads, retain_graph, train_mode):
    from . import autograd

    hg = list(head_grads) if head_grads else None
    autograd.backward(list(heads), head_grads=hg,
                      retain_graph=bool(retain_graph),
                      train_mode=bool(train_mode))


def ndarray_get_grad(arr):
    g = getattr(arr, "grad", None)
    if g is None:
        raise MXNetError("array has no gradient buffer (mark_variables first)")
    return g
