"""Python half of the general C API (src/c_api.cc → libmxtpu_c_api.so).

Reference counterpart: ``src/c_api/*.cc`` (3,502 LoC behind
``include/mxnet/c_api.h``'s 160 MXNET_DLL functions). Design mirrors the
predict ABI split: the C shared library owns the ABI and embeds CPython;
this module owns all behavior. Objects cross the boundary as owned
PyObject pointers; scalars/strings/shape buffers are marshalled by the
thin C layer.

The op-"creator" handles of the reference (AtomicSymbolCreator) are
realized as interned op-name strings — the registry is the single
source of truth, exactly as NNVM's Op* pointers were.
"""
from __future__ import annotations

import ctypes

import numpy as _np

from . import libinfo
from .base import MXNetError
from .context import Context
from .ndarray import ndarray as nd
from .ops import registry


# -- version / ops ----------------------------------------------------------
def version():
    # MXNET_VERSION convention: major*10000 + minor*100 + patch
    # (ref include/mxnet/base.h:112-116), so C consumers' threshold
    # checks against reference-style version numbers stay meaningful
    parts = (libinfo.__version__.split("-")[0].split(".") + ["0", "0"])[:3]
    major, minor, patch = (int("".join(ch for ch in p if ch.isdigit()) or 0)
                           for p in parts)
    return major * 10000 + minor * 100 + patch


def list_all_op_names():
    return registry.list_ops()


# -- NDArray ----------------------------------------------------------------
def ndarray_create(shape, dev_type, dev_id, delay_alloc, dtype_id):
    dtype = _DTYPE_FROM_ID[dtype_id]
    ctx = _ctx(dev_type, dev_id)
    del delay_alloc  # XLA allocates lazily anyway
    return nd.zeros(tuple(shape), ctx=ctx, dtype=dtype)


def ndarray_create_none():
    return nd.array(_np.zeros((0,), _np.float32))


def ndarray_shape(arr):
    return tuple(int(s) for s in arr.shape)


def ndarray_dtype_id(arr):
    return _DTYPE_TO_ID[_np.dtype(arr.dtype).name]


def ndarray_context(arr):
    c = arr.ctx
    return (_DEV_TYPE_TO_ID.get(c.device_type, 1), c.device_id)


def ndarray_sync_copy_from(arr, ptr, size):
    n = int(_np.prod(arr.shape)) if arr.shape else 1
    if size != n:
        raise MXNetError("SyncCopyFromCPU: expected %d elements, got %d"
                         % (n, size))
    name = _np.dtype(arr.dtype).name if arr.dtype != "bfloat16" else "bfloat16"
    if name == "bfloat16":
        # bf16 is reported to C as dtype id 2 (fp16): accept fp16 bits
        bits = _np.ctypeslib.as_array(
            ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint16)), shape=(n,))
        data = bits.copy().view(_np.float16).astype(_np.float32)
        arr[:] = nd.array(data.reshape(arr.shape), dtype="bfloat16")
        return
    ct = _np.ctypeslib.as_array(
        ctypes.cast(ptr, ctypes.POINTER(_CTYPE_FROM_NAME[name])),
        shape=(n,))
    data = ct.copy()
    if name == "float16":
        # the c_uint16 view carries raw fp16 bits: reinterpret, don't cast
        data = data.view(_np.float16)
    arr[:] = nd.array(data.reshape(arr.shape), dtype=arr.dtype)


def ndarray_sync_copy_to(arr, ptr, size):
    n = int(_np.prod(arr.shape)) if arr.shape else 1
    if size != n:
        raise MXNetError("SyncCopyToCPU: expected %d elements, got %d"
                         % (n, size))
    name = _np.dtype(arr.dtype).name if arr.dtype != "bfloat16" else "bfloat16"
    if name == "bfloat16":
        # deliver fp16 bit patterns, matching the reported dtype id 2
        flat = _np.asarray(arr.asnumpy(), _np.float32).reshape(-1)
        out = _np.ctypeslib.as_array(
            ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint16)), shape=(n,))
        out[:] = flat.astype(_np.float16).view(_np.uint16)
        return
    flat = _np.ascontiguousarray(arr.asnumpy()).reshape(-1)
    if name == "float16":
        flat = flat.view(_np.uint16)  # hand back raw fp16 bit patterns
    out = _np.ctypeslib.as_array(
        ctypes.cast(ptr, ctypes.POINTER(_CTYPE_FROM_NAME[name])),
        shape=(n,))
    out[:] = flat


def ndarray_slice(arr, begin, end):
    return arr[int(begin):int(end)]


def ndarray_reshape(arr, shape):
    return arr.reshape(tuple(int(s) for s in shape))


def ndarray_save(fname, arrays, keys):
    from .ndarray.utils import save

    if keys:
        save(fname, dict(zip(keys, arrays)))
    else:
        save(fname, list(arrays))


def ndarray_load(fname):
    from .ndarray.utils import load

    data = load(fname)
    if isinstance(data, dict):
        return list(data.keys()), list(data.values())
    return [], list(data)


def waitall():
    nd.waitall()


def random_seed(seed):
    from . import random as _rnd

    _rnd.seed(seed)


def imperative_invoke(op_name, inputs, keys, vals, outs=None):
    op = registry.get(op_name)
    attrs = op.parse_attrs(dict(zip(keys, vals)))
    out = nd.invoke(op, list(inputs), attrs, out=outs or None)
    return out if isinstance(out, list) else [out]


# -- Symbol -----------------------------------------------------------------
def symbol_create_from_json(json_str):
    from . import symbol as sym_mod

    return sym_mod.load_json(json_str)


def symbol_to_json(sym):
    return sym.tojson()


def symbol_create_variable(name):
    from . import symbol as sym_mod

    return sym_mod.var(name)


def symbol_create_atomic(op_name, keys, vals):
    """Partially-applied op: compose() binds its inputs (ref two-step
    MXSymbolCreateAtomicSymbol + MXSymbolCompose)."""
    return ("__atomic__", op_name, dict(zip(keys, vals)))


def symbol_compose(atom_or_sym, name, keys, args):
    if not (isinstance(atom_or_sym, tuple) and atom_or_sym[0] == "__atomic__"):
        raise MXNetError("compose expects an atomic symbol handle")
    _, op_name, attrs = atom_or_sym
    import mxnet_tpu.symbol as S

    op = registry.get(op_name)
    parsed = op.parse_attrs(attrs)
    fn = getattr(S, op_name)
    if keys:
        kwargs = dict(zip(keys, args))
        kwargs.update(parsed)
        return fn(name=name, **kwargs)
    return fn(*args, name=name, **parsed)


def symbol_list_arguments(sym):
    return sym.list_arguments()


def symbol_list_outputs(sym):
    return sym.list_outputs()


def symbol_list_aux(sym):
    return sym.list_auxiliary_states()


def symbol_copy(sym):
    import copy

    return copy.deepcopy(sym)


def symbol_get_attr(sym, key):
    v = sym.attr(key)
    return v


def symbol_set_attr(sym, key, value):
    sym._set_attr(**{key: value})


def symbol_infer_shape(sym, keys, ndims, data):
    """Returns (arg_shapes, out_shapes, aux_shapes, complete)."""
    kwargs = {}
    off = 0
    for k, nd_ in zip(keys, ndims):
        kwargs[k] = tuple(int(x) for x in data[off:off + nd_])
        off += nd_
    try:
        arg, out, aux = sym.infer_shape(**kwargs)
    except MXNetError:
        return None, None, None, 0
    if arg is None:
        return None, None, None, 0
    return ([tuple(s) for s in arg], [tuple(s) for s in out],
            [tuple(s) for s in aux], 1)


# -- Executor ---------------------------------------------------------------
def executor_bind_x(sym, dev_type, dev_id, map_keys, map_dev_types,
                    map_dev_ids, args, grads, req_ids, aux,
                    shared_exec=None):
    """Bind with a group2ctx device map (ref MXExecutorBindX/BindEX)."""
    ctx = _ctx(dev_type, dev_id)
    group2ctx = {k: _ctx(t, i) for k, t, i in
                 zip(map_keys, map_dev_types, map_dev_ids)} or None
    arg_names = sym.list_arguments()
    req_names = {0: "null", 1: "write", 3: "add"}
    grad_dict = {n: g for n, g in zip(arg_names, grads) if g is not None}
    grad_req = {n: req_names.get(int(r), "write")
                for n, r in zip(arg_names, req_ids)}
    del shared_exec  # memory pooling is XLA's job (see simple_bind)
    return sym.bind(ctx, list(args), args_grad=grad_dict or None,
                    grad_req=grad_req, aux_states=list(aux),
                    group2ctx=group2ctx)


def executor_bind(sym, dev_type, dev_id, args, grads, req_ids, aux):
    ctx = _ctx(dev_type, dev_id)
    arg_names = sym.list_arguments()
    req_names = {0: "null", 1: "write", 3: "add"}
    grad_dict = {n: g for n, g in zip(arg_names, grads) if g is not None}
    grad_req = {n: req_names.get(int(r), "write")
                for n, r in zip(arg_names, req_ids)}
    return sym.bind(ctx, list(args), args_grad=grad_dict or None,
                    grad_req=grad_req, aux_states=list(aux))


def executor_forward(exe, is_train):
    exe.forward(is_train=bool(is_train))


def executor_backward(exe, head_grads):
    exe.backward(list(head_grads) if head_grads else None)


def executor_outputs(exe):
    return list(exe.outputs)


# -- KVStore ----------------------------------------------------------------
def kvstore_create(kv_type):
    from . import kvstore as kv_mod

    return kv_mod.create(kv_type or "local")


def kvstore_init(kv, keys, vals):
    kv.init(list(keys), list(vals))


def kvstore_push(kv, keys, vals, priority):
    kv.push(list(keys), list(vals), priority=priority)


def kvstore_pull(kv, keys, outs, priority):
    kv.pull(list(keys), out=list(outs), priority=priority)


def kvstore_rank(kv):
    return int(kv.rank)


def kvstore_size(kv):
    return int(kv.num_workers)


def kvstore_barrier(kv):
    kv.barrier()


def kvstore_type(kv):
    return kv.type


# -- marshalling tables -----------------------------------------------------
_DTYPE_FROM_ID = {0: _np.float32, 1: _np.float64, 2: _np.float16,
                  3: _np.uint8, 4: _np.int32, 5: _np.int8, 6: _np.int64}
_DTYPE_TO_ID = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
                "int32": 4, "int8": 5, "int64": 6, "bfloat16": 2}
_CTYPE_FROM_NAME = {"float32": ctypes.c_float, "float64": ctypes.c_double,
                    "float16": ctypes.c_uint16, "uint8": ctypes.c_uint8,
                    "int32": ctypes.c_int32, "int8": ctypes.c_int8,
                    "int64": ctypes.c_int64}
_DEV_TYPE_TO_ID = {"cpu": 1, "gpu": 2, "tpu": 2, "cpu_pinned": 3}


def _ctx(dev_type, dev_id):
    name = {1: "cpu", 2: "tpu", 3: "cpu_pinned"}.get(int(dev_type), "cpu")
    return Context(name, int(dev_id))


# -- autograd (ref: MXAutograd*, c_api_ndarray.cc) ---------------------------
def autograd_set_is_recording(flag):
    from . import autograd

    return int(autograd.set_recording(bool(flag)))


def autograd_set_is_training(flag):
    from . import autograd

    return int(autograd.set_training(bool(flag)))


def autograd_is_recording():
    from . import autograd

    return int(autograd.is_recording())


def autograd_is_training():
    from . import autograd

    return int(autograd.is_training())


def autograd_mark_variables(variables, gradients, grad_reqs):
    from . import autograd

    autograd.mark_variables(list(variables), list(gradients),
                            [{0: "null", 1: "write", 3: "add"}.get(int(r), "write")
                             for r in grad_reqs])


def autograd_backward(heads, head_grads, retain_graph, train_mode):
    from . import autograd

    hg = list(head_grads) if head_grads else None
    autograd.backward(list(heads), head_grads=hg,
                      retain_graph=bool(retain_graph),
                      train_mode=bool(train_mode))


def ndarray_get_grad(arr):
    g = getattr(arr, "grad", None)
    if g is None:
        raise MXNetError("array has no gradient buffer (mark_variables first)")
    return g


# ===========================================================================
# Round-3 surface: op/iter info, DataIter, RecordIO, Symbol/Executor
# extras, KVStore full tier, CachedOp, Func tier, profiler/engine misc
# (ref: include/mxnet/c_api.h:828-860 info fns, :1214-1305 DataIter,
# :1730-1800 RecordIO). Same design stance as above: this module owns
# behavior, src/c_api.cc owns marshalling.
# ===========================================================================

def _type_info_str(default):
    """Render an attr default as the reference's dmlc::Parameter type
    string (what MXSymbolGetAtomicSymbolInfo feeds binding generators)."""
    if isinstance(default, bool):
        return "boolean, optional, default=%s" % int(default)
    if isinstance(default, int):
        return "int, optional, default='%d'" % default
    if isinstance(default, float):
        return "float, optional, default=%g" % default
    if isinstance(default, str):
        return "string, optional, default='%s'" % default
    if isinstance(default, (tuple, list)):
        return "Shape(tuple), optional, default=%s" % (tuple(default),)
    if default is None:
        return "string, optional, default='None'"
    return "string, optional"


def op_info(op_name):
    """(name, description, arg_names, arg_type_infos, arg_descriptions,
    key_var_num_args, return_type) — ref MXSymbolGetAtomicSymbolInfo."""
    op = registry.get(op_name)
    names, types, descs = [], [], []
    if op.var_inputs:
        # reference convention: variable-count input is one list-typed
        # arg ("NDArray-or-Symbol[]"); key_var_num_args (below) is the
        # separate count attr, present only when the op declares one
        names.append("data")
        types.append("NDArray-or-Symbol[]")
        descs.append("List of input symbols")
    for inp in op.input_names:
        names.append(inp)
        types.append("NDArray-or-Symbol, optional"
                     if inp in op.optional_inputs else "NDArray-or-Symbol")
        descs.append("Input %s" % inp)
    for k in sorted(op.attr_defaults):
        names.append(k)
        types.append(_type_info_str(op.attr_defaults[k]))
        descs.append("")
    # only ops that actually declare a count attr (Concat-style) get the
    # key_var_num_args marker; add_n-style *args ops take bare inputs
    key_var_num_args = ("num_args" if op.var_inputs
                        and "num_args" in op.attr_defaults else "")
    doc = op.doc.strip()
    if not doc:
        # synthesized description: what binding generators actually
        # consume is the signature; prose is best-effort
        doc = "%s(%s)%s — registered operator, %d output%s." % (
            op.name, ", ".join(op.input_names) or "...",
            (" with attrs " + ", ".join(sorted(op.attr_defaults))
             if op.attr_defaults else ""),
            op.num_outputs if isinstance(op.num_outputs, int) else 1,
            "s" if (op.num_outputs if isinstance(op.num_outputs, int)
                    else 1) != 1 else "")
    return (op.name, doc, names, types, descs, key_var_num_args, "Symbol")


# -- DataIter registry (ref: MXListDataIters over MXNET_REGISTER_IO_ITER;
#    the same 6 C++-registered iterators the reference exposes) -----------
def _iter_factories():
    from . import io as io_mod

    return {
        "MNISTIter": io_mod.MNISTIter,
        "CSVIter": io_mod.CSVIter,
        "LibSVMIter": io_mod.LibSVMIter,
        "ImageRecordIter": io_mod.ImageRecordIter,
        "ImageRecordUInt8Iter": io_mod.ImageRecordUInt8Iter,
        "ImageDetRecordIter": io_mod.ImageDetRecordIter,
    }


def list_data_iters():
    return sorted(_iter_factories())


def data_iter_info(name):
    import inspect as _inspect

    fac = _iter_factories()[name]
    doc = (fac.__doc__ or "").strip()
    names, types, descs = [], [], []
    try:
        sig = _inspect.signature(fac)
        for p in sig.parameters.values():
            if p.kind in (p.VAR_KEYWORD, p.VAR_POSITIONAL):
                continue
            names.append(p.name)
            types.append(_type_info_str(None if p.default is p.empty
                                        else p.default))
            descs.append("")
    except (TypeError, ValueError):
        pass
    return (name, doc, names, types, descs)


def _coerce_str_param(v):
    """String kwarg -> python value (the dmlc::Parameter parse step)."""
    import ast

    s = str(v)
    if s in ("True", "true"):
        return True
    if s in ("False", "false"):
        return False
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


class _CDataIter:
    """DataIterHandle: iterator + the current batch (the C surface is
    cursor-style: Next() then GetData/GetLabel/GetPad on the cursor)."""

    def __init__(self, it):
        self.it = it
        self.batch = None


def data_iter_create(name, keys, vals):
    fac = _iter_factories()[name]
    kwargs = {k: _coerce_str_param(v) for k, v in zip(keys, vals)}
    return _CDataIter(fac(**kwargs))


def data_iter_next(h):
    try:
        h.batch = h.it.next()
        return 1
    except StopIteration:
        h.batch = None
        return 0


def data_iter_before_first(h):
    h.it.reset()
    h.batch = None


def _require_batch(h):
    if h.batch is None:
        raise MXNetError("no current batch: call MXDataIterNext first")
    return h.batch


def data_iter_get_data(h):
    return _require_batch(h).data[0]


def data_iter_get_label(h):
    return _require_batch(h).label[0]


def data_iter_get_pad(h):
    return int(_require_batch(h).pad or 0)


def data_iter_get_index(h):
    idx = getattr(_require_batch(h), "index", None)
    if idx is None:
        return []
    return [int(i) for i in idx]


# -- RecordIO (ref: c_api.h:1730-1800 over dmlc recordio) ------------------
def recordio_writer_create(uri):
    from . import recordio

    return recordio.MXRecordIO(uri, "w")


def recordio_writer_write(w, data):
    w.write(data)


def recordio_writer_tell(w):
    return int(w.tell())


def recordio_reader_create(uri):
    from . import recordio

    return recordio.MXRecordIO(uri, "r")


def recordio_reader_read(r):
    """Returns bytes or None at EOF."""
    return r.read()


def recordio_reader_seek(r, pos):
    r.seek(int(pos))


def recordio_reader_tell(r):
    return int(r.tell())


def recordio_close(h):
    h.close()


# -- Symbol extras ---------------------------------------------------------
def symbol_create_from_file(fname):
    from . import symbol as sym_mod

    return sym_mod.load(fname)


def symbol_save_to_file(sym, fname):
    sym.save(fname)


def symbol_create_group(syms):
    from . import symbol as sym_mod

    return sym_mod.Group(list(syms))


def symbol_get_internals(sym):
    return sym.get_internals()


def symbol_get_children(sym):
    c = sym.get_children()
    if c is None:
        # leaf/variable: reference returns a valid empty symbol, not a
        # null handle — wrapping None would poison later calls
        from . import symbol as sym_mod

        return sym_mod.Group([])
    return c


def symbol_get_name(sym):
    """(name, success) — grouped/multi-output symbols have no single name."""
    n = getattr(sym, "name", None)
    return (n, 1) if n else (None, 0)


def symbol_get_output(sym, index):
    return sym[int(index)]


def symbol_get_num_outputs(sym):
    return len(sym.list_outputs())


def symbol_list_attr(sym):
    """Deep attr list: 'node_name$key' -> value pairs flattened (ref
    MXSymbolListAttr returns key/value interleaved)."""
    out = []
    attrs = sym.attr_dict()
    for node, kv in sorted(attrs.items()):
        for k, v in sorted(kv.items()):
            out.extend(["%s$%s" % (node, k), str(v)])
    return out


def symbol_list_attr_shallow(sym):
    out = []
    for k, v in sorted((sym.list_attr() or {}).items()):
        out.extend([str(k), str(v)])
    return out


def symbol_print(sym):
    return sym.debug_str()


def symbol_infer_type(sym, keys, type_ids):
    kwargs = {}
    for k, t in zip(keys, type_ids):
        kwargs[k] = _DTYPE_FROM_ID[int(t)]
    try:
        arg, out, aux = sym.infer_type(**kwargs)
    except MXNetError:
        return None, None, None, 0
    if arg is None:
        return None, None, None, 0
    to_id = lambda t: _DTYPE_TO_ID[_np.dtype(t).name]  # noqa: E731
    return ([to_id(t) for t in arg], [to_id(t) for t in out],
            [to_id(t) for t in aux], 1)


def symbol_infer_shape_partial(sym, keys, ndims, data):
    """Partial inference: unknown shapes come back 0-d (ref
    MXSymbolInferShapePartial's partial_infer=true)."""
    kwargs = {}
    off = 0
    for k, nd_ in zip(keys, ndims):
        kwargs[k] = tuple(int(x) for x in data[off:off + nd_])
        off += nd_
    try:
        arg, out, aux = sym.infer_shape_partial(**kwargs)
    except MXNetError:
        return None, None, None, 0
    if arg is None:
        return None, None, None, 0
    fix = lambda s: tuple(s) if s is not None else ()  # noqa: E731
    return ([fix(s) for s in arg], [fix(s) for s in out],
            [fix(s) for s in aux], 1)


# -- Executor extras -------------------------------------------------------
def executor_simple_bind(sym, dev_type, dev_id, g2c_keys, g2c_dev_types,
                         g2c_dev_ids, req_mode, req_names, req_types,
                         shape_names, shape_data, shape_idx, dtype_names,
                         dtype_ids, stype_names, stype_ids, shared_arg_names,
                         shared_buffer_names, shared_buffer_arrays,
                         shared_exec):
    """Backend for MXExecutorSimpleBind. req_mode follows the reference
    four-way convention (c_api_executor.cc:348-380): "string" (global
    req in req_types[0]), "list" (positional, matching arg order),
    "dict" (name->req pairs), "none" (no gradients)."""
    del stype_names, stype_ids, shared_arg_names  # dense-only TPU build
    ctx = _ctx(dev_type, dev_id)
    group2ctx = {k: _ctx(t, i) for k, t, i in
                 zip(g2c_keys, g2c_dev_types, g2c_dev_ids)} or None
    if req_mode == "none":
        grad_req = "null"
    elif req_mode == "string":
        grad_req = req_types[0]
    elif req_mode == "list":
        grad_req = dict(zip(sym.list_arguments(), req_types))
    else:
        grad_req = dict(zip(req_names, req_types))
    kwargs = {}
    for i, name in enumerate(shape_names):
        kwargs[name] = tuple(int(x) for x in
                             shape_data[shape_idx[i]:shape_idx[i + 1]])
    type_dict = {n: _DTYPE_FROM_ID[int(t)]
                 for n, t in zip(dtype_names, dtype_ids)} or None
    from .executor import simple_bind as _sb

    exe = _sb(sym, ctx, grad_req=grad_req, type_dict=type_dict,
              shared_exec=shared_exec, group2ctx=group2ctx, **kwargs)
    # shared_buffer updates: return what we were given (XLA owns pooling)
    del shared_buffer_names, shared_buffer_arrays
    arg_names = sym.list_arguments()
    in_args = [exe.arg_dict[n] for n in arg_names]
    arg_grads = [exe.grad_dict.get(n) for n in arg_names]
    aux = [exe.aux_dict[n] for n in sym.list_auxiliary_states()]
    return exe, in_args, arg_grads, aux


def executor_backward_ex(exe, head_grads, is_train):
    exe.backward(list(head_grads) if head_grads else None,
                 is_train=bool(is_train))


def executor_print(exe):
    return exe.debug_str()


def executor_set_monitor_callback(exe, py_cb):
    """py_cb is a C-side trampoline PyCFunction: (name, array) -> None."""
    exe.set_monitor_callback(lambda name, arr: py_cb(str(name), arr))


# -- KVStore full tier -----------------------------------------------------
def kvstore_init_int(kv, keys, vals):
    kv.init([int(k) for k in keys], list(vals))


def kvstore_push_int(kv, keys, vals, priority):
    kv.push([int(k) for k in keys], list(vals), priority=priority)


def kvstore_pull_int(kv, keys, outs, priority):
    kv.pull([int(k) for k in keys], out=list(outs), priority=priority)


def kvstore_pull_row_sparse(kv, keys, outs, row_ids, priority):
    kv.row_sparse_pull(list(keys), out=list(outs), priority=priority,
                       row_ids=list(row_ids))


def kvstore_set_updater(kv, py_cb):
    """py_cb: C trampoline (int_or_str_key, recv_array, local_array)."""

    def updater(key, recv, local):
        py_cb(key, recv, local)

    kv._set_updater(updater)


def kvstore_is_worker_node():
    import os

    return int(os.environ.get("DMLC_ROLE", "worker") == "worker")


def kvstore_is_server_node():
    import os

    return int(os.environ.get("DMLC_ROLE", "") == "server")


def kvstore_is_scheduler_node():
    import os

    return int(os.environ.get("DMLC_ROLE", "") == "scheduler")


def kvstore_get_num_dead_node(kv, node_id, timeout_sec):
    fn = getattr(kv, "get_num_dead_node", None)
    if fn is None:
        return 0
    return int(fn(int(node_id), timeout_sec=int(timeout_sec)))


def kvstore_set_barrier_before_exit(kv, flag):
    fn = getattr(kv, "set_barrier_before_exit", None)
    if fn is not None:
        fn(bool(flag))


def kvstore_set_gradient_compression(kv, keys, vals):
    # the C API ships every value as a string (ref: MXKVStoreSet-
    # GradientCompression const char** vals); coerce threshold here so
    # the typed Python validation stays strict
    params = dict(zip(keys, vals))
    if isinstance(params.get("threshold"), str):
        try:
            params["threshold"] = float(params["threshold"])
        except ValueError:
            pass  # validate_compression_params raises loudly
    kv.set_gradient_compression(params)


def kvstore_send_command_to_servers(kv, head, body):
    fn = getattr(kv, "_send_command_to_servers", None)
    if fn is not None:
        fn(int(head), str(body))


def kvstore_run_server(kv, py_controller):
    """Serverless design: the controller is invoked for parity when a
    command arrives; with no server processes this returns immediately
    (ref kvstore_dist_server.h Run — see kvstore_server.py)."""
    del kv, py_controller
    return 0


def init_ps_env(keys, vals):
    import os

    for k, v in zip(keys, vals):
        os.environ[str(k)] = str(v)


# -- CachedOp (ref: MXCreateCachedOp/MXInvokeCachedOp over
#    src/imperative/cached_op.cc; here: executor-backed apply cache keyed
#    on input signature — the executor owns the jit cache) ----------------
class _CCachedOp:
    def __init__(self, sym, flags=None):
        self.sym = sym
        self.flags = dict(flags or {})
        self._cache = {}

    def __call__(self, inputs):
        arg_names = self.sym.list_arguments()
        if len(inputs) != len(arg_names):
            raise MXNetError("CachedOp: expected %d inputs, got %d"
                             % (len(arg_names), len(inputs)))
        key = tuple((tuple(a.shape), str(a.dtype)) for a in inputs)
        exe = self._cache.get(key)
        if exe is None:
            # bind to private placeholder copies — never alias caller
            # arrays (a cache-hit copy into an aliased arg would
            # silently overwrite the first caller's data)
            placeholders = {n: a.copy() for n, a in zip(arg_names, inputs)}
            exe = self.sym.bind(inputs[0].ctx, placeholders,
                                grad_req="null")
            self._cache[key] = exe
        for name, a in zip(arg_names, inputs):
            a.copyto(exe.arg_dict[name])
        exe.forward(is_train=False)
        return list(exe.outputs)


def cached_op_create(sym, keys=(), vals=()):
    return _CCachedOp(sym, dict(zip(keys, vals)))


def cached_op_invoke(cop, inputs):
    return cop(list(inputs))


# -- legacy Func tier (ref: MXListFunctions/MXFuncInvoke — the pre-NNVM
#    imperative surface; FunctionHandle == interned op name) --------------
def func_describe(op_name):
    op = registry.get(op_name)
    n_in = 0 if op.var_inputs else len(op.input_names)
    # type_mask: kNDArrayArgBeforeScalar(1) | kAcceptEmptyMutateTarget(4)
    return (n_in, 0, op.num_outputs if isinstance(op.num_outputs, int) else 1,
            1 | 4)


def func_invoke(op_name, use_vars, scalars, mutate_vars, keys=(), vals=()):
    op = registry.get(op_name)
    attrs = op.parse_attrs(dict(zip(keys, vals)))
    out = nd.invoke(op, list(use_vars), attrs,
                    out=list(mutate_vars) or None)
    return out if isinstance(out, list) else [out]


# -- autograd extras -------------------------------------------------------
def autograd_backward_compat(heads, head_grads, retain_graph):
    autograd_backward(heads, head_grads, retain_graph, True)


def autograd_compute_gradient(heads):
    autograd_backward(heads, None, False, True)


# -- profiler / engine misc ------------------------------------------------
def set_profiler_config(mode, filename):
    from . import profiler

    profiler.profiler_set_config(
        mode={0: "symbolic", 1: "all"}.get(int(mode), "symbolic"),
        filename=filename)


def set_profiler_state(state):
    from . import profiler

    profiler.profiler_set_state(
        {0: "stop", 1: "run"}.get(int(state), "stop"))


def dump_profile():
    from . import profiler

    profiler.dump_profile()


def notify_shutdown():
    nd.waitall()


def set_num_omp_threads(n):
    import os

    os.environ["OMP_NUM_THREADS"] = str(int(n))


def engine_set_bulk_size(size):
    from . import engine

    prev = engine.set_bulk_size(int(size))
    return int(prev if prev is not None else 0)


# -- NDArray extras (sparse aux, raw bytes, views, grad state) -------------
_STYPE_TO_ID = {"default": 0, "row_sparse": 1, "csr": 2}


def ndarray_storage_type(arr):
    return _STYPE_TO_ID.get(getattr(arr, "stype", "default"), 0)


def ndarray_create_sparse(stype_id, shape, dev_type, dev_id, dtype_id):
    from .ndarray import sparse as sp

    stype = {1: "row_sparse", 2: "csr"}.get(int(stype_id))
    if stype is None:
        raise MXNetError("unknown storage type id %d" % stype_id)
    return sp.zeros(stype, tuple(int(s) for s in shape),
                    ctx=_ctx(dev_type, dev_id),
                    dtype=_DTYPE_FROM_ID[int(dtype_id)])


def ndarray_get_aux_type(arr, i):
    from .ndarray import sparse as sp

    if not isinstance(arr, sp.BaseSparseNDArray):
        raise MXNetError("GetAuxType: dense array has no aux data")
    order = (["indices"] if arr.stype == "row_sparse"
             else ["indices", "indptr"])
    return _DTYPE_TO_ID[_np.dtype(arr._aux[order[int(i)]].dtype).name]


def ndarray_get_aux_ndarray(arr, i):
    from .ndarray import sparse as sp

    if not isinstance(arr, sp.BaseSparseNDArray):
        raise MXNetError("GetAuxNDArray: dense array has no aux data")
    order = (["indices"] if arr.stype == "row_sparse"
             else ["indices", "indptr"])
    return arr._aux[order[int(i)]]


def ndarray_get_data_ndarray(arr):
    from .ndarray import sparse as sp

    if isinstance(arr, sp.BaseSparseNDArray):
        return arr.data
    return arr


def ndarray_at(arr, idx):
    return arr[int(idx)]


def ndarray_detach(arr):
    fn = getattr(arr, "detach", None)
    if fn is not None:
        return fn()
    return arr.copy()


def ndarray_set_grad_state(arr, state):
    arr._grad_entry = arr._grad_entry if hasattr(arr, "_grad_entry") else None
    arr._fresh_grad = bool(state)


def ndarray_get_grad_state(arr):
    return int(bool(getattr(arr, "_fresh_grad", False)))


def ndarray_save_raw_bytes(arr):
    """Single-array serialization as .npy bytes (same container family
    as save/load's .npz; ref NDArray::SaveRawBytes)."""
    import io as _io

    buf = _io.BytesIO()
    _np.save(buf, arr.asnumpy(), allow_pickle=False)
    return buf.getvalue()


def ndarray_load_from_raw_bytes(data):
    import io as _io

    return nd.array(_np.load(_io.BytesIO(bytes(data)), allow_pickle=False))


def ndarray_sync_copy_from_ndarray(dst, src, i):
    """dst[:] = src (i == -1) or dst[:] = src.aux[i] / src slice semantics
    (ref MXNDArraySyncCopyFromNDArray)."""
    if int(i) >= 0:
        src = ndarray_get_aux_ndarray(src, int(i))
    src.copyto(dst)


def ndarray_sync_check_format(arr, full_check):
    from .ndarray import sparse as sp

    if isinstance(arr, sp.CSRNDArray) and full_check:
        ptr = _np.asarray(arr.indptr.asnumpy(), _np.int64)
        if ptr[0] != 0 or (_np.diff(ptr) < 0).any():
            raise MXNetError("CSR indptr must be monotonic from 0")
        if int(ptr[-1]) != int(arr.indices.shape[0]):
            raise MXNetError("CSR indptr end must equal nnz")
    if isinstance(arr, sp.RowSparseNDArray) and full_check:
        idx = _np.asarray(arr.indices.asnumpy(), _np.int64)
        if (_np.diff(idx) <= 0).any():
            raise MXNetError("row_sparse indices must be strictly increasing")


def ndarray_data_ptr(arr):
    """(keepalive, address): host copy whose lifetime the C handle owns
    (ref MXNDArrayGetData returns a host-readable pointer)."""
    a = _np.ascontiguousarray(arr.asnumpy())
    return a, int(a.ctypes.data)


# -- shared memory (ref: MXNDArrayCreateFromSharedMem /
#    MXNDArrayGetSharedMemHandle over CPUSharedStorageManager) ------------
_SHM_COUNTER = None


def ndarray_get_shared_mem_handle(arr):
    """Copy into a /dev/shm segment; returns (shared_pid, shared_id).

    Lifecycle: the consumer's create-from copies the data out and
    unlinks the segment (our arrays are device-resident, so unlike the
    reference's CPUSharedStorageManager there is no live mapping to
    keep); unconsumed segments are swept at process exit."""
    import atexit
    import itertools
    import os

    global _SHM_COUNTER
    if _SHM_COUNTER is None:
        _SHM_COUNTER = itertools.count(os.getpid() & 0xFFFF)
        atexit.register(_shm_sweep)
    a = _np.ascontiguousarray(arr.asnumpy())
    pid = os.getpid()
    shared_id = next(_SHM_COUNTER)
    path = "/dev/shm/mxtpu_%d_%d" % (pid, shared_id)
    with open(path, "wb") as f:
        f.write(a.tobytes())
    return pid, shared_id


def _shm_sweep():
    import glob
    import os

    for p in glob.glob("/dev/shm/mxtpu_%d_*" % os.getpid()):
        try:
            os.remove(p)
        except OSError:
            pass


def ndarray_create_from_shared_mem(shared_pid, shared_id, shape, dtype_id):
    import os

    dtype = _DTYPE_FROM_ID[int(dtype_id)]
    path = "/dev/shm/mxtpu_%d_%d" % (int(shared_pid), int(shared_id))
    data = _np.fromfile(path, dtype=dtype).reshape(tuple(int(s) for s in shape))
    out = nd.array(data, dtype=dtype)
    try:
        os.remove(path)  # handoff complete; see GetSharedMemHandle docs
    except OSError:
        pass
    return out


# ---------------------------------------------------------------------------
# Custom operator C tier (MXCustomOpRegister / MXCustomFunctionRecord /
# MXAutogradGetSymbol) — marshalling lives in mxnet_tpu.c_custom
# ---------------------------------------------------------------------------
def custom_op_register(op_type, creator_addr):
    from .c_custom import register_c_op

    return register_c_op(op_type, creator_addr)


def custom_function_record(inputs, outputs, cblist_addr):
    from .c_custom import record_custom_function

    return record_custom_function(inputs, outputs, cblist_addr)


def autograd_get_symbol(arr):
    from . import autograd as ag

    return ag.get_symbol(arr)
