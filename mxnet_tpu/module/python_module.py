"""PythonModule / PythonLossModule — module API over pure-Python compute.

Reference counterpart: ``python/mxnet/module/python_module.py`` (a
convenience base that stubs the parameter/optimizer surface so a user
only implements forward/backward; PythonLossModule feeds custom loss
gradients back into a preceding module).
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from ..io import DataDesc
from ..ndarray import ndarray as nd
from .base_module import BaseModule


class PythonModule(BaseModule):
    """Subclass and implement ``forward`` (and ``backward`` if training);
    parameter-free by default."""

    def __init__(self, data_names, label_names, output_names, logger=logging):
        super().__init__(logger=logger)
        if isinstance(data_names, tuple):
            data_names = list(data_names)
        if isinstance(label_names, tuple):
            label_names = list(label_names)
        self._data_names = data_names
        self._label_names = label_names or []
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    # -- properties ----------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # -- params (none by default) --------------------------------------------
    def get_params(self):
        return ({}, {})

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        self.params_initialized = True

    def update(self):
        pass

    def update_metric(self, eval_metric, labels):
        if self._label_shapes is None:
            return
        eval_metric.update(labels, self.get_outputs())

    # -- bind ----------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        assert grad_req == "write", "PythonModule only supports write grad_req"
        self._data_shapes = [
            d if isinstance(d, DataDesc) else DataDesc(d[0], tuple(d[1]))
            for d in data_shapes
        ]
        self._label_shapes = (
            [l if isinstance(l, DataDesc) else DataDesc(l[0], tuple(l[1]))
             for l in label_shapes]
            if label_shapes else None)
        self._output_shapes = self._compute_output_shapes()
        self.binded = True

    def _compute_output_shapes(self):
        """Subclass: [(name, shape)] of the outputs."""
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self.optimizer_initialized = True


class PythonLossModule(PythonModule):
    """A loss head in pure Python: forward stores the scores, backward
    produces d(loss)/d(scores) via ``grad_func`` (or cross-entropy-style
    pass-through by default)."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__(data_names, label_names,
                         [name + "_output"], logger=logger)
        self._name = name
        assert len(data_names) == 1 and data_names[0] == "data"
        assert len(label_names) == 1 and label_names[0] == "softmax_label"
        self._scores = None
        self._labels = None
        self._scores_grad = None
        if grad_func is not None and not callable(grad_func):
            raise MXNetError("grad_func must be callable")
        self._grad_func = grad_func

    def _compute_output_shapes(self):
        return [(self._name + "_output", self._data_shapes[0].shape)]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if is_train is None:
            is_train = self.for_training
        if is_train:
            self._labels = data_batch.label[0] if data_batch.label else None

    def get_outputs(self, merge_multi_context=True):
        assert merge_multi_context
        return [self._scores]

    def backward(self, out_grads=None):
        assert out_grads is None, "PythonLossModule is a loss head"
        assert self.for_training
        if self._grad_func is not None:
            grad = self._grad_func(self._scores, self._labels)
            if not isinstance(grad, nd.NDArray):
                grad = nd.array(grad)
            self._scores_grad = grad
        else:
            # default: d/dx of cross-entropy(softmax(x)) = p - onehot(y)
            scores = self._scores.asnumpy()
            labels = self._labels.asnumpy().astype(np.int64)
            e = np.exp(scores - scores.max(axis=1, keepdims=True))
            p = e / e.sum(axis=1, keepdims=True)
            p[np.arange(len(labels)), labels] -= 1.0
            self._scores_grad = nd.array(p)

    def get_input_grads(self, merge_multi_context=True):
        assert merge_multi_context
        return [self._scores_grad]

    def install_monitor(self, mon):
        raise NotImplementedError()
