"""Fused SPMD execution group for Module(kvstore='tpu').

The reference's ``kvstore='device'/'nccl'`` tier runs one executor per GPU
and reduces gradients through a Comm tree (module/module.py:468-530 +
kvstore comm.h). The TPU-native tier replaces that whole pipeline with ONE
compiled XLA program per batch: forward + backward + optimizer update with
the batch sharded over the mesh's ``dp`` axis, so the gradient all-reduce
is a psum over ICI *inside* the step (the reference's priority-scheduled
push/pull overlap becomes XLA latency hiding).

Module routes ``forward_backward``/``update`` here when it detects a
``tpu`` kvstore; the kvstore itself carries the mesh (TPUKVStore.mesh) for
introspection parity.
"""
from __future__ import annotations

import pickle

import numpy as np

from ..base import MXNetError
from ..ndarray import ndarray as nd
from ..parallel.mesh import make_mesh
from ..parallel.spmd import (
    TrainStep,
    data_sharding,
    functional_from_optimizer,
)


class FusedSPMDGroup:
    """One fused train step over a dp mesh built from Module's contexts."""

    def __init__(self, symbol, contexts, optimizer, arg_params, aux_params,
                 data_names, label_names, fixed_param_names=None, logger=None,
                 batch_size=None, inputs_need_grad=False):
        import jax

        if fixed_param_names:
            raise MXNetError("fused SPMD step: fixed_param_names not supported")
        if inputs_need_grad:
            raise MXNetError("fused SPMD step: inputs_need_grad not supported")
        devices = [c.jax_device() for c in contexts]
        if len({id(d) for d in devices}) != len(devices):
            raise MXNetError("fused SPMD step: duplicate devices in context list")
        if batch_size is not None and batch_size % len(devices) != 0:
            raise MXNetError(
                "fused SPMD step: batch size %d not divisible by %d devices"
                % (batch_size, len(devices)))
        self.mesh = make_mesh({"dp": len(devices)}, devices=devices)
        self._fopt = functional_from_optimizer(
            optimizer, [n for n in symbol.list_arguments()
                        if n not in data_names and n not in label_names])
        # rescale_grad already carries the 1/batch normalization Module set.
        self._ts = TrainStep(
            symbol, self._fopt, mesh=self.mesh,
            data_names=tuple(data_names), label_names=tuple(label_names),
            compute_dtype=None, normalize_grads=False, return_outputs=True,
        )
        self.param_names = list(self._ts.param_names)
        self.aux_names = list(self._ts.aux_names)
        params = {k: arg_params[k]._data() for k in self.param_names}
        aux = {k: aux_params[k]._data() for k in self.aux_names}
        opt_state = self._fopt.init(params)
        self._carry = self._ts.place(params, opt_state, aux)
        self._data_names = list(data_names)
        self._label_names = list(label_names)
        self._output_names = list(symbol.list_outputs())
        self._key = jax.random.PRNGKey(0)
        self._step_no = 0
        self._loss = None
        self._outputs = None

    # -- the hot loop --------------------------------------------------------
    def forward_backward_update(self, data_batch):
        """Run one fused step: shard batch over dp, fwd+bwd+update in XLA."""
        import jax

        ndev = self.mesh.devices.size
        sh = data_sharding(self.mesh)
        batch = {}
        for name, arr in zip(self._data_names, data_batch.data):
            if arr.shape[0] % ndev != 0:
                raise MXNetError(
                    "fused SPMD step: batch dim %d of %r not divisible by "
                    "%d mesh devices" % (arr.shape[0], name, ndev))
            batch[name] = jax.device_put(arr._data(), sh)
        labels = getattr(data_batch, "label", None) or []
        for name, arr in zip(self._label_names, labels):
            batch[name] = jax.device_put(arr._data(), sh)
        key = jax.random.fold_in(self._key, self._step_no)
        self._carry, (loss, outs) = self._ts(self._carry, batch, key)
        self._step_no += 1
        self._loss = loss
        self._outputs = [nd.NDArray(o) for o in outs]

    def get_outputs(self):
        if self._outputs is None:
            raise MXNetError("fused SPMD step: no batch has run yet")
        return list(self._outputs)

    def update_metric(self, eval_metric, labels):
        # Same name-keyed dispatch as DataParallelExecutorGroup.update_metric
        # so metrics with output_names/label_names pick the right arrays.
        labels_ = dict(zip(self._label_names, labels))
        preds_ = dict(zip(self._output_names, self.get_outputs()))
        eval_metric.update_dict(labels_, preds_)

    # -- host sync -----------------------------------------------------------
    def copy_params_to(self, arg_params, aux_params):
        import jax

        params, _opt, aux, _step = self._carry
        host_p, host_a = jax.device_get((params, aux))  # one batched D2H
        for k in self.param_names:
            nd.NDArray(host_p[k]).copyto(arg_params[k])
        for k in self.aux_names:
            nd.NDArray(host_a[k]).copyto(aux_params[k])

    def _replace(self, params=None, opt_state=None, aux=None, step=None):
        """Re-place the carry, preserving the pieces not overridden."""
        import jax
        import jax.numpy as jnp
        from ..parallel.spmd import replicated

        old_p, old_o, old_a, old_s = self._carry
        p = params if params is not None else dict(old_p)
        o = opt_state if opt_state is not None else old_o
        a = aux if aux is not None else dict(old_a)
        carry = self._ts.place(p, o, a)
        s = old_s if step is None else jax.device_put(
            jnp.asarray(step, jnp.int32), replicated(self.mesh))
        self._carry = (carry[0], carry[1], carry[2], s)

    def set_params(self, arg_params, aux_params):
        """Reset device params/aux from host (e.g. after load)."""
        params = {k: arg_params[k]._data() for k in self.param_names}
        aux = {k: aux_params[k]._data() for k in self.aux_names}
        self._replace(params=params, aux=aux)

    # -- optimizer state -----------------------------------------------------
    _STATE_FORMAT = "fused-spmd-v1"

    def get_states(self):
        import jax

        _params, opt_state, _aux, step_no = self._carry
        host = jax.tree_util.tree_map(np.asarray, opt_state)
        return pickle.dumps({"format": self._STATE_FORMAT,
                             "opt_state": host, "step": int(step_no)})

    def set_states(self, blob):
        try:
            data = pickle.loads(blob)
        except Exception as e:
            raise MXNetError("fused SPMD step: unreadable optimizer states "
                             "(%s)" % e)
        if not isinstance(data, dict) or data.get("format") != self._STATE_FORMAT:
            raise MXNetError(
                "fused SPMD step: optimizer-state file was not written by the "
                "fused (kvstore='tpu') path; resume with the same kvstore "
                "type it was saved under")
        self._replace(opt_state=data["opt_state"], step=data["step"])
        self._step_no = data["step"]
