"""Fused SPMD execution group for Module(kvstore='tpu').

The reference's ``kvstore='device'/'nccl'`` tier runs one executor per GPU
and reduces gradients through a Comm tree (module/module.py:468-530 +
kvstore comm.h). The TPU-native tier replaces that whole pipeline with ONE
compiled XLA program per batch: forward + backward + optimizer update with
the batch sharded over the mesh's ``dp`` axis, so the gradient all-reduce
is a psum over ICI *inside* the step (the reference's priority-scheduled
push/pull overlap becomes XLA latency hiding).

Module routes ``forward_backward``/``update`` here when it detects a
``tpu`` kvstore; the kvstore itself carries the mesh (TPUKVStore.mesh) for
introspection parity.
"""
from __future__ import annotations

import collections
import pickle
import time

import numpy as np

from .. import config, profiler
from ..base import MXNetError
from ..ndarray import ndarray as nd
from ..parallel.feed import is_preplaced, place_batch_array
from ..parallel.spmd import (
    TrainStep,
    data_sharding,
    functional_from_optimizer,
)


class _DeviceMetricSource:
    """Device-resident (sum, count) accumulator attached to an EvalMetric
    by :meth:`FusedSPMDGroup.update_metric`. ``add`` folds one batch's
    in-step statistics with an async jitted device add (jit, not eager:
    the stats are replicated over the group's GLOBAL mesh, and eager ops
    on non-fully-addressable arrays are rejected on multi-host — jit is
    the supported multiprocess path); ``drain`` is the ONE blocking
    ``jax.device_get`` (legal on fully-replicated arrays), run by
    ``EvalMetric.get()`` at Speedometer/epoch boundaries."""

    def __init__(self, group, kind):
        self.group = group
        self.kind = kind  # stats key: "correct" | "sum_ce" | "sum_loss"
        self._sum = None
        self._n = None

    def add(self, stats):
        s, n = stats[self.kind], stats["n"]
        if self._sum is None:
            self._sum, self._n = s, n
        else:
            self._sum, self._n = self.group._metric_accumulate(
                (self._sum, self._n), (s, n))

    def drain(self):
        if self._sum is None:
            return 0.0, 0
        import jax

        s, n = jax.device_get((self._sum, self._n))
        self._sum = None
        self._n = None
        return float(s), int(n)

    def clear(self):
        self._sum = None
        self._n = None


class FusedSPMDGroup:
    """One fused train step over a dp mesh built from Module's contexts.

    With ``distributed=True`` (multi-process job via tools/launch.py /
    jax.distributed), the mesh is the GLOBAL ``("dcn", "dp")`` mesh from
    :func:`mxnet_tpu.dist.global_mesh`: every process contributes its
    local batch shard and the cross-host gradient all-reduce happens
    *inside* the compiled step over the dcn axis — XLA overlaps it with
    backprop (the reference got overlap from priority-scheduled push,
    model.py:126-137; the DistKVStore tier remains as the compatibility
    path when the fused step can't be used).
    """

    def __init__(self, symbol, contexts, optimizer, arg_params, aux_params,
                 data_names, label_names, fixed_param_names=None, logger=None,
                 batch_size=None, inputs_need_grad=False, distributed=False,
                 zero=None):
        import jax

        if fixed_param_names:
            raise MXNetError("fused SPMD step: fixed_param_names not supported")
        if inputs_need_grad:
            raise MXNetError("fused SPMD step: inputs_need_grad not supported")
        devices = [c.jax_device() for c in contexts]
        if len({id(d) for d in devices}) != len(devices):
            raise MXNetError("fused SPMD step: duplicate devices in context list")
        if batch_size is not None and batch_size % len(devices) != 0:
            raise MXNetError(
                "fused SPMD step: batch size %d not divisible by %d devices"
                % (batch_size, len(devices)))
        self.distributed = bool(distributed)
        # ISSUE 20: tensor parallelism — the strictly-validated knobs
        # split the contexts into a (dp, mp) mesh and hand the parsed
        # MXNET_MP_RULES to TrainStep's param_shardings. mp=1 (the
        # default) builds the identical 1-axis {"dp": N} mesh as before
        # — bit-identical to the pure data-parallel path.
        from ..parallel.mesh import mp_size, train_mesh
        from ..parallel.spmd import parse_rules

        mp = mp_size()
        self._param_rules = parse_rules(config.get("MXNET_MP_RULES"))
        if self.distributed:
            from .. import dist

            self._dist = dist
            if mp > 1:
                raise MXNetError(
                    "fused dist step: MXNET_MP_SIZE=%d is single-process "
                    "only for now (the multi-host (dcn, dp, mp) mesh is "
                    "the scripted on-chip follow-up — see ROADMAP)" % mp)
            if len(devices) != jax.local_device_count():
                raise MXNetError(
                    "fused dist step: contexts must cover all %d local "
                    "devices (got %d)"
                    % (jax.local_device_count(), len(devices)))
            self.mesh = dist.global_mesh({"dp": len(devices)})
            data_axes = self.mesh.axis_names  # ("dcn","dp") when multi-proc
        else:
            self._dist = None
            self.mesh = train_mesh(devices=devices, mp=mp)
            data_axes = ("dp",)
        self._data_axes = tuple(data_axes)
        if mp > 1 and batch_size is not None \
                and batch_size % (len(devices) // mp) != 0:
            raise MXNetError(
                "fused SPMD step: batch size %d not divisible by the "
                "dp size %d (MXNET_MP_SIZE=%d over %d devices)"
                % (batch_size, len(devices) // mp, mp, len(devices)))
        # ISSUE 5 knobs: bound on compiled steps dispatched ahead of the
        # device (donated carry makes >1 safe) and the in-step metric
        # statistics that keep the hot loop free of per-batch host syncs
        max_inflight = config.get_int("MXNET_TPU_MAX_INFLIGHT", 2)
        if max_inflight is None or max_inflight < 1:
            raise MXNetError(
                "MXNET_TPU_MAX_INFLIGHT must be an integer >= 1 (got %r)"
                % config.get("MXNET_TPU_MAX_INFLIGHT"))
        self._max_inflight = max_inflight
        self._inflight = collections.deque()
        self._device_metrics = config.get_bool("MXNET_TPU_DEVICE_METRICS",
                                               True)
        # ISSUE 7: weight-update sharding — explicit arg wins, else the
        # (strictly validated) MXNET_TPU_ZERO knob, so Module.fit users
        # opt in via env or ctor without touching jax
        if zero is None:
            zero = config.get_strict_bool("MXNET_TPU_ZERO")
        self.zero = bool(zero)
        self._fopt = functional_from_optimizer(
            optimizer, [n for n in symbol.list_arguments()
                        if n not in data_names and n not in label_names])
        # rescale_grad already carries the 1/batch normalization Module set.
        self._ts = TrainStep(
            symbol, self._fopt, mesh=self.mesh, data_axes=self._data_axes,
            param_rules=self._param_rules,
            data_names=tuple(data_names), label_names=tuple(label_names),
            compute_dtype=None, normalize_grads=False, return_outputs=True,
            metric_stats=self._device_metrics, zero=self.zero,
        )
        self.param_names = list(self._ts.param_names)
        self.aux_names = list(self._ts.aux_names)
        params = {k: arg_params[k]._data() for k in self.param_names}
        aux = {k: aux_params[k]._data() for k in self.aux_names}
        params, aux = self._sync_rank0(params, aux)
        opt_state = self._fopt.init(params)
        self._carry = self._ts.place(params, opt_state, aux)
        if mp > 1:
            # mpStats gauge (ISSUE 20): the measured per-chip footprint
            # of the freshly placed carry — the ~1/mp memory claim
            ms = self._ts.memory_stats(self._carry)
            profiler.mp_record(
                mp_size=mp, dp_size=len(devices) // mp,
                group_size=len(devices),
                param_bytes_per_chip=ms["param_bytes_per_dev"],
                live_bytes_per_chip=(ms["param_bytes_per_dev"]
                                     + ms["opt_bytes_per_dev"]
                                     + ms["aux_bytes_per_dev"]))
        self._data_names = list(data_names)
        self._label_names = list(label_names)
        self._output_names = list(symbol.list_outputs())
        self._key = jax.random.PRNGKey(0)
        self._step_no = 0
        self._loss = None
        self._outputs = None
        self._raw_outputs = None
        self._batch_sharding = data_sharding(self.mesh, self._data_axes)
        self._stats = None           # last step's in-program metric stats
        # per-metric double-accumulation guard: ids of the EvalMetric
        # objects that already folded the CURRENT batch's stats (a
        # batch-global flag would starve a second metric updated for
        # the same batch)
        self._stats_consumers = set()
        self._accum_fn = None        # jitted pairwise metric-stat add

    def _sync_rank0(self, params, aux):
        """Rank-0's host values win on every process (the reference's
        kvstore.init broadcast, kvstore_local.h) — one flattened
        collective for all params+aux. Arrays cross the wire as raw
        bytes (uint8) so every dtype — int64 counters, float64 — is
        bit-exact regardless of JAX's 32-bit canonicalization."""
        import jax

        if not self.distributed or jax.process_count() == 1:
            return params, aux
        keys_p = sorted(params)
        keys_a = sorted(aux)
        arrs = [np.ascontiguousarray(np.asarray(params[k])) for k in keys_p]
        arrs += [np.ascontiguousarray(np.asarray(aux[k])) for k in keys_a]
        if not arrs:
            return params, aux
        blob = np.frombuffer(b"".join(a.tobytes() for a in arrs), np.uint8)
        # the reduction promotes uint8 (sum dtype widening); every value
        # is still a byte (one nonzero contributor), so cast back
        buf = np.asarray(self._dist.broadcast0(blob),
                         np.uint8).tobytes()
        off = 0

        def take(a):
            nonlocal off
            v = np.frombuffer(buf, a.dtype, count=a.size,
                              offset=off).reshape(a.shape)
            off += a.nbytes
            return v

        out_p = {k: take(a) for k, a in zip(keys_p, arrs[:len(keys_p)])}
        out_a = {k: take(a) for k, a in zip(keys_a, arrs[len(keys_p):])}
        return out_p, out_a

    def _check_local_batch_agreement(self, n_rows_list):
        """A per-rank local-batch mismatch builds inconsistent global
        programs (a silent cross-host hang); turn it into an error.
        Runs unconditionally, ONE collective per batch covering every
        input array's leading dim: memoizing per-process would itself
        desynchronize ranks when one rank sees a repeat size while
        another sees a new one (unequal shard tails) — the exact
        deadlock this check exists to prevent."""
        # allgather the raw per-rank sizes and compare rows: exact for
        # any size < 2^31 (an allreduce of n^2 would wrap on the int32
        # wire — JAX canonicalizes int64 down — at n >= 46341)
        arr = np.asarray(n_rows_list, np.int32)
        rows = self._dist.allgather(arr)
        if not (rows == arr[None, :]).all():
            raise MXNetError(
                "fused dist step: local batch sizes %s differ across "
                "workers (per-rank sizes %s); pad or drop the tail "
                "batch so every rank agrees"
                % (list(n_rows_list), rows.tolist()))

    # -- the hot loop --------------------------------------------------------
    def forward_backward_update(self, data_batch):
        """Run one fused step: shard batch over the mesh data axes,
        fwd+bwd+update in XLA (cross-host all-reduce included).

        Batches already placed on the mesh (DeviceQueueIter) skip the
        device_put AND the per-batch cross-host agreement collective —
        a pre-placed global array fixed its global shape at
        construction. The step itself is dispatched asynchronously; the
        host throttles only when more than MXNET_TPU_MAX_INFLIGHT steps
        are outstanding (dispatch-ahead, ISSUE 5)."""
        import jax

        from .. import chaos

        # ISSUE 9 fault matrix: worker:R:nan@step=N poisons this step's
        # data batch — on the fused tier the gradient lives only inside
        # the compiled program, so the injection point is its input;
        # every gradient of the step goes non-finite, which is exactly
        # the class of silent fault the in-graph sentinel detects
        poison = chaos.nan_fault()
        arrays = list(zip(self._data_names, data_batch.data))
        labels = getattr(data_batch, "label", None) or []
        arrays += list(zip(self._label_names, labels))
        values = []
        host_rows = []
        for name, arr in arrays:
            value = arr._data() if isinstance(arr, nd.NDArray) else arr
            if poison and name == self._data_names[0]:
                value = value * np.float32("nan")
            if not is_preplaced(value, self._batch_sharding):
                host_rows.append(value.shape[0])
            values.append((name, value))
        if host_rows and self.distributed and jax.process_count() > 1:
            self._check_local_batch_agreement(host_rows)
        batch = {
            name: place_batch_array(self.mesh, self._data_axes,
                                    self.distributed, name, value,
                                    sharding=self._batch_sharding)
            for name, value in values
        }
        key = jax.random.fold_in(self._key, self._step_no)
        if self._device_metrics:
            self._carry, (loss, outs, stats) = self._ts(self._carry, batch,
                                                        key)
            self._stats = stats
            self._stats_consumers.clear()
        else:
            self._carry, (loss, outs) = self._ts(self._carry, batch, key)
        self._step_no += 1
        self._loss = loss
        # keep raw device arrays — materialization is deferred to
        # get_outputs() so the hot loop stays async when outputs
        # aren't consumed every step
        self._raw_outputs = outs
        self._outputs = None
        self._throttle(loss)

    def _throttle(self, token):
        """Dispatch-ahead bound: enqueue this step's completion token and
        block on the OLDEST one only when more than MXNET_TPU_MAX_INFLIGHT
        steps are outstanding — the host never runs unboundedly ahead of
        the device, but also never serializes on the step it just
        dispatched."""
        import jax

        self._inflight.append(token)
        while len(self._inflight) > self._max_inflight:
            t0 = time.perf_counter()
            jax.block_until_ready(self._inflight.popleft())
            profiler.h2d_record(
                stall_compute=time.perf_counter() - t0)
        profiler.h2d_record(steps=1, inflight=len(self._inflight))

    def drain(self):
        """Block until every dispatched step has retired. The explicit
        pipeline drain point: checkpoint/epoch/eval boundaries
        (copy_params_to, get_states) call it, and the PR 3 quiesce
        choreography inherits it through save_optimizer_states."""
        import jax

        while self._inflight:
            jax.block_until_ready(self._inflight.popleft())

    def _materialize_outputs(self, outs):
        """Wrap step outputs; in multi-process mode return each
        worker's own rows (the addressable shards of the global array),
        matching what this worker's metric expects to see."""
        import jax

        # a blocking device→host materialization: when this happens at
        # batch rate the loop is NOT stall-free — the profiler counter
        # is what the ISSUE 5 acceptance test asserts is zero on the
        # device-metric path
        profiler.h2d_record(host_syncs=1)
        if not self.distributed or jax.process_count() == 1:
            return [nd.NDArray(o) for o in outs]
        return [nd.array(self._local_rows_host(o)) for o in outs]

    @staticmethod
    def _local_rows_host(o):
        """One global device array → this worker's own rows on host:
        fully-replicated arrays dedupe to shard 0; sharded arrays
        reassemble the addressable shards in row order."""
        if getattr(o, "is_fully_replicated", False):
            return np.asarray(o.addressable_data(0))
        # shards live on different local devices: assemble on host
        shards = sorted(
            o.addressable_shards,
            key=lambda s: (s.index[0].start or 0) if s.index else 0)
        seen = set()
        pieces = []
        for s in shards:
            k = tuple((sl.start, sl.stop) for sl in s.index)
            if k in seen:
                continue
            seen.add(k)
            pieces.append(np.asarray(s.data))
        return np.concatenate(pieces, axis=0)

    def _materialize_labels(self, labels):
        """Pre-placed (DeviceQueueIter) labels in multi-process jobs are
        global arrays whose remote shards ``jax.device_get`` cannot
        fetch; pull back this worker's own rows, mirroring
        :meth:`_materialize_outputs` for preds. Host arrays and
        single-process device labels pass through — the metric's
        batched ``device_get`` handles those."""
        import jax

        if not self.distributed or jax.process_count() == 1:
            return list(labels)
        out = []
        for l in labels:
            data = l._data() if isinstance(l, nd.NDArray) else l
            if (type(data).__module__.startswith("jax")
                    and not getattr(data, "is_fully_addressable", True)):
                l = nd.array(self._local_rows_host(data))
            out.append(l)
        return out

    def get_outputs(self):
        if self._outputs is None:
            if self._raw_outputs is None:
                raise MXNetError("fused SPMD step: no batch has run yet")
            self._outputs = self._materialize_outputs(self._raw_outputs)
        return list(self._outputs)

    def _device_metric_plan(self, eval_metric):
        """[(leaf_metric, stats_key)] when EVERY leaf of eval_metric can
        be reproduced exactly from the in-step statistics; None forces
        the host fallback (mixed accumulation would double-count)."""
        from .. import metric as metric_mod

        # the in-step stats cover outputs[0]/labels[0] only; a
        # multi-output/multi-label graph's host metric sums over EVERY
        # (label, pred) pair — force the host path rather than silently
        # reporting half the pairs
        if len(self._output_names) != 1 or len(self._label_names) != 1:
            return None
        stats = self._stats
        leaves, stack = [], [eval_metric]
        while stack:
            m = stack.pop()
            if isinstance(m, metric_mod.CompositeEvalMetric):
                stack.extend(m.metrics)
                continue
            leaves.append(m)
        plan = []
        for m in leaves:
            if m.output_names is not None or m.label_names is not None:
                return None  # name-filtered metrics need the real arrays
            if (type(m) is metric_mod.Accuracy and m.axis == 1
                    and "correct" in stats):
                plan.append((m, "correct"))
            elif (type(m) in (metric_mod.CrossEntropy,
                              metric_mod.NegativeLogLikelihood)
                    and m.eps == 1e-12 and "sum_ce" in stats):
                plan.append((m, "sum_ce"))
            else:
                return None
        return plan

    def _metric_accumulate(self, acc, batch_stats):
        """Jitted pairwise add of (sum, n) device scalars (async; the
        multiprocess-legal way to combine replicated global arrays)."""
        import jax

        if self._accum_fn is None:
            self._accum_fn = jax.jit(
                lambda a, b: jax.tree_util.tree_map(
                    lambda x, y: x + y, a, b))
        return self._accum_fn(acc, batch_stats)

    def _attach_source(self, m, kind):
        by_kind = m.__dict__.setdefault("_fused_metric_srcs", {})
        src = by_kind.get((id(self), kind))
        if src is None:
            src = by_kind[(id(self), kind)] = _DeviceMetricSource(self, kind)
        m._attach_device_source(src)
        return src

    def update_metric(self, eval_metric, labels):
        # Device-resident path (ISSUE 5): fold the step's in-program
        # statistics into device accumulators — eager async adds, zero
        # host syncs; EvalMetric.get() drains them at Speedometer/epoch
        # boundaries. In multi-process jobs the stats are GLOBAL sums
        # (they psum across hosts inside the compiled step), so every
        # worker's log shows the global metric.
        if self._device_metrics and self._stats is not None:
            plan = self._device_metric_plan(eval_metric)
            if plan is not None:
                if id(eval_metric) not in self._stats_consumers:
                    for m, kind in plan:
                        self._attach_source(m, kind).add(self._stats)
                    self._stats_consumers.add(id(eval_metric))
                return
        # Host fallback — same name-keyed dispatch as
        # DataParallelExecutorGroup.update_metric so metrics with
        # output_names/label_names pick the right arrays. Materializes
        # outputs: a per-batch host sync (profiler host_syncs counts it).
        labels_ = dict(zip(self._label_names,
                           self._materialize_labels(labels)))
        preds_ = dict(zip(self._output_names, self.get_outputs()))
        eval_metric.update_dict(labels_, preds_)

    # -- host sync -----------------------------------------------------------
    def _fetch_host(self, tree):
        """Device tree → host tree, legal on EVERY tier. A plain
        ``jax.device_get`` crashes on global arrays with non-addressable
        shards (the multi-process tier — same bug class as the PR 5
        label fallback): fully-replicated leaves dedupe to this
        process's shard 0, and genuinely sharded leaves (ZeRO optimizer
        state) all-gather through a jitted identity first (the
        multiprocess-legal collective), then read the local copy.
        Single-process trees keep the one batched device_get."""
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if all(getattr(l, "is_fully_addressable", True) for l in leaves):
            return jax.device_get(tree)
        rep = None
        out = []
        for l in leaves:
            if getattr(l, "is_fully_addressable", True):
                out.append(jax.device_get(l))
            elif getattr(l, "is_fully_replicated", False):
                out.append(np.asarray(l.addressable_data(0)))
            else:
                if rep is None:
                    from ..parallel.spmd import replicated

                    rep = jax.jit(
                        lambda x: x,
                        out_shardings=replicated(self.mesh))
                out.append(np.asarray(rep(l).addressable_data(0)))
        return jax.tree_util.tree_unflatten(treedef, out)

    def copy_params_to(self, arg_params, aux_params):
        self.drain()
        params, _opt, aux, _step = self._carry
        host_p, host_a = self._fetch_host((params, aux))  # one batched D2H
        for k in self.param_names:
            nd.NDArray(host_p[k]).copyto(arg_params[k])
        for k in self.aux_names:
            nd.NDArray(host_a[k]).copyto(aux_params[k])

    def _replace(self, params=None, opt_state=None, aux=None, step=None):
        """Re-place the carry, preserving the pieces not overridden."""
        import jax
        import jax.numpy as jnp
        from ..parallel.spmd import replicated

        self.drain()
        old_p, old_o, old_a, old_s = self._carry
        p = params if params is not None else dict(old_p)
        o = opt_state if opt_state is not None else old_o
        a = aux if aux is not None else dict(old_a)
        carry = self._ts.place(p, o, a)
        s = old_s if step is None else jax.device_put(
            jnp.asarray(step, jnp.int32), replicated(self.mesh))
        self._carry = (carry[0], carry[1], carry[2], s)

    def set_params(self, arg_params, aux_params):
        """Reset device params/aux from host (e.g. after load). In
        distributed mode rank-0's values win, same as __init__ — a
        per-process re-init must not silently desynchronize ranks."""
        params = {k: arg_params[k]._data() for k in self.param_names}
        aux = {k: aux_params[k]._data() for k in self.aux_names}
        params, aux = self._sync_rank0(params, aux)
        self._replace(params=params, aux=aux)

    # -- optimizer state -----------------------------------------------------
    _STATE_FORMAT = "fused-spmd-v1"

    def get_states(self):
        self.drain()
        params, opt_state, _aux, step_no = self._carry
        # ONE tree fetch instead of a blocking np.asarray per state
        # array (ISSUE 5 satellite), through the per-shard/allgather
        # path so ZeRO-sharded state on the multi-process tier never
        # hits device_get's non-addressable crash (ISSUE 7 satellite).
        # The blob stores the LOGICAL layout — un-padded, param-shaped,
        # mesh-size independent — so a state saved under zero=True on N
        # devices restores bit-exactly under zero=False (and any mesh).
        host = self._fetch_host(opt_state)
        logical = self._ts.logical_opt_state(host, params)
        return pickle.dumps({"format": self._STATE_FORMAT,
                             "opt_state": logical, "step": int(step_no),
                             "zero": self._ts.zero})

    def set_states(self, blob):
        try:
            data = pickle.loads(blob)
        except Exception as e:
            raise MXNetError("fused SPMD step: unreadable optimizer states "
                             "(%s)" % e)
        if not isinstance(data, dict) or data.get("format") != self._STATE_FORMAT:
            raise MXNetError(
                "fused SPMD step: optimizer-state file was not written by the "
                "fused (kvstore='tpu') path; resume with the same kvstore "
                "type it was saved under")
        self._replace(opt_state=data["opt_state"], step=data["step"])
        self._step_no = data["step"]

    # -- self-healing (ISSUE 9) ----------------------------------------------
    @property
    def sentinel(self):
        return self._ts.sentinel

    def health_stats(self):
        """Drain the in-graph sentinel's device counters (None when the
        sentinel is off). ONE blocking read of replicated scalars —
        the HealthGuard amortizes it over MXNET_TPU_GUARD_INTERVAL
        batches; the counters themselves accumulate inside the compiled
        step, so the steady-state loop stays sync-free. Publishes the
        snapshot to the profiler healthStats gauge."""
        snap = self._ts.health_stats(self._carry)
        if snap is not None:
            profiler.health_sentinel(snap)
        return snap

    def reset_optimizer(self, optimizer):
        """Rebuild the compiled step around the (re-tuned) imperative
        optimizer — the HealthGuard LR-backoff path. Params/aux stay
        device-resident; optimizer state round-trips through the
        logical layout into a fresh TrainStep (a recompile: rollback
        is exceptional, correctness beats a warm jit cache). Sentinel
        counters restart from zero — a rollback must not instantly
        re-trigger on the pre-rollback consec count."""
        import jax
        import jax.numpy as jnp
        from ..parallel.spmd import replicated

        self.drain()
        params, opt_state, aux, step_no = self._carry
        host_opt = self._fetch_host(opt_state)
        logical = self._ts.logical_opt_state(host_opt, params)
        self._fopt = functional_from_optimizer(
            optimizer, list(self.param_names))
        self._ts = TrainStep(
            self._ts.symbol, self._fopt, mesh=self.mesh,
            data_axes=self._data_axes,
            param_rules=self._param_rules,
            data_names=tuple(self._data_names),
            label_names=tuple(self._label_names),
            compute_dtype=None, normalize_grads=False, return_outputs=True,
            metric_stats=self._device_metrics, zero=self.zero,
        )
        carry = self._ts.place(params, logical, aux)
        step = jax.device_put(
            jnp.asarray(int(self._fetch_host(step_no)), jnp.int32),
            replicated(self.mesh))
        self._carry = (carry[0], carry[1], carry[2], step)
