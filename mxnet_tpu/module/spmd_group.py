"""Fused SPMD execution group for Module(kvstore='tpu').

The reference's ``kvstore='device'/'nccl'`` tier runs one executor per GPU
and reduces gradients through a Comm tree (module/module.py:468-530 +
kvstore comm.h). The TPU-native tier replaces that whole pipeline with ONE
compiled XLA program per batch: forward + backward + optimizer update with
the batch sharded over the mesh's ``dp`` axis, so the gradient all-reduce
is a psum over ICI *inside* the step (the reference's priority-scheduled
push/pull overlap becomes XLA latency hiding).

Module routes ``forward_backward``/``update`` here when it detects a
``tpu`` kvstore; the kvstore itself carries the mesh (TPUKVStore.mesh) for
introspection parity.
"""
from __future__ import annotations

import pickle

import numpy as np

from ..base import MXNetError
from ..ndarray import ndarray as nd
from ..parallel.mesh import make_mesh
from ..parallel.spmd import (
    TrainStep,
    data_sharding,
    functional_from_optimizer,
)


class FusedSPMDGroup:
    """One fused train step over a dp mesh built from Module's contexts.

    With ``distributed=True`` (multi-process job via tools/launch.py /
    jax.distributed), the mesh is the GLOBAL ``("dcn", "dp")`` mesh from
    :func:`mxnet_tpu.dist.global_mesh`: every process contributes its
    local batch shard and the cross-host gradient all-reduce happens
    *inside* the compiled step over the dcn axis — XLA overlaps it with
    backprop (the reference got overlap from priority-scheduled push,
    model.py:126-137; the DistKVStore tier remains as the compatibility
    path when the fused step can't be used).
    """

    def __init__(self, symbol, contexts, optimizer, arg_params, aux_params,
                 data_names, label_names, fixed_param_names=None, logger=None,
                 batch_size=None, inputs_need_grad=False, distributed=False):
        import jax

        if fixed_param_names:
            raise MXNetError("fused SPMD step: fixed_param_names not supported")
        if inputs_need_grad:
            raise MXNetError("fused SPMD step: inputs_need_grad not supported")
        devices = [c.jax_device() for c in contexts]
        if len({id(d) for d in devices}) != len(devices):
            raise MXNetError("fused SPMD step: duplicate devices in context list")
        if batch_size is not None and batch_size % len(devices) != 0:
            raise MXNetError(
                "fused SPMD step: batch size %d not divisible by %d devices"
                % (batch_size, len(devices)))
        self.distributed = bool(distributed)
        if self.distributed:
            from .. import dist

            self._dist = dist
            if len(devices) != jax.local_device_count():
                raise MXNetError(
                    "fused dist step: contexts must cover all %d local "
                    "devices (got %d)"
                    % (jax.local_device_count(), len(devices)))
            self.mesh = dist.global_mesh({"dp": len(devices)})
            data_axes = self.mesh.axis_names  # ("dcn","dp") when multi-proc
        else:
            self._dist = None
            self.mesh = make_mesh({"dp": len(devices)}, devices=devices)
            data_axes = ("dp",)
        self._data_axes = tuple(data_axes)
        self._fopt = functional_from_optimizer(
            optimizer, [n for n in symbol.list_arguments()
                        if n not in data_names and n not in label_names])
        # rescale_grad already carries the 1/batch normalization Module set.
        self._ts = TrainStep(
            symbol, self._fopt, mesh=self.mesh, data_axes=self._data_axes,
            data_names=tuple(data_names), label_names=tuple(label_names),
            compute_dtype=None, normalize_grads=False, return_outputs=True,
        )
        self.param_names = list(self._ts.param_names)
        self.aux_names = list(self._ts.aux_names)
        params = {k: arg_params[k]._data() for k in self.param_names}
        aux = {k: aux_params[k]._data() for k in self.aux_names}
        params, aux = self._sync_rank0(params, aux)
        opt_state = self._fopt.init(params)
        self._carry = self._ts.place(params, opt_state, aux)
        self._data_names = list(data_names)
        self._label_names = list(label_names)
        self._output_names = list(symbol.list_outputs())
        self._key = jax.random.PRNGKey(0)
        self._step_no = 0
        self._loss = None
        self._outputs = None
        self._raw_outputs = None

    def _sync_rank0(self, params, aux):
        """Rank-0's host values win on every process (the reference's
        kvstore.init broadcast, kvstore_local.h) — one flattened
        collective for all params+aux. Arrays cross the wire as raw
        bytes (uint8) so every dtype — int64 counters, float64 — is
        bit-exact regardless of JAX's 32-bit canonicalization."""
        import jax

        if not self.distributed or jax.process_count() == 1:
            return params, aux
        keys_p = sorted(params)
        keys_a = sorted(aux)
        arrs = [np.ascontiguousarray(np.asarray(params[k])) for k in keys_p]
        arrs += [np.ascontiguousarray(np.asarray(aux[k])) for k in keys_a]
        if not arrs:
            return params, aux
        blob = np.frombuffer(b"".join(a.tobytes() for a in arrs), np.uint8)
        # the reduction promotes uint8 (sum dtype widening); every value
        # is still a byte (one nonzero contributor), so cast back
        buf = np.asarray(self._dist.broadcast0(blob),
                         np.uint8).tobytes()
        off = 0

        def take(a):
            nonlocal off
            v = np.frombuffer(buf, a.dtype, count=a.size,
                              offset=off).reshape(a.shape)
            off += a.nbytes
            return v

        out_p = {k: take(a) for k, a in zip(keys_p, arrs[:len(keys_p)])}
        out_a = {k: take(a) for k, a in zip(keys_a, arrs[len(keys_p):])}
        return out_p, out_a

    def _check_local_batch_agreement(self, n_rows_list):
        """A per-rank local-batch mismatch builds inconsistent global
        programs (a silent cross-host hang); turn it into an error.
        Runs unconditionally, ONE collective per batch covering every
        input array's leading dim: memoizing per-process would itself
        desynchronize ranks when one rank sees a repeat size while
        another sees a new one (unequal shard tails) — the exact
        deadlock this check exists to prevent."""
        # allgather the raw per-rank sizes and compare rows: exact for
        # any size < 2^31 (an allreduce of n^2 would wrap on the int32
        # wire — JAX canonicalizes int64 down — at n >= 46341)
        arr = np.asarray(n_rows_list, np.int32)
        rows = self._dist.allgather(arr)
        if not (rows == arr[None, :]).all():
            raise MXNetError(
                "fused dist step: local batch sizes %s differ across "
                "workers (per-rank sizes %s); pad or drop the tail "
                "batch so every rank agrees"
                % (list(n_rows_list), rows.tolist()))

    def _put_batch_array(self, name, arr):
        """Host batch → device: local device_put, or the process-local
        shard of the global batch in distributed mode."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        value = arr._data()
        if not self.distributed or jax.process_count() == 1:
            ndev = self.mesh.devices.size
            if value.shape[0] % ndev != 0:
                raise MXNetError(
                    "fused SPMD step: batch dim %d of %r not divisible by "
                    "%d mesh devices" % (value.shape[0], name, ndev))
            return jax.device_put(value, data_sharding(self.mesh,
                                                       self._data_axes))
        local = np.asarray(value)
        nproc = jax.process_count()
        if local.shape[0] % jax.local_device_count() != 0:
            raise MXNetError(
                "fused dist step: local batch dim %d of %r not divisible "
                "by %d local devices"
                % (local.shape[0], name, jax.local_device_count()))
        sh = NamedSharding(self.mesh, P(self._data_axes))
        return jax.make_array_from_process_local_data(
            sh, local, global_shape=(local.shape[0] * nproc,) + local.shape[1:])

    # -- the hot loop --------------------------------------------------------
    def forward_backward_update(self, data_batch):
        """Run one fused step: shard batch over the mesh data axes,
        fwd+bwd+update in XLA (cross-host all-reduce included)."""
        import jax

        arrays = list(zip(self._data_names, data_batch.data))
        labels = getattr(data_batch, "label", None) or []
        arrays += list(zip(self._label_names, labels))
        if self.distributed and jax.process_count() > 1:
            self._check_local_batch_agreement(
                [a.shape[0] for _n, a in arrays])
        batch = {}
        for name, arr in arrays:
            batch[name] = self._put_batch_array(name, arr)
        key = jax.random.fold_in(self._key, self._step_no)
        self._carry, (loss, outs) = self._ts(self._carry, batch, key)
        self._step_no += 1
        self._loss = loss
        # keep raw device arrays — materialization is deferred to
        # get_outputs() so the hot loop stays async when outputs
        # aren't consumed every step
        self._raw_outputs = outs
        self._outputs = None

    def _materialize_outputs(self, outs):
        """Wrap step outputs; in multi-process mode return each
        worker's own rows (the addressable shards of the global array),
        matching what this worker's metric expects to see."""
        import jax

        if not self.distributed or jax.process_count() == 1:
            return [nd.NDArray(o) for o in outs]
        res = []
        for o in outs:
            if getattr(o, "is_fully_replicated", False):
                res.append(nd.array(np.asarray(o.addressable_data(0))))
                continue
            # shards live on different local devices: assemble on host
            shards = sorted(
                o.addressable_shards,
                key=lambda s: (s.index[0].start or 0) if s.index else 0)
            seen = set()
            pieces = []
            for s in shards:
                k = tuple((sl.start, sl.stop) for sl in s.index)
                if k in seen:
                    continue
                seen.add(k)
                pieces.append(np.asarray(s.data))
            res.append(nd.array(np.concatenate(pieces, axis=0)))
        return res

    def get_outputs(self):
        if self._outputs is None:
            if self._raw_outputs is None:
                raise MXNetError("fused SPMD step: no batch has run yet")
            self._outputs = self._materialize_outputs(self._raw_outputs)
        return list(self._outputs)

    def update_metric(self, eval_metric, labels):
        # Same name-keyed dispatch as DataParallelExecutorGroup.update_metric
        # so metrics with output_names/label_names pick the right arrays.
        labels_ = dict(zip(self._label_names, labels))
        preds_ = dict(zip(self._output_names, self.get_outputs()))
        eval_metric.update_dict(labels_, preds_)

    # -- host sync -----------------------------------------------------------
    def copy_params_to(self, arg_params, aux_params):
        import jax

        params, _opt, aux, _step = self._carry
        host_p, host_a = jax.device_get((params, aux))  # one batched D2H
        for k in self.param_names:
            nd.NDArray(host_p[k]).copyto(arg_params[k])
        for k in self.aux_names:
            nd.NDArray(host_a[k]).copyto(aux_params[k])

    def _replace(self, params=None, opt_state=None, aux=None, step=None):
        """Re-place the carry, preserving the pieces not overridden."""
        import jax
        import jax.numpy as jnp
        from ..parallel.spmd import replicated

        old_p, old_o, old_a, old_s = self._carry
        p = params if params is not None else dict(old_p)
        o = opt_state if opt_state is not None else old_o
        a = aux if aux is not None else dict(old_a)
        carry = self._ts.place(p, o, a)
        s = old_s if step is None else jax.device_put(
            jnp.asarray(step, jnp.int32), replicated(self.mesh))
        self._carry = (carry[0], carry[1], carry[2], s)

    def set_params(self, arg_params, aux_params):
        """Reset device params/aux from host (e.g. after load). In
        distributed mode rank-0's values win, same as __init__ — a
        per-process re-init must not silently desynchronize ranks."""
        params = {k: arg_params[k]._data() for k in self.param_names}
        aux = {k: aux_params[k]._data() for k in self.aux_names}
        params, aux = self._sync_rank0(params, aux)
        self._replace(params=params, aux=aux)

    # -- optimizer state -----------------------------------------------------
    _STATE_FORMAT = "fused-spmd-v1"

    def get_states(self):
        import jax

        _params, opt_state, _aux, step_no = self._carry
        host = jax.tree_util.tree_map(np.asarray, opt_state)
        return pickle.dumps({"format": self._STATE_FORMAT,
                             "opt_state": host, "step": int(step_no)})

    def set_states(self, blob):
        try:
            data = pickle.loads(blob)
        except Exception as e:
            raise MXNetError("fused SPMD step: unreadable optimizer states "
                             "(%s)" % e)
        if not isinstance(data, dict) or data.get("format") != self._STATE_FORMAT:
            raise MXNetError(
                "fused SPMD step: optimizer-state file was not written by the "
                "fused (kvstore='tpu') path; resume with the same kvstore "
                "type it was saved under")
        self._replace(opt_state=data["opt_state"], step=data["step"])
        self._step_no = data["step"]
