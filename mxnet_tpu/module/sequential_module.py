"""SequentialModule — chain sub-modules into one training pipeline.

Reference counterpart: ``python/mxnet/module/sequential_module.py``
(SequentialModule.add with META_TAKE_LABELS / META_AUTO_WIRING, chained
forward/backward). Each sub-module's outputs become the next one's data;
gradients flow back through ``get_input_grads``.
"""
from __future__ import annotations

import logging

from ..initializer import Uniform
from ..io import DataDesc
from .base_module import BaseModule


def _norm(shapes):
    """[(name, shape)] from DataDesc or tuple entries."""
    out = []
    for d in shapes:
        if isinstance(d, DataDesc):
            out.append((d.name, tuple(d.shape)))
        else:
            out.append((d[0], tuple(d[1])))
    return out


class SequentialModule(BaseModule):
    """A container chaining several modules end to end."""

    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._label_shapes = None
        self._data_shapes = None
        self._meta_keys = {self.META_TAKE_LABELS, self.META_AUTO_WIRING}

    def add(self, module, **kwargs):
        """Append a module. ``take_labels=True`` routes the pipeline's
        labels to this module; ``auto_wiring=True`` renames this module's
        data to the previous module's outputs."""
        self._modules.append(module)
        for key in kwargs:
            assert key in self._meta_keys, (
                "unknown meta %r (known: %s)" % (key, self._meta_keys))
        self._metas.append(kwargs)
        # binding state resets whenever the chain changes
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self  # chaining: seq.add(a).add(b)

    # -- properties ----------------------------------------------------------
    @property
    def data_names(self):
        if self._modules:
            return self._modules[0].data_names
        return []

    @property
    def output_names(self):
        if self._modules:
            return self._modules[-1].output_names
        return []

    @property
    def data_shapes(self):
        assert self.binded
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._modules[-1].output_shapes

    # -- params --------------------------------------------------------------
    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params, aux_params = {}, {}
        for module in self._modules:
            arg, aux = module.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return arg_params, aux_params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before init_params"
        initializer = initializer or Uniform(0.01)
        for module in self._modules:
            module.init_params(initializer=initializer, arg_params=arg_params,
                               aux_params=aux_params, allow_missing=True,
                               force_init=force_init, allow_extra=True)

        # no duplicate parameter names across sub-modules (ref _check_name)
        seen = {}
        for i, module in enumerate(self._modules):
            arg, aux = module.get_params()
            for name in list(arg) + list(aux):
                assert name not in seen, (
                    "duplicate parameter %r in modules %d and %d"
                    % (name, seen[name], i))
                seen[name] = i
        self.params_initialized = True

    # -- bind ----------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        assert shared_module is None, (
            "shared_module is not supported for SequentialModule")
        assert len(self._modules) > 0, "add modules before bind"
        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._label_shapes = label_shapes

        my_data_shapes = data_shapes
        anybody_ever_needs_label = False
        for i, (module, meta) in enumerate(zip(self._modules, self._metas)):
            take_labels = meta.get(self.META_TAKE_LABELS, False)
            my_label_shapes = label_shapes if take_labels else None
            if take_labels:
                anybody_ever_needs_label = True
            my_inputs_need_grad = inputs_need_grad if i == 0 else True
            if meta.get(self.META_AUTO_WIRING, False) and i > 0:
                # rename the previous outputs to this module's data names
                data_names = module.data_names
                assert len(data_names) == len(my_data_shapes)
                my_data_shapes = [
                    (name, shape)
                    for name, (_, shape) in zip(data_names,
                                                _norm(my_data_shapes))
                ]
            module.bind(data_shapes=my_data_shapes,
                        label_shapes=my_label_shapes,
                        for_training=for_training,
                        inputs_need_grad=my_inputs_need_grad,
                        force_rebind=force_rebind, grad_req=grad_req)
            # next module consumes this one's outputs
            my_data_shapes = _norm(module.output_shapes)
        if not anybody_ever_needs_label:
            self._label_shapes = None

    # -- optimizer -----------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        for module in self._modules:
            module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                  optimizer_params=optimizer_params,
                                  force_init=force_init)
        self.optimizer_initialized = True

    # -- computation ---------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        from ..io import DataBatch

        batch = data_batch
        for i, module in enumerate(self._modules):
            module.forward(batch, is_train=is_train)
            if i == len(self._modules) - 1:
                break
            batch = DataBatch(data=module.get_outputs(),
                              label=data_batch.label)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        for i, module in reversed(list(enumerate(self._modules))):
            module.backward(out_grads=out_grads)
            if i == 0:
                break
            out_grads = module.get_input_grads()

    def update(self):
        assert self.binded and self.params_initialized and self.optimizer_initialized
        for module in self._modules:
            module.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and self.inputs_need_grad
        return self._modules[0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        assert self.binded and self.params_initialized
        for meta, module in zip(self._metas, self._modules):
            if meta.get(self.META_TAKE_LABELS, False):
                module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        assert self.binded
        for module in self._modules:
            module.install_monitor(mon)
