"""DataParallelExecutorGroup — per-device executors + batch slicing.

Reference counterpart: ``python/mxnet/module/executor_group.py`` (:65
_split_input_slice/_load_data, :128 class). On TPU, single-device groups
dominate (mesh sharding happens inside the compiled step); the multi-ctx
path mirrors the reference so unit tests can treat N cpu contexts as
distinct devices (SURVEY §4 'fakes').
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..executor import simple_bind
from ..io import DataDesc
from ..ndarray import ndarray as nd


def _split_input_slice(batch_size, work_load_list):
    """Slice batch among devices proportionally (ref: executor_group.py:65)."""
    total = sum(work_load_list)
    if batch_size < len(work_load_list):
        raise MXNetError("batch size smaller than device count")
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        if i == len(work_load_list) - 1:
            end = batch_size
        else:
            end = start + int(round(batch_size * w / total))
        slices.append(slice(start, end))
        start = end
    return slices


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad, shared_group=None,
                 logger=None, fixed_param_names=None, grad_req="write", state_names=None,
                 group2ctxs=None):
        self.symbol = symbol
        self.contexts = contexts
        self.workload = workload or [1] * len(contexts)
        self.param_names = param_names
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = set(fixed_param_names or [])
        self.state_names = set(state_names or [])
        self.group2ctxs = group2ctxs or [None] * len(contexts)
        self.logger = logger

        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()

        self.data_names = [d.name if isinstance(d, DataDesc) else d[0] for d in data_shapes]
        self.label_names = (
            [l.name if isinstance(l, DataDesc) else l[0] for l in label_shapes]
            if label_shapes
            else []
        )

        self.grad_req = {}
        for name in self.arg_names:
            if name in self.param_names:
                self.grad_req[name] = (
                    "null" if (not for_training or name in self.fixed_param_names) else grad_req
                )
            elif name in self.data_names:
                self.grad_req[name] = grad_req if inputs_need_grad else "null"
            else:
                self.grad_req[name] = "null"

        self.batch_size = None
        self.slices = None
        self.execs = []
        self._total_data_shapes = None
        self._total_label_shapes = None
        self.shared_group = shared_group
        self.bind_exec(data_shapes, label_shapes, shared_group)

    def bind_exec(self, data_shapes, label_shapes, shared_group=None, reshape=False):
        self.batch_size = None
        norm_data = []
        for d in data_shapes:
            name, shape = (d.name, d.shape) if isinstance(d, DataDesc) else (d[0], d[1])
            if self.batch_size is None:
                self.batch_size = shape[0]
            norm_data.append((name, tuple(shape)))
        norm_label = []
        for l in label_shapes or []:
            name, shape = (l.name, l.shape) if isinstance(l, DataDesc) else (l[0], l[1])
            norm_label.append((name, tuple(shape)))
        self._total_data_shapes = norm_data
        self._total_label_shapes = norm_label

        self.slices = _split_input_slice(self.batch_size, self.workload)
        self.execs = []
        for i, ctx in enumerate(self.contexts):
            sl = self.slices[i]
            n_i = sl.stop - sl.start
            shapes = {}
            for name, shape in norm_data + norm_label:
                shapes[name] = (n_i,) + tuple(shape[1:])
            shared = shared_group.execs[i] if shared_group is not None else None
            self.execs.append(
                simple_bind(self.symbol, ctx, grad_req=self.grad_req, shared_exec=shared,
                            group2ctx=self.group2ctxs[i], **shapes)
            )
        # param arrays: list (per param) of list (per device)
        self.param_arrays = [
            [e.arg_dict[name] for e in self.execs] for name in self.param_names
        ]
        self.grad_arrays = [
            [e.grad_dict.get(name) for e in self.execs]
            for name in self.param_names
        ]
        self.data_arrays = [
            [e.arg_dict[name] for e in self.execs] for name in self.data_names
        ]
        self.label_arrays = [
            [e.arg_dict.get(name) for e in self.execs] for name in self.label_names
        ]
        self.aux_arrays = [
            [e.aux_dict[name] for e in self.execs] for name in self.aux_names
        ]
        self.input_grad_arrays = (
            [[e.grad_dict.get(name) for e in self.execs] for name in self.data_names]
            if self.inputs_need_grad
            else []
        )

    @property
    def data_shapes(self):
        return [DataDesc(n, s) for n, s in self._total_data_shapes]

    @property
    def label_shapes(self):
        return [DataDesc(n, s) for n, s in self._total_label_shapes]

    def reshape(self, data_shapes, label_shapes):
        self.bind_exec(data_shapes, label_shapes, self.shared_group)

    def set_params(self, arg_params, aux_params, allow_extra=False):
        for e in self.execs:
            e.copy_params_from(arg_params, aux_params, allow_extra_params=allow_extra)

    def get_params(self, arg_params, aux_params):
        """Average over devices into the given dicts (ref: executor_group.get_params)."""
        for name, block in zip(self.param_names, self.param_arrays):
            full = sum(w.asnumpy() for w in block) / len(block)
            arg_params[name][:] = nd.array(full, dtype=arg_params[name].dtype)
        for name, block in zip(self.aux_names, self.aux_arrays):
            full = sum(w.asnumpy() for w in block) / len(block)
            aux_params[name][:] = nd.array(full, dtype=aux_params[name].dtype)

    def _load_slice(self, arrays, targets):
        """Scatter batch slices to per-device input arrays (ref: _load_data)."""
        import jax

        for arr, per_dev in zip(arrays, targets):
            if arr is None:
                continue
            for sl, tgt in zip(self.slices, per_dev):
                if tgt is None:
                    continue
                chunk = arr[sl] if (sl.stop - sl.start) != arr.shape[0] else arr
                if hasattr(chunk, "_data"):
                    val = chunk._data().astype(tgt._data().dtype)
                    val = jax.device_put(val, tgt.ctx.jax_device())
                    tgt._rebind(val)
                else:
                    tgt[:] = nd.array(chunk, ctx=tgt.ctx, dtype=tgt.dtype)

    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        self._load_slice(data_batch.data, self.data_arrays)
        if data_batch.label is not None and self.label_names:
            self._load_slice(data_batch.label, self.label_arrays)
        for e in self.execs:
            e.forward(is_train=is_train)

    def forward_backward(self, data_batch):
        """Fused step — the TPU hot path (one XLA program per device)."""
        self._load_slice(data_batch.data, self.data_arrays)
        if data_batch.label is not None and self.label_names:
            self._load_slice(data_batch.label, self.label_arrays)
        for e in self.execs:
            e.forward_backward()

    def backward(self, out_grads=None):
        for i, e in enumerate(self.execs):
            og = None
            if out_grads is not None:
                og = [g[self.slices[i]] if g is not None else None for g in out_grads]
            e.backward(og)

    def get_outputs(self, merge_multi_context=True):
        outputs = [[e.outputs[i] for e in self.execs] for i in range(len(self.execs[0].outputs))]
        if merge_multi_context:
            return [
                outs[0] if len(outs) == 1 else nd.concatenate(outs, axis=0) for outs in outputs
            ]
        return outputs

    def get_input_grads(self, merge_multi_context=True):
        if not self.inputs_need_grad:
            raise MXNetError("bind with inputs_need_grad=True")
        grads = [[e.grad_dict.get(n) for e in self.execs] for n in self.data_names]
        if merge_multi_context:
            return [g[0] if len(g) == 1 else nd.concatenate(g, axis=0) for g in grads]
        return grads

    def update_metric(self, eval_metric, labels):
        for i, e in enumerate(self.execs):
            labels_slice = [l[self.slices[i]] if l.shape[0] != (self.slices[i].stop - self.slices[i].start) else l for l in labels]
            eval_metric.update_dict(
                dict(zip(self.label_names, labels_slice)),
                dict(zip(self.symbol.list_outputs(), e.outputs)),
            )

    def install_monitor(self, mon):
        for e in self.execs:
            mon.install(e)
