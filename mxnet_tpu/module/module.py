"""Module — symbol + executor group + optimizer intermediate API.

Reference counterpart: ``python/mxnet/module/module.py:39-736`` (bind →
DataParallelExecutorGroup, init_params, init_optimizer with kvstore,
update via _update_params_on_kvstore).
"""
from __future__ import annotations

import logging
import warnings

from .. import context as ctx_mod
from .. import optimizer as opt_mod
from ..base import MXNetError
from ..initializer import InitDesc, Uniform
from ..io import DataDesc
from ..model import (
    _create_kvstore,
    _initialize_kvstore,
    _update_params,
    _update_params_on_kvstore,
    load_checkpoint,
    save_checkpoint,
)
from ..ndarray import ndarray as nd
from .base_module import BaseModule, _check_input_names
from .executor_group import DataParallelExecutorGroup


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None, zero=None):
        super().__init__(logger=logger)
        # ISSUE 7: weight-update sharding on the fused tier. True/False
        # forces it; None defers to the MXNET_TPU_ZERO env knob — so
        # Module.fit users get ZeRO without touching jax.
        self._zero = zero
        if context is None:
            context = ctx_mod.current_context()
        if isinstance(context, ctx_mod.Context):
            context = [context]
        self._context = context
        # per-device ctx-group maps (ref: module.py group2ctxs — a dict
        # shared by all devices, or a list of dicts, one per device)
        if isinstance(group2ctxs, dict) or group2ctxs is None:
            group2ctxs = [group2ctxs] * len(self._context)
        assert len(group2ctxs) == len(self._context)
        self._group2ctxs = group2ctxs
        if work_load_list is None:
            work_load_list = [1] * len(self._context)
        assert len(work_load_list) == len(self._context)
        self._work_load_list = work_load_list

        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []

        arg_names = symbol.list_arguments()
        input_names = data_names + label_names
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = list(fixed_param_names or [])
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = list(state_names or [])
        self._output_names = symbol.list_outputs()

        _check_input_names(symbol, data_names, "data", True)
        _check_input_names(symbol, label_names, "label", False)
        _check_input_names(symbol, self._state_names, "state", True)
        _check_input_names(symbol, self._fixed_param_names, "fixed_param", True)

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._compression_params = compression_params
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._exec_group = None
        self._fused = None
        self._data_shapes = None
        self._label_shapes = None

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        self._sync_params_from_devices()
        save_checkpoint(prefix, epoch, self.symbol, *self.get_params())
        if save_optimizer_states:
            state_name = "%s-%04d.states" % (prefix, epoch)
            self.save_optimizer_states(state_name)

    def _reset_bind(self):
        self.binded = False
        self._exec_group = None
        if self._fused is not None:
            # the fused group owns the device-resident optimizer state:
            # dropping it must force init_optimizer to rebuild the
            # group, else a re-bound fit() silently trains unfused
            self.optimizer_initialized = False
        self._fused = None
        self._data_shapes = None
        self._label_shapes = None

    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._exec_group.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._exec_group.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        # inferred once per bind/reshape (ref: module.py output_shapes
        # comes from the bound graph's inferred shapes, not a forward)
        key = tuple(self._exec_group._total_data_shapes
                    + self._exec_group._total_label_shapes)
        cached = getattr(self, "_output_shape_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        _, out_shapes, _ = self._symbol.infer_shape(**dict(key))
        result = list(zip(self._output_names,
                          [tuple(s) for s in out_shapes]))
        self._output_shape_cache = (key, result)
        return result

    def get_params(self):
        assert self.binded and self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            warnings.warn("Parameters already initialized and force_init=False. "
                          "init_params call ignored.", stacklevel=2)
            return
        assert self.binded, "call bind before initializing the parameters"
        if initializer is None:
            initializer = Uniform(0.01)

        def _impl(name, arr, cache):
            if cache is not None:
                if name in cache:
                    cache_arr = cache[name]
                    if cache_arr is not arr:
                        cache_arr.copyto(arr)
                else:
                    if not allow_missing:
                        raise RuntimeError("%s is not presented" % name)
                    if initializer is not None:
                        initializer(InitDesc(name, self._arg_attrs.get(name)), arr)
            else:
                initializer(InitDesc(name, self._arg_attrs.get(name)), arr)

        attrs = self._symbol.attr_dict()
        self._arg_attrs = {n: attrs.get(n, {}) for n in self._param_names + self._aux_names}

        if self._arg_params is None:
            self._arg_params = {
                name: nd.zeros(block[0].shape, dtype=block[0].dtype)
                for name, block in zip(self._param_names, self._exec_group.param_arrays)
            }
        if self._aux_params is None:
            self._aux_params = {
                name: nd.zeros(block[0].shape, dtype=block[0].dtype)
                for name, block in zip(self._aux_names, self._exec_group.aux_arrays)
            }

        for name, arr in sorted(self._arg_params.items()):
            _impl(name, arr, arg_params)
        for name, arr in sorted(self._aux_params.items()):
            _impl(name, arr, aux_params)

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params, allow_extra=allow_extra)
        if self._fused is not None:
            self._fused.set_params(self._arg_params, self._aux_params)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None, grad_req="write"):
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        if not for_training:
            assert not inputs_need_grad

        self._data_shapes = data_shapes
        self._label_shapes = label_shapes

        shared_group = None
        if shared_module is not None:
            assert shared_module.binded and shared_module.params_initialized
            shared_group = shared_module._exec_group

        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list, data_shapes,
            label_shapes, self._param_names, for_training, inputs_need_grad,
            shared_group, logger=self.logger, fixed_param_names=self._fixed_param_names,
            grad_req=grad_req, state_names=self._state_names,
            group2ctxs=self._group2ctxs,
        )
        if shared_module is not None:
            self.params_initialized = True
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
        elif self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params)

    def reshape(self, data_shapes, label_shapes=None):
        assert self.binded
        # host copies must be refreshed from the *old* executors before
        # they are replaced
        if self.params_initialized and self._params_dirty:
            self._sync_params_from_devices()
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self._exec_group.reshape(data_shapes, label_shapes)
        # rebinding allocated fresh (zeroed) arg arrays — restore weights
        # (ref: reshape shares the original arrays; here buffers are new)
        if self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params)

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),), force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return
        if self._params_dirty:
            self._sync_params_from_devices()

        (kvstore, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context), self._arg_params
        )
        batch_size = self._exec_group.batch_size
        if kvstore and "dist" in kvstore.type and "_async" in kvstore.type:
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        idx2name = {}
        if update_on_kvstore:
            idx2name.update(enumerate(self._exec_group.param_names))
        else:
            for k in range(len(self._context)):
                idx2name.update(
                    {i * len(self._context) + k: n for i, n in enumerate(self._exec_group.param_names)}
                )
        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt_mod.create(optimizer, sym=self.symbol,
                                       param_idx2name=idx2name, **optimizer_params)
        else:
            assert isinstance(optimizer, opt_mod.Optimizer)
            if optimizer.rescale_grad != rescale_grad:
                warnings.warn(
                    "Optimizer created manually outside Module but rescale_grad "
                    "is not normalized to 1.0/batch_size/num_workers (%s vs. %s). "
                    % (optimizer.rescale_grad, rescale_grad)
                )
            if not optimizer.idx2name:
                optimizer.idx2name = idx2name.copy()

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        # kvstore='tpu': run the whole train step (fwd+bwd+update) as one
        # compiled SPMD program over a mesh built from the context list —
        # the TPU answer to the reference's DataParallelExecutorGroup +
        # Comm reduce (module.py:468-530, comm.h). Falls back to the
        # per-executor path for optimizers the fused step can't mirror.
        self._fused = None
        fused_types = ("tpu", "dist_sync", "dist_sync_device", "dist_async")
        if (kvstore is not None and kvstore.type in fused_types
                and not getattr(kvstore, "server_side", False)
                and self.for_training):
            from .spmd_group import FusedSPMDGroup

            distributed = kvstore.type.startswith("dist")
            try:
                self._fused = FusedSPMDGroup(
                    self._symbol, self._context, self._optimizer,
                    self._arg_params, self._aux_params,
                    self._data_names, self._label_names,
                    fixed_param_names=self._fixed_param_names,
                    logger=self.logger,
                    batch_size=self._exec_group.batch_size,
                    inputs_need_grad=self.inputs_need_grad,
                    distributed=distributed,
                    zero=self._zero,
                )
                if hasattr(kvstore, "attach_mesh"):
                    kvstore.attach_mesh(self._fused.mesh)
                update_on_kvstore = False
                self._update_on_kvstore = False
            except MXNetError as e:
                self.logger.warning(
                    "kvstore=%r: %s; using per-executor update path",
                    kvstore.type, e)
                self._fused = None
            except Exception as e:  # mesh/device construction failed
                self.logger.warning(
                    "kvstore=%r: fused step unavailable (%r); using "
                    "per-executor update path", kvstore.type, e)
                self._fused = None

        if self._fused is not None and getattr(self, "_monitor_installed",
                                               False):
            self._warn_monitor_on_fused()

        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            _initialize_kvstore(
                kvstore=kvstore, param_arrays=self._exec_group.param_arrays,
                arg_params=self._arg_params, param_names=self._param_names,
                update_on_kvstore=update_on_kvstore,
            )
        if update_on_kvstore:
            kvstore.set_optimizer(self._optimizer)
        else:
            self._updater = opt_mod.get_updater(optimizer)

        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if self._fused is not None:
            self._last_fused = False
            if self._params_dirty:
                # eval/predict goes through the per-ctx executors: refresh
                # them (and the host copies) from the fused device carry.
                self._sync_params_from_devices()
        curr_data_shapes = tuple(i.shape for i in self._exec_group.data_shapes)
        if isinstance(data_batch, list):
            new_data_shapes = tuple(b.data[0].shape for b in data_batch)
        else:
            new_data_shapes = tuple(i.shape for i in data_batch.data)
        if curr_data_shapes != new_data_shapes:
            if hasattr(data_batch, "provide_data") and data_batch.provide_data:
                new_dshape = data_batch.provide_data
            else:
                new_dshape = [
                    DataDesc(i.name, shape, i.dtype, i.layout)
                    for i, shape in zip(self._exec_group.data_shapes, new_data_shapes)
                ]
            if hasattr(data_batch, "provide_label") and data_batch.provide_label:
                new_lshape = data_batch.provide_label
            elif hasattr(data_batch, "label") and data_batch.label:
                new_lshape = [
                    DataDesc(i.name, j.shape, i.dtype, i.layout)
                    for i, j in zip(self._exec_group.label_shapes, data_batch.label)
                ]
            else:
                new_lshape = None
            self.reshape(new_dshape, new_lshape)
        self._exec_group.forward(data_batch, is_train)

    def forward_backward(self, data_batch):
        assert self.binded and self.params_initialized
        if self._fused is not None:
            # One compiled step: fwd+bwd+optimizer update, batch sharded
            # over the mesh. update() below becomes a no-op.
            if getattr(self, "_fused_stale", False):
                # an explicit forward/backward/update() round went through
                # the per-executor path meanwhile: refresh the device carry
                self._exec_group.get_params(self._arg_params, self._aux_params)
                self._fused.set_params(self._arg_params, self._aux_params)
                self._fused_stale = False
            self._fused.forward_backward_update(data_batch)
            from .. import chaos

            chaos.tick_step()  # fused step = one worker chaos step (the
            # per-executor paths tick inside model._update_params*)
            self._params_dirty = True
            self._last_fused = True
            return
        self._exec_group.forward_backward(data_batch)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized and self.optimizer_initialized
        self._params_dirty = True
        if self._fused is not None:
            if getattr(self, "_last_fused", False):
                return  # update already applied inside the fused step
            # explicit forward()/backward() round: apply the per-executor
            # update and mark the fused carry stale so the next fused step
            # reloads parameters from the executors.
            self._fused_stale = True
        if self._update_on_kvstore:
            _update_params_on_kvstore(
                self._exec_group.param_arrays, self._exec_group.grad_arrays,
                self._kvstore, self._exec_group.param_names,
            )
        else:
            _update_params(
                self._exec_group.param_arrays, self._exec_group.grad_arrays,
                updater=self._updater, num_device=len(self._context),
                kvstore=self._kvstore, param_names=self._exec_group.param_names,
            )

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        if self._fused is not None and getattr(self, "_last_fused", False):
            return self._fused.get_outputs()
        return self._exec_group.get_outputs(merge_multi_context=merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context=merge_multi_context)

    def update_metric(self, eval_metric, labels):
        if self._fused is not None and getattr(self, "_last_fused", False):
            self._fused.update_metric(eval_metric, labels)
            return
        self._exec_group.update_metric(eval_metric, labels)

    def _sync_params_from_devices(self):
        if self._fused is not None:
            if getattr(self, "_fused_stale", False):
                # per-executor update ran last: executors hold the truth
                self._exec_group.get_params(self._arg_params, self._aux_params)
                self._fused.set_params(self._arg_params, self._aux_params)
                self._fused_stale = False
            else:
                self._fused.copy_params_to(self._arg_params, self._aux_params)
                self._exec_group.set_params(self._arg_params, self._aux_params)
            self._params_dirty = False
            return
        self._exec_group.get_params(self._arg_params, self._aux_params)
        if self._kvstore and self._update_on_kvstore:
            # ONE batched pull (per-shard multi-key frames on the
            # server tier) instead of a round trip per parameter
            names = sorted(self._arg_params)
            if names:
                self._kvstore.pull(names,
                                   [self._arg_params[n] for n in names],
                                   priority=0)
        self._params_dirty = False

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        from ..checkpoint import atomic_write_bytes

        # every branch writes tmp-fsync-rename: a crash mid-save must
        # never leave a torn .states file (ISSUE 3 satellite)
        if self._fused is not None:
            atomic_write_bytes(fname, self._fused.get_states())
            return
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            atomic_write_bytes(fname, self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._fused is not None:
            self._fused.set_states(open(fname, "rb").read())
            return
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            self._updater.set_states(open(fname, "rb").read())

    def install_monitor(self, mon):
        assert self.binded
        self._monitor_installed = True
        if self._fused is not None:
            self._warn_monitor_on_fused()
        self._exec_group.install_monitor(mon)

    def _warn_monitor_on_fused(self):
        # loud, not fatal: the job still trains — but the monitor's
        # callbacks never fire inside the fused program AND its
        # tic/toc host syncs defeat the stall-free loop; the in-graph
        # sentinel is the fused-tier tool (see monitor.py docstring)
        self.logger.warning(
            "Monitor is installed but this Module trains through the "
            "fused SPMD step (kvstore='tpu' tier): per-op monitor "
            "callbacks never run inside the compiled program, and "
            "Monitor's per-batch host syncs would defeat the "
            "stall-free fit loop anyway. Use the in-graph sentinel "
            "(MXNET_TPU_SENTINEL=record|skip|halt) and profiler "
            "healthStats instead.")

    def prepare(self, data_batch, sparse_row_id_fn=None):
        assert self.binded
        if sparse_row_id_fn is not None and self._kvstore is not None:
            row_ids = sparse_row_id_fn(data_batch)
            for name, rid in row_ids.items():
                if name in self._param_names:
                    idx = self._param_names.index(name)
                    self._kvstore.row_sparse_pull(
                        name, out=self._exec_group.param_arrays[idx], row_ids=rid
                    )
