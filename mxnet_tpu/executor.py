"""Graph executor: bind a Symbol, compile to one XLA program, run fwd/bwd.

Reference counterpart: ``src/executor/graph_executor.cc`` (1,866 LoC of
NNVM pass orchestration: Gradient, PlaceDevice, PlanMemory, AttachOpExecs,
memory pooling, cached engine ops, bulking — SURVEY §2.2/§3.1). TPU-native
design: the whole of that machinery is replaced by tracing the graph into
jitted JAX functions — XLA performs memory planning, fusion, scheduling and
(through jax.vjp) the gradient pass. Three compiled artifacts per executor:

- ``fwd_infer``  : inference forward (is_train=False)
- ``fwd_train``  : training forward (batch stats, dropout active)
- ``fwd_bwd``    : fused forward+backward → (outputs, grads, aux updates) —
  the Module training hot path, one XLA module per step (the analogue of
  the reference's bulked op segments, graph_executor.cc:1502).

Model parallelism note (ISSUE 20): ``bind(group2ctx=...)`` below is the
LEGACY per-op device-placement style (ctx_group attributes → explicit
devices, the reference's PlaceDevice pass). The TPU-native path shards
tensors instead: a ``(dp, mp)`` mesh (``parallel/mesh.py:train_mesh``)
with megatron column/row ``PartitionSpec`` rules applied by
``parallel/spmd.py:param_shardings`` — GSPMD then partitions this same
traced program across the mesh. Prefer ``MXNET_MP_SIZE`` over group2ctx
for anything larger than a two-device demo.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as _np

from . import profiler as _profiler
from .base import MXNetError, dtype_name, dtype_np
from .context import Context, current_context
from .ndarray.ndarray import NDArray, array as nd_array, zeros as nd_zeros
from .symbol.symbol import _AUX_PARAMS, Symbol

_RNG_SALT = 0x5EED


def eval_node(node, ins, key, salt, is_train):
    """Evaluate ONE symbol-graph node under the executor's op-invocation
    contract: ``__is_train__`` threading for train/infer-polymorphic ops
    and the per-node RNG fold-in for stochastic ops. Always returns a
    tuple of outputs. Shared by the training/inference closures below
    and the serving tier's constant-fold / inference split
    (``mxnet_tpu/serving/predictor.py``) so both bind paths invoke ops
    identically."""
    attrs = dict(node.attrs)
    if "__is_train__" in node.op.attr_defaults:
        attrs["__is_train__"] = is_train
    if node.op.needs_rng:
        sub = jax.random.fold_in(key, salt + _RNG_SALT)
        out = node.op.fn(sub, *ins, **attrs)
    else:
        out = node.op.fn(*ins, **attrs)
    return out if isinstance(out, tuple) else (out,)


def _graph_closure(symbol: Symbol, is_train: bool, placement=None,
                   remat_names=None):
    """Build a pure function evaluating the symbol graph.

    Returns fn(values: dict[str, jax.Array], key) -> (outputs, aux_updates)
    where aux_updates maps aux var name -> new value (BatchNorm moving
    stats etc., applied by the caller after forward).

    ``placement`` maps a ``ctx_group`` name to a concrete jax.Device: the
    TPU-native PlaceDevice pass (ref: graph_executor.cc:411). Each node
    stamped with that group is pinned there via ``jax.device_put`` inside
    the traced program; XLA inserts the cross-device transfers that the
    reference realized as explicit ``_CrossDeviceCopy`` nodes, in both the
    forward and (through the transpose of device_put) the gradient graph.

    ``remat_names`` (ISSUE 19) is the selective-remat save set: outputs
    of nodes named here are tagged with ``checkpoint_name`` so a
    ``jax.checkpoint`` under ``save_only_these_names`` keeps exactly
    them and recomputes everything else in backward (the per-SITE
    policy ``ir/remat.py`` plans). None/empty builds the tag-free
    closure — bit-identical to the pre-ISSUE-19 behavior.
    """
    nodes = symbol._topo()
    entries = symbol._entries
    node_ids = {id(n): i for i, n in enumerate(nodes)}
    placement = placement or {}
    remat_names = frozenset(remat_names or ())

    def _place(node, out):
        dev = placement.get(node.attr_dict.get("ctx_group"))
        if dev is None:
            return out
        return tuple(jax.device_put(o, dev) for o in out)

    def fn(values, key):
        results = {}  # node id -> tuple of outputs
        aux_updates = {}
        for i, node in enumerate(nodes):
            if node.is_variable():
                if node.name not in values:
                    raise MXNetError("unbound variable %r" % node.name)
                results[i] = _place(node, (values[node.name],))
                continue
            ins = [results[node_ids[id(inp)]][idx] for inp, idx in node.inputs]
            out = _place(node, eval_node(node, ins, key, i, is_train))
            if node.name in remat_names:
                from jax.ad_checkpoint import checkpoint_name

                out = tuple(checkpoint_name(o, node.name) for o in out)
            results[i] = out
            # generic aux-state contract: op declares which outputs
            # replace which aux inputs each training step (fused blocks)
            if is_train and node.op.aux_state_outputs and node._arity:
                for pname, (inode, _) in zip(node._arity, node.inputs):
                    idx = node.op.aux_state_outputs.get(pname)
                    if idx is not None and inode.is_variable():
                        aux_updates[inode.name] = out[idx]
            # aux-state update semantics (BatchNorm moving stats)
            elif is_train and node.op.name in _AUX_PARAMS and node._arity:
                momentum = node.attrs.get("momentum", 0.9)
                for pname, (inode, _) in zip(node._arity, node.inputs):
                    if not inode.is_variable():
                        continue
                    if pname == "moving_mean":
                        aux_updates[inode.name] = (
                            momentum * values[inode.name] + (1 - momentum) * out[1]
                        )
                    elif pname == "moving_var":
                        aux_updates[inode.name] = (
                            momentum * values[inode.name] + (1 - momentum) * out[2]
                        )
        outs = [results[node_ids[id(n)]][idx] for n, idx in entries]
        return outs, aux_updates

    return fn


# ---------------------------------------------------------------------------
# shape/type inference (ref: src/executor/infer_graph_attr_pass.cc — here a
# single jax.eval_shape abstract evaluation replaces the fixpoint pass)
# ---------------------------------------------------------------------------
def infer_graph_shapes(symbol, kwargs, partial=False, type_dict=None):
    arg_names = symbol.list_arguments()
    aux_names = symbol.list_auxiliary_states()
    known = {}
    for k, v in kwargs.items():
        if v is not None:
            known[k] = tuple(v)
    shapes, dtypes = _solve_shapes(symbol, known, type_dict or {}, partial=partial)
    if shapes is None:
        return None, None, None
    arg_shapes = [shapes.get(n) for n in arg_names]
    aux_shapes = [shapes.get(n) for n in aux_names]
    out_shapes = shapes["__outputs__"]
    return arg_shapes, out_shapes, aux_shapes


def infer_graph_types(symbol, kwargs):
    """Propagate dtypes through the graph by abstract evaluation.

    Needs at least placeholder shapes: uses per-variable __shape__ attrs or
    rank-agnostic (1,1,1,1) fallbacks, since XLA dtype rules are shape-
    independent for the ops we register."""
    arg_names = symbol.list_arguments()
    aux_names = symbol.list_auxiliary_states()
    dtypes = {k: v for k, v in kwargs.items() if v is not None}
    # dummy rank-1 shapes let elementwise/cast chains propagate dtype even
    # when real shapes are unknown; shape-constrained ops fall back to f32
    dummy = {n: (1,) for n in arg_names + aux_names}
    try:
        shapes, out_dtypes = _solve_shapes(symbol, dummy, dtypes, partial=True)
        out_types = out_dtypes if out_dtypes else [None] * len(symbol._entries)
    except Exception:
        out_types = [None] * len(symbol._entries)
    arg_types = [dtype_np(dtypes.get(n, _np.float32)) for n in arg_names]
    aux_types = [dtype_np(dtypes.get(n, _np.float32)) for n in aux_names]
    out_types = [t if t is not None else _np.float32 for t in out_types]
    return arg_types, out_types, aux_types


def _solve_shapes(symbol, known_shapes, type_dict, partial=False):
    """Infer all variable shapes by constraint propagation.

    Strategy (TPU-first; replaces NNVM's per-op FInferShape): per-op python
    shape rules for the parameterized layers (Convolution/FC/RNN/…) whose
    weights can't be deduced by abstract evaluation alone, then a final
    jax.eval_shape over the whole graph to fill outputs and validate.
    """
    nodes = symbol._topo()
    node_ids = {id(n): i for i, n in enumerate(nodes)}
    shapes = dict(known_shapes)  # varname -> shape
    dtypes = {k: dtype_np(v) for k, v in type_dict.items()}

    node_out = {}  # node idx -> list of (shape, dtype)
    node_errors = {}  # node name -> last abstract-eval error (diagnostics)

    def get_in_structs(node):
        ins = []
        for inp, idx in node.inputs:
            if inp.is_variable():
                s = shapes.get(inp.name)
                ins.append(None if s is None else (s, dtypes.get(inp.name, _np.float32)))
            else:
                outs = node_out.get(node_ids[id(inp)])
                ins.append(outs[idx] if outs else None)
        return ins

    progress = True
    rounds = 0
    while progress and rounds < len(nodes) + 2:
        progress = False
        rounds += 1
        for i, node in enumerate(nodes):
            if node.is_variable():
                if node.name not in shapes and "__shape__" in node.attr_dict:
                    sh = node.attr_dict["__shape__"]
                    if isinstance(sh, str):
                        from .ops.registry import _parse_tuple

                        sh = _parse_tuple(sh)
                    shapes[node.name] = tuple(sh)
                    progress = True
                if node.name not in dtypes and "__dtype__" in node.attr_dict:
                    dtypes[node.name] = dtype_np(node.attr_dict["__dtype__"])
                continue
            if i in node_out:
                continue
            in_structs = get_in_structs(node)
            hints = _param_shape_hints(node, [s[0] if s else None for s in in_structs])
            if hints:
                for pname, shape in hints.items():
                    for an, (inode, _) in zip(node._arity or (), node.inputs):
                        if an != pname:
                            continue
                        # the int8 serving path routes weights through
                        # an in-graph _quantize_rows_int8 node (shape-
                        # preserving on output 0): the hint lands on
                        # the variable BEHIND it
                        if (not inode.is_variable()
                                and inode.op.name == "_quantize_rows_int8"
                                and inode.inputs
                                and inode.inputs[0][0].is_variable()):
                            inode = inode.inputs[0][0]
                        if inode.is_variable() and inode.name not in shapes:
                            shapes[inode.name] = shape
                            in_structs = get_in_structs(node)
                            progress = True
            if any(s is None for s in in_structs):
                continue
            # abstract eval this node: shapes AND dtypes in one pass
            attrs = dict(node.attrs)
            if "__is_train__" in node.op.attr_defaults:
                attrs["__is_train__"] = False
            try:
                structs = [jax.ShapeDtypeStruct(s, d) for s, d in in_structs]
                if node.op.needs_rng:
                    kstruct = jax.ShapeDtypeStruct((2,), _np.uint32)
                    out = jax.eval_shape(lambda k, *a: node.op.fn(k, *a, **attrs), kstruct, *structs)
                else:
                    out = jax.eval_shape(lambda *a: node.op.fn(*a, **attrs), *structs)
                out = out if isinstance(out, tuple) else (out,)
                node_out[i] = [(tuple(o.shape), o.dtype) for o in out]
                node_errors.pop(node.name, None)
                progress = True
            except Exception as e:
                # unresolved nodes are normal mid-fixpoint; keep the last
                # error per node so a *final* failure names its cause
                # (set MXNET_INFER_DEBUG=1 for full tracebacks)
                lines = str(e).strip().splitlines()
                node_errors[node.name] = "%s(%s): %s" % (
                    node.op.name, node.name,
                    lines[-1][:200] if lines else type(e).__name__)
                if os.environ.get("MXNET_INFER_DEBUG"):
                    import sys
                    import traceback

                    print("[infer_shape] node %r (%s) failed:\n%s"
                          % (node.name, node.op.name,
                             traceback.format_exc()), file=sys.stderr)
                continue

    out_shapes = []
    out_dtypes = []
    ok = True
    for n, idx in symbol._entries:
        if n.is_variable():
            out_shapes.append(shapes.get(n.name))
            out_dtypes.append(dtypes.get(n.name))
        else:
            outs = node_out.get(node_ids[id(n)])
            out_shapes.append(outs[idx][0] if outs else None)
            out_dtypes.append(outs[idx][1] if outs else None)
        if out_shapes[-1] is None:
            ok = False
    if not ok and not partial:
        missing = [v.name for v in nodes if v.is_variable() and v.name not in shapes]
        detail = "; ".join(list(node_errors.values())[:3])
        raise MXNetError(
            "infer_shape failed; unresolved variables: %s%s"
            % (missing, (" — node errors: " + detail) if detail else ""))
    shapes["__outputs__"] = out_shapes
    return shapes, out_dtypes


def _param_shape_hints(node, in_shapes):
    """Infer parameter shapes from data shape for parameterized layers
    (the NNVM FInferShape backward-direction rules the compiler can't do)."""
    op = node.op.name
    attrs = node.attrs
    data = in_shapes[0] if in_shapes else None
    if data is None:
        return {}
    hints = {}
    if op in ("Convolution", "Convolution_v1", "_ConvResidualAdd",
              "_int8_convolution"):
        # the IR rewrites (_ConvResidualAdd, the int8 serving conv)
        # keep Convolution's weight contract exactly
        kernel = tuple(int(k) for k in attrs.get("kernel", ()))
        nf = int(attrs.get("num_filter", 1))
        ng = int(attrs.get("num_group", 1))
        hints["weight"] = (nf, data[1] // ng) + kernel
        if not attrs.get("no_bias"):
            hints["bias"] = (nf,)
        if op == "_int8_convolution":
            hints["wscale"] = (nf,)
    elif op == "FusedBottleneckUnit":
        # data is NHWC; weights keep the unfused OIHW checkpoint shapes
        nf = int(attrs.get("num_filter", 1))
        c = nf // 4
        ci = data[3]
        hints["conv1_weight"] = (c, ci, 1, 1)
        hints["conv2_weight"] = (c, c, 3, 3)
        hints["conv3_weight"] = (nf, c, 1, 1)
        hints["sc_weight"] = (nf, ci, 1, 1)
        for i, ch in (("1", ci), ("2", c), ("3", c)):
            hints["bn%s_gamma" % i] = (ch,)
            hints["bn%s_beta" % i] = (ch,)
            hints["bn%s_moving_mean" % i] = (ch,)
            hints["bn%s_moving_var" % i] = (ch,)
    elif op == "Deconvolution":
        kernel = tuple(int(k) for k in attrs.get("kernel", ()))
        nf = int(attrs.get("num_filter", 1))
        ng = int(attrs.get("num_group", 1))
        hints["weight"] = (data[1], nf // ng) + kernel
        if not attrs.get("no_bias", True):
            hints["bias"] = (nf,)
    elif op in ("FullyConnected", "_int8_fully_connected"):
        nh = int(attrs.get("num_hidden", 1))
        flatten = attrs.get("flatten", True)
        in_dim = 1
        if flatten:
            for d in data[1:]:
                in_dim *= d
        else:
            in_dim = data[-1]
        hints["weight"] = (nh, in_dim)
        if not attrs.get("no_bias"):
            hints["bias"] = (nh,)
        if op == "_int8_fully_connected":
            hints["wscale"] = (nh,)
    elif op in ("BatchNorm", "BatchNorm_v1", "batch_norm"):
        ax = int(attrs.get("axis", 1)) % len(data)
        c = data[ax]
        hints.update({"gamma": (c,), "beta": (c,), "moving_mean": (c,), "moving_var": (c,)})
    elif op == "LayerNorm":
        ax = int(attrs.get("axis", -1)) % len(data)
        c = data[ax]
        hints.update({"gamma": (c,), "beta": (c,)})
    elif op == "InstanceNorm":
        hints.update({"gamma": (data[1],), "beta": (data[1],)})
    elif op == "Embedding":
        hints["weight"] = (int(attrs.get("input_dim", 0)), int(attrs.get("output_dim", 0)))
    elif op == "LeakyReLU" and attrs.get("act_type") == "prelu":
        hints["gamma"] = (data[1] if len(data) > 1 else 1,)
    elif op in ("SoftmaxOutput", "SVMOutput"):
        # label shape deduced from data (ref: SoftmaxOutput FInferShape) so
        # inference-only binds need no label_shapes
        if op == "SoftmaxOutput" and attrs.get("multi_output"):
            hints["label"] = (data[0],) + tuple(data[2:])
        else:
            hints["label"] = (data[0],)
    elif op in ("LinearRegressionOutput", "MAERegressionOutput",
                "LogisticRegressionOutput"):
        hints["label"] = tuple(data)
    elif op == "RNN":
        H = int(attrs.get("state_size", 0))
        L = int(attrs.get("num_layers", 1))
        D = 2 if attrs.get("bidirectional") else 1
        mode = attrs.get("mode", "lstm")
        ngates = {"lstm": 4, "gru": 3, "rnn_relu": 1, "rnn_tanh": 1}[mode]
        I = data[2]
        size = 0
        for layer in range(L):
            for d in range(D):
                in_size = I if layer == 0 else H * D
                size += ngates * H * in_size + ngates * H * H
        size += L * D * 2 * ngates * H
        hints["parameters"] = (size,)
        hints["state"] = (L * D, data[1], H)
        if mode == "lstm":
            hints["state_cell"] = (L * D, data[1], H)
    return hints


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------
class Executor:
    """A bound computation (ref: include/mxnet/executor.h Executor)."""

    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, group2ctx=None):
        self._symbol = symbol
        self._ctx = Context(ctx) if not isinstance(ctx, Context) else ctx
        self._monitor_callback = None
        self._group2ctx = group2ctx

        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()

        # normalize args
        if isinstance(args, dict):
            self.arg_dict = dict(args)
            missing = [n for n in arg_names if n not in self.arg_dict]
            if missing:
                raise MXNetError("bind: missing arguments %s" % missing)
        else:
            if len(args) != len(arg_names):
                raise MXNetError(
                    "bind: expected %d args, got %d" % (len(arg_names), len(args))
                )
            self.arg_dict = dict(zip(arg_names, args))
        self.arg_arrays = [self.arg_dict[n] for n in arg_names]

        if aux_states is None:
            aux_states = {}
        if isinstance(aux_states, dict):
            self.aux_dict = dict(aux_states)
        else:
            self.aux_dict = dict(zip(aux_names, aux_states))
        for n in aux_names:
            if n not in self.aux_dict:
                raise MXNetError("bind: missing auxiliary state %r" % n)
        self.aux_arrays = [self.aux_dict[n] for n in aux_names]

        # grad requirements
        if isinstance(grad_req, str):
            self.grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self.grad_req = dict(zip(arg_names, grad_req))
        else:
            self.grad_req = {n: grad_req.get(n, "null") for n in arg_names}

        if args_grad is None:
            self.grad_dict = {}
        elif isinstance(args_grad, dict):
            self.grad_dict = dict(args_grad)
        else:
            self.grad_dict = dict(zip(arg_names, args_grad))
        self.grad_arrays = [self.grad_dict.get(n) for n in arg_names]

        self._arg_names = arg_names
        self._aux_names = aux_names
        self._grad_names = [
            n for n in arg_names if self.grad_req.get(n, "null") != "null" and self.grad_dict.get(n) is not None
        ]

        self.outputs = []
        self._out_arrays = None
        self._compiled = {}
        self._rng_counter = 0
        self._last_fwd_train = False

    # -- compilation ---------------------------------------------------------
    def _placement(self):
        """ctx_group name → jax.Device map from the bind-time group2ctx
        (ref: symbol.py:1255 group2ctx → PlaceDevice)."""
        if not self._group2ctx:
            return None
        return {g: (Context(c) if not isinstance(c, Context) else c).jax_device()
                for g, c in self._group2ctx.items()}

    def _get_compiled(self, kind):
        fn = self._compiled.get(kind)
        if fn is not None:
            return fn
        placement = self._placement()
        if kind in ("fwd_infer", "fwd_train"):
            is_train = kind == "fwd_train"
            graph = _graph_closure(self._symbol, is_train, placement)

            def run(values, key):
                outs, aux_updates = graph(values, key)
                return outs, aux_updates

            fn = jax.jit(run)
        elif kind == "fwd_bwd":
            graph = _graph_closure(self._symbol, True, placement)
            grad_names = tuple(self._grad_names)
            # MXNET_BACKWARD_DO_MIRROR: recompute-in-backward (sublinear
            # memory; ref graph_executor.cc:282-305 mirror predicate →
            # jax.checkpoint on the whole bound program)
            from . import config as _cfg

            if _cfg.get_bool("MXNET_BACKWARD_DO_MIRROR"):
                graph = jax.checkpoint(graph)

            def run(values, key, head_grads):
                def of_grads(gvals):
                    all_vals = dict(values)
                    all_vals.update(gvals)
                    outs, aux_updates = graph(all_vals, key)
                    return outs, aux_updates

                gvals = {n: values[n] for n in grad_names}
                outs, vjp_fn = jax.vjp(lambda gv: of_grads(gv)[0], gvals)
                # aux updates from a plain re-eval (free under jit — XLA CSE)
                _, aux_updates = of_grads(gvals)
                cts = [
                    hg if hg is not None else jnp.ones_like(o)
                    for hg, o in zip(head_grads, outs)
                ]
                (grads,) = vjp_fn(cts)
                return outs, grads, aux_updates

            fn = jax.jit(run)
        else:
            raise MXNetError(kind)
        self._compiled[kind] = fn
        return fn

    def _values(self, include_aux=True):
        vals = {n: self.arg_dict[n]._data() for n in self._arg_names}
        if include_aux:
            for n in self._aux_names:
                vals[n] = self.aux_dict[n]._data()
        return vals

    def _next_key(self):
        from . import random as _rnd

        return _rnd.next_key(self._ctx)

    # -- execution -----------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k in self.arg_dict:
                tgt = self.arg_dict[k]
                src = v if isinstance(v, NDArray) else nd_array(v, ctx=self._ctx)
                tgt._rebind(src._data().astype(tgt._data().dtype) if src._data().dtype != tgt._data().dtype else src._data())
        fn = self._get_compiled("fwd_train" if is_train else "fwd_infer")
        key = self._next_key()
        self._last_key = key  # backward() must replay the same PRNG draws
        # ref: executor RunOps stamps each push (graph_executor.cc:1461);
        # one XLA program = one event here
        with _profiler.maybe_scope(self._symbol.name or "executor", "forward"):
            outs, aux_updates = fn(self._values(), key)
        self._last_fwd_train = is_train
        self._set_outputs(outs)
        self._aux_applied = False
        if is_train:
            self._apply_aux(aux_updates)
            self._aux_applied = True
        if self._monitor_callback is not None:
            for name, val in zip(self._symbol.list_outputs(), self.outputs):
                self._monitor_callback(name, val)
        return self.outputs

    def _set_outputs(self, outs):
        if self._out_arrays is None:
            self._out_arrays = [NDArray(o, ctx=self._ctx) for o in outs]
        else:
            for arr, o in zip(self._out_arrays, outs):
                arr._rebind(o)
        self.outputs = self._out_arrays

    def _apply_aux(self, aux_updates):
        for name, val in aux_updates.items():
            self.aux_dict[name]._rebind(val)

    def backward(self, out_grads=None, is_train=True):
        """Backward pass. Runs the fused fwd+bwd XLA program (forward results
        are recomputed inside the compiled module — XLA CSE makes the fused
        program the fast path; see class docstring)."""
        heads = self._normalize_head_grads(out_grads)
        fn = self._get_compiled("fwd_bwd")
        with _profiler.maybe_scope(self._symbol.name or "executor", "backward"):
            outs, grads, aux_updates = fn(self._values(), self._reuse_key(), heads)
        self._set_outputs(outs)
        if not getattr(self, "_aux_applied", False):
            self._apply_aux(aux_updates)
        self._aux_applied = False
        for n in self._grad_names:
            buf = self.grad_dict.get(n)
            if buf is None:
                continue
            g = grads[n]
            if self.grad_req.get(n) == "add":
                buf._rebind(buf._data() + g)
            else:
                buf._rebind(g)
        return grads

    def forward_backward(self, out_grads=None, **kwargs):
        """Fused training step — forward + backward in one compiled call."""
        for k, v in kwargs.items():
            if k in self.arg_dict:
                tgt = self.arg_dict[k]
                src = v if isinstance(v, NDArray) else nd_array(v, ctx=self._ctx)
                tgt._rebind(src._data())
        heads = self._normalize_head_grads(out_grads)
        fn = self._get_compiled("fwd_bwd")
        key = self._next_key()
        self._last_key = key
        with _profiler.maybe_scope(self._symbol.name or "executor",
                                   "forward_backward"):
            outs, grads, aux_updates = fn(self._values(), key, heads)
        self._set_outputs(outs)
        self._apply_aux(aux_updates)
        self._aux_applied = False
        for n in self._grad_names:
            buf = self.grad_dict.get(n)
            if buf is None:
                continue
            if self.grad_req.get(n) == "add":
                buf._rebind(buf._data() + grads[n])
            else:
                buf._rebind(grads[n])
        return self.outputs

    def _reuse_key(self):
        key = getattr(self, "_last_key", None)
        if key is None:
            key = self._next_key()
        return key

    def _normalize_head_grads(self, out_grads):
        n_out = len(self._symbol._entries)
        if out_grads is None:
            return [None] * n_out
        if isinstance(out_grads, NDArray):
            out_grads = [out_grads]
        return [g._data() if isinstance(g, NDArray) else g for g in out_grads] + [None] * (
            n_out - len(out_grads)
        )

    # -- parameter management ------------------------------------------------
    def copy_params_from(self, arg_params, aux_params=None, allow_extra_params=False):
        for name, arr in (arg_params or {}).items():
            if name in self.arg_dict:
                arr.copyto(self.arg_dict[name])
            elif not allow_extra_params:
                raise MXNetError("unknown argument %r" % name)
        for name, arr in (aux_params or {}).items():
            if name in self.aux_dict:
                arr.copyto(self.aux_dict[name])
            elif not allow_extra_params:
                raise MXNetError("unknown aux state %r" % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = {}
        for name, shape in zip(self._arg_names, arg_shapes):
            old = self.arg_dict[name]
            if tuple(old.shape) == tuple(shape):
                new_args[name] = old
            else:
                new_args[name] = nd_zeros(shape, ctx=self._ctx, dtype=old.dtype)
        new_grads = {}
        for name in self._arg_names:
            g = self.grad_dict.get(name)
            if g is None:
                continue
            shape = new_args[name].shape
            new_grads[name] = g if tuple(g.shape) == tuple(shape) else nd_zeros(shape, ctx=self._ctx, dtype=g.dtype)
        new_aux = {}
        for name, shape in zip(self._aux_names, aux_shapes):
            old = self.aux_dict[name]
            new_aux[name] = old if tuple(old.shape) == tuple(shape) else nd_zeros(shape, ctx=self._ctx, dtype=old.dtype)
        return Executor(self._symbol, self._ctx, new_args, new_grads, self.grad_req, new_aux)

    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor_callback = callback

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    def debug_str(self):
        return self._symbol.debug_str()


def simple_bind(symbol, ctx, grad_req="write", type_dict=None, shared_exec=None,
                group2ctx=None, **kwargs):
    """Allocate arg/grad/aux arrays from inferred shapes and bind
    (ref: symbol.py:1255-1512 simple_bind + memory sharing via shared_exec —
    memory pooling is XLA's job here, so shared_exec only shares buffers)."""
    arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**{
        k: v for k, v in kwargs.items() if isinstance(v, (list, tuple))
    })
    if arg_shapes is None:
        raise MXNetError("simple_bind: shape inference failed")
    arg_names = symbol.list_arguments()
    aux_names = symbol.list_auxiliary_states()
    type_dict = type_dict or {}
    args = {}
    for name, shape in zip(arg_names, arg_shapes):
        dtype = type_dict.get(name, _np.float32)
        if shared_exec is not None and name in shared_exec.arg_dict and tuple(shared_exec.arg_dict[name].shape) == tuple(shape):
            args[name] = shared_exec.arg_dict[name]
        else:
            args[name] = nd_zeros(shape, ctx=ctx, dtype=dtype)
    grad_req_dict = (
        {n: grad_req for n in arg_names} if isinstance(grad_req, str) else dict(grad_req)
    )
    grads = {}
    for name in arg_names:
        if grad_req_dict.get(name, "null") != "null":
            if shared_exec is not None and name in shared_exec.grad_dict and shared_exec.grad_dict[name] is not None and tuple(shared_exec.grad_dict[name].shape) == tuple(args[name].shape):
                grads[name] = shared_exec.grad_dict[name]
            else:
                grads[name] = nd_zeros(args[name].shape, ctx=ctx, dtype=type_dict.get(name, _np.float32))
    aux = {}
    for name, shape in zip(aux_names, aux_shapes):
        if shared_exec is not None and name in shared_exec.aux_dict and tuple(shared_exec.aux_dict[name].shape) == tuple(shape):
            aux[name] = shared_exec.aux_dict[name]
        else:
            aux[name] = nd_zeros(shape, ctx=ctx, dtype=type_dict.get(name, _np.float32))
    return Executor(symbol, ctx, args, grads, grad_req_dict, aux,
                    group2ctx=group2ctx)
