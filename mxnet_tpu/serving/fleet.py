"""Fault-tolerant serving fleet (ISSUE 11): tracker-discovered
replicas, a retrying router with health-driven draining, and zero-drop
rolling checkpoint swap.

Reference counterpart: the reference serves production traffic as N
``c_predict_api`` processes behind the ps-lite scheduler's discovery
plane (PAPER.md layer 8) — never one process. This module closes that
gap over the pieces previous PRs banked:

- **Discovery = the tracker (PR 2/3).** Each :class:`ReplicaServer`
  wraps a :class:`~mxnet_tpu.serving.ModelServer` behind a TCP endpoint
  speaking the pickle-5 out-of-band wire framing (PR 4,
  ``tracker._send_msg``) and registers with the scheduler under the
  slot-free ``replica`` role: model names, bucket ladder, and a load
  gauge (queue depth + ``profiler.serving_stats`` p50/p99) that it
  re-publishes every ``MXNET_FLEET_VIEW_INTERVAL`` seconds and on every
  hot-swap. Heartbeats and dead-node detection are the tracker's
  existing machinery — a SIGKILLed replica drops off the view within a
  heartbeat timeout with no new code.
- **Routing = least-loaded + bounded retry.** :class:`FleetRouter`
  coalesces the tracker view and sends each request to the lowest
  (router-local in-flight + published queue depth) live ``serving``
  replica. Failures are classified, not guessed at: a request that was
  *never sent* (connect refused, send-phase drop) retries on a
  different replica regardless of idempotency; a request that was sent
  but got no reply fails distinctly as :class:`ReplicaConnectionLost`
  and retries only when ``idempotent=True`` (the inference default —
  the forward may have executed, but re-executing it is harmless);
  typed admission rejections (:class:`~.broker.ReplicaDraining`,
  :class:`~.broker.ServerClosed` — the request never executed) always
  retry elsewhere, while genuine request failures surface immediately.
  Retries are bounded by ``MXNET_FLEET_RETRIES`` with exponential
  backoff (``MXNET_FLEET_BACKOFF``) under one end-to-end deadline
  budget (``MXNET_FLEET_TIMEOUT``) that is also forwarded to the
  replica as its deadline-at-dequeue shed bound (PR 9) — under
  fleet-wide overload the router raises a typed
  :class:`FleetOverloaded` instead of queueing unboundedly.
- **Draining + rolling swap.** The ``drain`` RPC moves a replica to
  ``draining``: it admits nothing (typed rejection), finishes queued +
  in-flight work, and optionally deregisters. :meth:`FleetRouter.
  fleet_swap` rolls a checkpoint across the fleet one replica at a
  time — drain, quiesced :meth:`ModelServer.swap_from_checkpoint`,
  resume + re-publish — while the other replicas absorb the drained
  one's retried traffic: zero dropped requests.
- **Determinism = chaos.py.** ``replica:R:crash@req=N`` /
  ``replica:R:stall@req=N`` / ``router:drop@...`` rules drive every
  reaction path above at exact, reproducible points.

Entrypoints (``tools/launch.py --serve`` supervises the replica one,
exit-75 free respawn included)::

    python -m mxnet_tpu.serving.fleet replica --prefix ckpt --epoch 0 \\
        --data-shape data:1,128
    python -m mxnet_tpu.serving.fleet router status|drain|swap|stop ...
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import struct
import sys
import threading
import time

import numpy as np

from .. import chaos, config, profiler
from ..tracker import (
    TrackerClient,
    TrackerError,
    _recv_msg,
    _send_msg,
    connect_with_backoff,
)
from .broker import (
    DeadlineExceeded,
    ModelServer,
    ReplicaDraining,
    ServerClosed,
    ServerOverloaded,
)
from .predictor import ServingError
from .qos import QosPolicy, TenantQuotaExceeded

#: mirrors health.EXIT_PREEMPTED / launch.py: a SIGTERMed replica exits
#: with this status and the supervisor respawns it for free
EXIT_PREEMPTED = 75

_TRANSPORT_ERRORS = (OSError, ConnectionError, EOFError, struct.error)


class FleetError(ServingError):
    """Fleet-layer failure (no discovery plane, exhausted retries on a
    non-overload error, malformed admin op)."""


class FleetOverloaded(FleetError):
    """The request could not be served inside its deadline/retry
    budget because the FLEET is saturated (every attempt was shed,
    backpressured, or found no admitting replica). The router raises
    this typed error instead of queueing unboundedly — callers decide
    whether to back off or degrade."""


class NoLiveReplica(FleetError):
    """The view holds no live ``serving`` replica for the model —
    a discovery gap, not an overload."""


class ReplicaConnectionLost(FleetError):
    """The request WAS sent but the connection died before a reply:
    the forward may or may not have executed. Distinct from never-sent
    failures (always retried) — the router retries this one only for
    ``idempotent=True`` requests."""


class FleetRemoteError(FleetError):
    """A replica saw the request and failed it for a non-retryable
    reason (bad input, model error). Carries the remote ``kind``."""

    def __init__(self, kind, msg):
        super().__init__(msg)
        self.kind = kind


# ---------------------------------------------------------------------------
# knobs (ISSUE 11 satellite: strict accessors, loud validation)
# ---------------------------------------------------------------------------
def _knob_retries():
    return config.get_nonneg_int("MXNET_FLEET_RETRIES")


def _knob_timeout():
    return config.get_positive_float("MXNET_FLEET_TIMEOUT")


def _knob_backoff():
    return config.get_nonneg_float("MXNET_FLEET_BACKOFF")


def _knob_view_interval():
    return config.get_positive_float("MXNET_FLEET_VIEW_INTERVAL")


def _knob_connect_deadline():
    return config.get_positive_float("MXNET_FLEET_CONNECT_DEADLINE")


def _knob_drain_timeout():
    return config.get_positive_float("MXNET_SERVE_DRAIN_TIMEOUT")


# ---------------------------------------------------------------------------
# wire helpers — arrays ride the PR-4 zero-copy framing via the ONE
# proven (dtype, shape, buffer) encoding (kvstore_server)
# ---------------------------------------------------------------------------
def _np_to_wire(a):
    from ..kvstore_server import _arr_to_wire

    return _arr_to_wire(np.asarray(a), zero_copy=True)


def _np_from_wire(w):
    from ..kvstore_server import _arr_from_wire

    return _arr_from_wire(w)


def _error_kind(exc):
    """Replica-side exception -> wire error kind. The kind is the
    router's retry contract: draining/closed never executed (retry
    anywhere), deadline/overloaded are load shedding (retry elsewhere
    or surface FleetOverloaded), bad_request/error are genuine
    failures (never retried)."""
    if isinstance(exc, ReplicaDraining):
        return "draining"
    if isinstance(exc, ServerClosed):
        return "closed"
    if isinstance(exc, DeadlineExceeded):
        return "deadline"
    if isinstance(exc, ServerOverloaded):
        return "overloaded"
    if isinstance(exc, TenantQuotaExceeded):
        # terminal, never retried: the quota is the tenant's contract
        # fleet-wide, not this replica's state
        return "quota"
    if isinstance(exc, ServingError):
        return "bad_request"
    return "error"


_KIND_TO_ERROR = {
    "draining": ReplicaDraining,
    "closed": ServerClosed,
    "deadline": DeadlineExceeded,
    "overloaded": ServerOverloaded,
    "quota": TenantQuotaExceeded,
    "bad_request": ServingError,
}


# ---------------------------------------------------------------------------
# replica
# ---------------------------------------------------------------------------
class ReplicaServer:
    """One serving replica: a TCP front end over a
    :class:`ModelServer`, registered with the tracker under the
    slot-free ``replica`` role.

    State machine: ``serving`` → (drain RPC / ``fleet_swap``) →
    ``draining`` (admits nothing, finishes queued + in-flight) →
    ``drained`` → (resume RPC) → ``serving``; ``stop`` from any state
    shuts the endpoint down. The state plus the load gauge is published
    to the tracker every ``publish_interval`` seconds and on every
    transition, so routers route around a draining replica before ever
    hitting its typed rejection."""

    def __init__(self, server, tracker_uri=None, host="127.0.0.1", port=0,
                 advertise_host=None, rank=None, restart=0,
                 publish_interval=None, drain_timeout=None, qos=None,
                 group=None, group_size=1, group_rank=0):
        if not isinstance(server, ModelServer):
            raise FleetError("ReplicaServer wraps a ModelServer, got %r"
                             % type(server).__name__)
        self._server = server
        # sharded replica group (ISSUE 20): ``group`` names the
        # mesh-sharing member set; the router routes ONLY to the
        # group's leader (group_rank 0) and only while all group_size
        # members publish alive + serving — one member dying drains
        # the whole group.
        if group is not None:
            group_size = int(group_size)
            group_rank = int(group_rank)
            if group_size < 1:
                raise FleetError("ReplicaServer: group_size must be "
                                 ">= 1, got %d" % group_size)
            if not 0 <= group_rank < group_size:
                raise FleetError(
                    "ReplicaServer: group_rank %d outside the group "
                    "of %d member(s)" % (group_rank, group_size))
        self._group = group
        self._group_size = int(group_size)
        self._group_rank = int(group_rank)
        # QoS boundary (ISSUE 18): quotas enforced here too, so a
        # deployment with several routers (or none) still caps tenants.
        # None with an empty MXNET_QOS_TENANTS — zero per-request cost.
        self._qos = QosPolicy.from_env() if qos is None else qos
        self._publish_interval = _knob_view_interval() \
            if publish_interval is None else float(publish_interval)
        self._drain_timeout = _knob_drain_timeout() \
            if drain_timeout is None else float(drain_timeout)
        self._cv = threading.Condition()
        self._state = "serving"
        self._inflight = 0
        self._admitted = 0
        self._swap_gen = 0
        self._stop = threading.Event()
        self._conns = set()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        bound_host, bound_port = self._sock.getsockname()[:2]
        self.addr = "%s:%d" % (advertise_host or bound_host or "127.0.0.1",
                               bound_port)
        self.rank = rank
        self._client = None
        self._publisher = None
        if tracker_uri:
            self._client = TrackerClient(
                tracker_uri, "replica", addr=self.addr, rank=rank,
                restart_count=restart, info=self._info())
            self.rank = self._client.rank
            self._publisher = threading.Thread(
                target=self._publish_loop, daemon=True,
                name="replica-publish")
            self._publisher.start()

    # -- published view -------------------------------------------------------
    def _info(self):
        """The load gauge the router routes on: state, models, bucket
        ladder, queued + in-flight depth, and the serving-tier
        p50/p99."""
        with self._cv:
            state, inflight = self._state, self._inflight
            swap_gen, admitted = self._swap_gen, self._admitted
        stats = profiler.serving_stats()
        p50 = max((s.get("p50_ms") or 0.0 for s in stats.values()),
                  default=0.0)
        p99 = max((s.get("p99_ms") or 0.0 for s in stats.values()),
                  default=0.0)
        gen = profiler.generate_stats()
        out = {"state": state, "models": self._server.models(),
               "ladder": list(self._server._ladder),
               "queued": self._server.pending(), "inflight": inflight,
               "admitted": admitted, "p50_ms": p50, "p99_ms": p99,
               "gen_occupancy": gen.get("slot_occupancy", 0.0),
               "swap_gen": swap_gen, "pid": os.getpid()}
        if self._group is not None:
            out["group"] = self._group
            out["group_size"] = self._group_size
            out["group_rank"] = self._group_rank
        return out

    def _publish(self):
        if self._client is None:
            return
        try:
            self._client.publish(self._info())
        except (TrackerError, OSError, ConnectionError):
            pass  # tracker gone: heartbeat loss handles liveness

    def _publish_loop(self):
        while not self._stop.wait(self._publish_interval):
            self._publish()

    # -- request handling -----------------------------------------------------
    def _op_predict(self, p):
        with self._cv:
            if self._state != "serving":
                raise ReplicaDraining(
                    "replica %s is %s: request not admitted (retry on "
                    "another replica)" % (self.addr, self._state))
            self._inflight += 1
            self._admitted += 1
        try:
            # chaos hook fires INSIDE admission: a crash here is a
            # replica dying with this request genuinely in flight
            fault = chaos.replica_request_fault()
            if fault == "stall":
                self._stop.wait()  # wedge: no reply ever leaves
                raise ServerClosed("replica stopped while wedged")
            model = p.get("model")
            wire = p.get("inputs")
            if not isinstance(wire, dict) or not wire:
                raise ServingError("predict: inputs must be a non-empty "
                                   "{name: array} dict")
            if set(wire) == {"__single__"}:
                # positional form: the model's single data input
                inputs = _np_from_wire(wire["__single__"])
            else:
                inputs = {str(k): _np_from_wire(v)
                          for k, v in wire.items()}
            deadline = p.get("deadline")
            tenant = p.get("tenant")
            priority = p.get("priority")
            if self._qos is not None:
                sample = inputs if not isinstance(inputs, dict) \
                    else next(iter(inputs.values()))
                rows = int(np.asarray(sample).shape[0]) \
                    if np.asarray(sample).ndim else 1
                # raises the typed TenantQuotaExceeded (wire kind
                # "quota") — never queued, never retried elsewhere
                priority = self._qos.admit(tenant, rows=rows)
            fut = self._server.submit(
                model, inputs,
                deadline=float(deadline) if deadline else None,
                tenant=tenant, priority=priority)
            outs = fut.result(
                timeout=(float(deadline) if deadline else 60.0) + 60.0)
            return {"outputs": [_np_to_wire(o) for o in outs]}
        finally:
            with self._cv:
                self._inflight -= 1
                self._cv.notify_all()

    # -- admin ops ------------------------------------------------------------
    def drain(self, deregister=False, timeout=None):
        """Stop admitting (typed :class:`ReplicaDraining` rejections),
        publish the state, and block until queued + in-flight requests
        finished. With ``deregister`` the replica also reports ``done``
        to the tracker — it leaves the fleet (decommission) instead of
        pausing for a swap."""
        timeout = self._drain_timeout if timeout is None else float(timeout)
        with self._cv:
            if self._state == "stopped":
                raise ServerClosed("replica %s is stopped" % self.addr)
            self._state = "draining"
        self._publish()
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._inflight > 0 or self._server.pending() > 0:
                if self._stop.is_set():
                    raise ServerClosed("replica stopped mid-drain")
                if time.monotonic() >= deadline:
                    raise FleetError(
                        "drain of %s did not finish in %.1fs "
                        "(MXNET_SERVE_DRAIN_TIMEOUT): %d in flight, %d "
                        "queued" % (self.addr, timeout, self._inflight,
                                    self._server.pending()))
                self._cv.wait(timeout=0.05)
            self._state = "drained"
        self._publish()
        if deregister and self._client is not None:
            self._client.done()
        return {"state": "drained"}

    def resume(self):
        """Re-admit traffic after a drain/swap and re-publish."""
        with self._cv:
            if self._state == "stopped":
                raise ServerClosed("replica %s is stopped" % self.addr)
            self._state = "serving"
        self._publish()
        return {"state": "serving"}

    def swap(self, p):
        """Quiesced checkpoint hot-swap of one (or every) resident
        model, then re-publish with a bumped ``swap_gen`` so routers
        can see the new weights generation land."""
        directory = p.get("directory")
        prefix = p.get("prefix")
        models = [p["model"]] if p.get("model") else self._server.models()
        swapped = 0
        for name in models:
            swapped += self._server.swap_from_checkpoint(
                name, prefix=prefix,
                epoch=p.get("epoch") if prefix is not None else None,
                directory=directory)
        with self._cv:
            self._swap_gen += 1
            gen = self._swap_gen
        self._publish()
        return {"swapped": swapped, "swap_gen": gen}

    def _op_stats(self):
        return {"info": self._info(),
                "serving": profiler.serving_stats()}

    # -- protocol loop --------------------------------------------------------
    def _dispatch(self, op, p):
        if op == "predict":
            return self._op_predict(p)
        if op == "ping":
            return {"state": self._state, "addr": self.addr,
                    "info": self._info()}
        if op == "stats":
            return self._op_stats()
        if op == "drain":
            return self.drain(deregister=bool(p.get("deregister")),
                              timeout=p.get("timeout"))
        if op == "resume":
            return self.resume()
        if op == "swap":
            return self.swap(p)
        raise FleetError("replica: unknown op %r" % (op,))

    def _handle(self, conn):
        try:
            while not self._stop.is_set():
                op, p = _recv_msg(conn)
                if op == "stop":
                    _send_msg(conn, ("ok", None))
                    self.shutdown()
                    return
                try:
                    payload = self._dispatch(op, p or {})
                except Exception as e:
                    try:
                        _send_msg(conn, ("err", {
                            "kind": _error_kind(e),
                            "msg": "%s: %s" % (type(e).__name__, e)}))
                    except OSError:
                        raise ConnectionError("reply failed")
                    continue
                _send_msg(conn, ("ok", payload))
        except _TRANSPORT_ERRORS:
            pass
        finally:
            self._conns.discard(conn)
            conn.close()

    def serve_forever(self):
        self._sock.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            self._conns.add(conn)
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def serve_in_background(self):
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self, close_server=True):
        with self._cv:
            self._state = "stopped"
            self._stop.set()
            self._cv.notify_all()
        if self._client is not None:
            self._client.done()
            self._client.close()
        for conn in list(self._conns):
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        try:
            self._sock.close()
        except OSError:
            pass
        if close_server:
            self._server.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False


# ---------------------------------------------------------------------------
# router
# ---------------------------------------------------------------------------
class _NeverSent(Exception):
    """Internal: the attempt failed before the request left the router
    — retry-safe on any replica regardless of idempotency."""


class _Handle:
    """Router-side view of one replica: published gauge + router-local
    in-flight + a small connection pool (one in-flight request per
    pooled socket)."""

    __slots__ = ("addr", "rank", "node_id", "alive", "state", "models",
                 "queued", "info", "inflight", "cooldown_until", "_pool",
                 "_lock", "group", "group_size", "group_rank", "group_ok")

    def __init__(self, addr, rank=0, node_id=None):
        self.addr = addr
        self.rank = rank
        self.node_id = node_id
        self.alive = True
        self.state = "serving"
        self.models = None          # None = unknown: route anything
        self.queued = 0
        self.info = {}
        self.group = None           # sharded replica group (ISSUE 20):
        self.group_size = 1         # only the leader (group_rank 0) is
        self.group_rank = 0         # routable, and only while ALL
        self.group_ok = True        # members are alive + serving
        self.inflight = 0           # router-local, atomic under _lock
        self.cooldown_until = 0.0   # transport-failure penalty box: a
        # WEDGED replica still heartbeats and publishes healthy, so
        # only the router's own failed attempts can steer load off it
        self._pool = []
        self._lock = threading.Lock()

    def load(self):
        with self._lock:
            return self.inflight + self.queued

    def acquire(self, connect_deadline):
        while True:
            with self._lock:
                if not self._pool:
                    break
                sock = self._pool.pop()
            # staleness probe: a pooled socket to a replica that died
            # since shows EOF/RST here — sending into it would succeed
            # locally and misclassify a NEVER-DELIVERED request as an
            # in-flight loss, breaking the idempotency retry contract.
            # setblocking(False), NOT MSG_DONTWAIT: Python's timeout
            # layer waits for readability before recv, so a leftover
            # per-attempt timeout would stall the probe and then
            # discard the LIVE socket as dead
            try:
                sock.setblocking(False)
                try:
                    if sock.recv(1, socket.MSG_PEEK):
                        raise OSError(
                            "unexpected bytes on idle connection")
                    # 0 bytes without raising = orderly EOF: dead
                    raise OSError("peer closed idle connection")
                finally:
                    sock.setblocking(True)
            except (BlockingIOError, InterruptedError):
                return sock  # no pending data: the connection is live
            except OSError:
                try:
                    sock.close()
                except OSError:
                    pass
        return connect_with_backoff(self.addr, deadline=connect_deadline)

    def release(self, sock):
        with self._lock:
            if len(self._pool) < 64:
                self._pool.append(sock)
                return
        try:
            sock.close()
        except OSError:
            pass

    def close(self):
        with self._lock:
            pool, self._pool = self._pool, []
        for s in pool:
            try:
                s.close()
            except OSError:
                pass


class FleetRouter:
    """Routes requests to the least-loaded live replica with bounded,
    failure-classified retry (module docstring has the full story).

    Exactly one discovery source:

    - ``tracker_uri`` — coalesce the scheduler's ``members`` view
      (production mode; refreshed every ``MXNET_FLEET_VIEW_INTERVAL``);
    - ``replicas`` — a static ``["host:port", ...]`` list, refreshed by
      pinging each replica (tracker-less deployments);
    - ``view_fn`` — a callable returning the member-dict list (the
      subprocess-free unit-test seam).
    """

    def __init__(self, tracker_uri=None, replicas=None, view_fn=None,
                 retries=None, timeout=None, backoff=None,
                 view_interval=None, connect_deadline=None, qos=None):
        sources = sum(x is not None for x in (tracker_uri, replicas,
                                              view_fn))
        if sources != 1:
            raise FleetError("FleetRouter: pass exactly one of "
                             "tracker_uri=, replicas=, view_fn=")
        self._tracker_uri = tracker_uri
        # QoS admission boundary (ISSUE 18): quotas charged BEFORE the
        # retry loop — a rejected request never queues, never retries
        self._qos = QosPolicy.from_env() if qos is None else qos
        self._static = list(replicas) if replicas is not None else None
        self._view_fn = view_fn
        self._retries = _knob_retries() if retries is None \
            else int(retries)
        self._timeout = _knob_timeout() if timeout is None \
            else float(timeout)
        self._backoff = _knob_backoff() if backoff is None \
            else float(backoff)
        self._view_interval = _knob_view_interval() \
            if view_interval is None else float(view_interval)
        self._connect_deadline = _knob_connect_deadline() \
            if connect_deadline is None else float(connect_deadline)
        if self._retries < 0:
            raise FleetError("FleetRouter: retries must be >= 0, got %d"
                             % self._retries)
        self._handles = {}          # addr -> _Handle
        self._view_lock = threading.Lock()
        self._last_refresh = 0.0
        self._tracker_sock = None
        self._tracker_lock = threading.Lock()
        self._closed = False
        self.refresh_view(force=True)

    # -- discovery ------------------------------------------------------------
    def _tracker_rpc(self, op, payload=None, timeout=15.0):
        with self._tracker_lock:
            if self._tracker_sock is None:
                self._tracker_sock = connect_with_backoff(
                    self._tracker_uri, deadline=self._connect_deadline)
            sock = self._tracker_sock
            try:
                sock.settimeout(timeout)
                _send_msg(sock, (op, payload or {}))
                status, reply = _recv_msg(sock)
            except _TRANSPORT_ERRORS as e:
                self._tracker_sock = None
                try:
                    sock.close()
                except OSError:
                    pass
                raise TrackerError("fleet view rpc %r failed: %s"
                                   % (op, e))
        if status != "ok":
            raise TrackerError("fleet view: %s" % (reply,))
        return reply

    def _view_entries(self):
        if self._view_fn is not None:
            return list(self._view_fn())
        if self._tracker_uri is not None:
            return self._tracker_rpc("members", {"role": "replica"})
        # static mode: ping every address in PARALLEL with a short
        # connect bound — a sequential full-deadline connect loop on
        # one dead replica would stall the request thread that
        # triggered the refresh for seconds per refresh
        entries = [{"addr": addr, "alive": False, "done": False,
                    "rank": i, "node_id": None, "info": {}}
                   for i, addr in enumerate(self._static)]

        def ping(entry):
            try:
                reply = self._admin_rpc(
                    entry["addr"], "ping", timeout=2.0,
                    connect_deadline=min(1.0, self._connect_deadline))
                entry["alive"] = True
                entry["info"] = reply.get("info") or {}
            except FleetError:
                pass

        threads = [threading.Thread(target=ping, args=(e,), daemon=True)
                   for e in entries]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=5.0)
        return entries

    def refresh_view(self, force=False):
        """Re-read the discovery plane (throttled to the view
        interval unless ``force``)."""
        now = time.monotonic()
        with self._view_lock:
            if not force and now - self._last_refresh < self._view_interval:
                return
            self._last_refresh = now
        try:
            entries = self._view_entries()
        except TrackerError:
            if not force:
                return  # keep routing on the stale view
            raise
        with self._view_lock:
            seen = set()
            for e in entries:
                addr = e.get("addr")
                if not addr:
                    continue
                seen.add(addr)
                h = self._handles.get(addr)
                if h is None:
                    h = self._handles[addr] = _Handle(
                        addr, rank=int(e.get("rank") or 0),
                        node_id=e.get("node_id"))
                info = e.get("info") or {}
                h.alive = bool(e.get("alive", True)) \
                    and not e.get("done", False)
                h.state = info.get("state", "serving")
                h.models = info.get("models")
                h.queued = int(info.get("queued") or 0)
                h.info = info
                h.rank = int(e.get("rank") or h.rank)
                h.group = info.get("group")
                h.group_size = int(info.get("group_size") or 1)
                h.group_rank = int(info.get("group_rank") or 0)
            for addr in list(self._handles):
                if addr not in seen:
                    self._handles.pop(addr).close()
            # sharded-group gate (ISSUE 20): a group is one routable
            # replica — its leader — and only while EVERY member is
            # alive and serving. One dead/draining member drains the
            # whole group (a partial group would hang or corrupt the
            # collective), so no request is ever routed to a torn group.
            members = {}
            for h in self._handles.values():
                if h.group is not None:
                    members.setdefault(h.group, []).append(h)
            for h in self._handles.values():
                if h.group is None:
                    h.group_ok = True
                    continue
                grp = members[h.group]
                h.group_ok = (
                    len(grp) >= h.group_size
                    and all(m.alive and m.state == "serving"
                            for m in grp))
            alive = sum(1 for h in self._handles.values()
                        if h.alive and h.state == "serving")
        profiler.fleet_record(replicas_alive=alive)

    def _routable(self, model, exclude, honor_cooldown=True):
        now = time.monotonic()
        with self._view_lock:
            handles = list(self._handles.values())
        return [h for h in handles
                if h.alive and h.state == "serving"
                and (h.group is None
                     or (h.group_rank == 0 and h.group_ok))
                and (h.models is None or model in h.models)
                and h.addr not in exclude
                and (not honor_cooldown or h.cooldown_until <= now)]

    def _pick(self, model, exclude):
        """Least-loaded live ``serving`` replica (router-local
        in-flight + published queue depth; rank breaks ties).
        Preference order degrades gracefully: skip replicas in the
        transport-failure penalty box, then skip only the ones this
        request already tried, then anything serving — after backoff a
        retried overload may well succeed on the same replica."""
        for ex, cool in ((exclude, True), (exclude, False),
                         (set(), False)):
            cands = self._routable(model, ex, honor_cooldown=cool)
            if cands:
                return min(cands,
                           key=lambda h: (h.load(), h.rank, h.addr))
        return None

    def replicas(self):
        """[(addr, state, alive, load)] snapshot of the current view."""
        with self._view_lock:
            return sorted(
                (h.addr, h.state, h.alive, h.load())
                for h in self._handles.values())

    # -- request path ---------------------------------------------------------
    def request(self, model, inputs, timeout=None, idempotent=True,
                tenant=None, priority=None):
        """Route one request; returns the list of output arrays.

        ``timeout`` overrides ``MXNET_FLEET_TIMEOUT`` as this request's
        end-to-end budget (attempts + backoff + replica queueing: the
        remaining budget rides to the replica as its shed deadline).
        ``idempotent=False`` disables the in-flight-loss retry: a
        request whose connection died after the send then raises
        :class:`ReplicaConnectionLost` instead of re-executing.
        ``tenant`` labels the request for QoS (ISSUE 18): the router
        charges the tenant's quota HERE, before any replica is picked —
        an over-quota request raises the typed
        :class:`TenantQuotaExceeded` without queueing or retrying —
        and the label rides the wire so the broker sheds by priority
        class at dequeue. ``priority`` overrides the tenant's class
        (an int from qos.PRIORITIES)."""
        self._check_open()
        budget = self._timeout if timeout is None else float(timeout)
        if not budget > 0:
            raise FleetError("request: timeout must be > 0, got %r"
                             % timeout)
        deadline = time.monotonic() + budget
        if not isinstance(inputs, dict):
            inputs = {"__single__": inputs}
        if self._qos is not None:
            sample = np.asarray(next(iter(inputs.values())))
            rows = int(sample.shape[0]) if sample.ndim else 1
            admitted_priority = self._qos.admit(tenant, rows=rows)
            if priority is None:
                priority = admitted_priority
        wire = {k: _np_to_wire(v) for k, v in inputs.items()}
        profiler.fleet_record(requests=1)
        t0 = time.perf_counter()
        self.refresh_view()
        exclude = set()
        attempts_left = self._retries
        overloaded_path = False
        last_err = None
        while True:
            h = self._pick(model, exclude)
            if h is None:
                try:
                    self.refresh_view(force=True)
                except TrackerError as e:
                    # a dead discovery plane must surface as the TYPED
                    # error (and count), not leak a raw TrackerError
                    profiler.fleet_record(failed=1)
                    raise NoLiveReplica(
                        "no routable replica for %r and the discovery "
                        "plane is unreachable (%s)" % (model, e))
                h = self._pick(model, exclude)
            if h is None:
                profiler.fleet_record(failed=1)
                if overloaded_path:
                    raise FleetOverloaded(
                        "no admitting replica for %r within the "
                        "budget (last: %s)" % (model, last_err))
                raise NoLiveReplica(
                    "no live serving replica for model %r (view: %s)"
                    % (model, self.replicas()))
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                profiler.fleet_record(failed=1)
                raise FleetOverloaded(
                    "request budget %.1fs exhausted after retries "
                    "(MXNET_FLEET_TIMEOUT; last: %s)"
                    % (budget, last_err))
            attempt_timeout = max(
                remaining / (attempts_left + 1.0), 0.05)
            try:
                outs = self._forward(h, model, wire, attempt_timeout,
                                     remaining, tenant=tenant,
                                     priority=priority)
                profiler.fleet_record(
                    completed=1,
                    latencies=[time.perf_counter() - t0])
                if tenant is not None:
                    profiler.qos_record(
                        str(tenant), completed=1,
                        latencies=[time.perf_counter() - t0])
                return outs
            except TenantQuotaExceeded:
                # replica-enforced quota: terminal by contract — the
                # budget is fleet-wide per tenant, retrying elsewhere
                # would just spend capacity circumventing it
                profiler.fleet_record(failed=1)
                raise
            except _NeverSent as e:
                profiler.fleet_record(failovers=1)
                h.cooldown_until = time.monotonic() + self._view_interval
                last_err = e
            except ReplicaConnectionLost as e:
                profiler.fleet_record(inflight_lost=1)
                # penalty box: a wedged replica looks healthy on the
                # tracker (it still beats + publishes) — only these
                # failed attempts can steer traffic off it
                h.cooldown_until = time.monotonic() \
                    + 2.0 * self._view_interval
                if not idempotent:
                    profiler.fleet_record(failed=1)
                    raise
                last_err = e
            except ReplicaDraining as e:
                # typed admission rejection: never executed, the
                # health-driven drain path — always retry elsewhere
                profiler.fleet_record(draining_rejections=1)
                self._mark_draining(h)
                last_err = e
            except ServerClosed as e:
                profiler.fleet_record(draining_rejections=1)
                self._mark_draining(h, state="closed")
                last_err = e
            except (DeadlineExceeded, ServerOverloaded) as e:
                profiler.fleet_record(overload_rejections=1)
                overloaded_path = True
                last_err = e
            # every other exception (FleetRemoteError, ServingError
            # validation) is a genuine failure: surface it unretried
            except FleetRemoteError:
                profiler.fleet_record(failed=1)
                raise
            exclude.add(h.addr)
            if attempts_left <= 0:
                profiler.fleet_record(failed=1)
                if overloaded_path or isinstance(
                        last_err, (DeadlineExceeded, ServerOverloaded)):
                    raise FleetOverloaded(
                        "retry budget %d exhausted under overload "
                        "(MXNET_FLEET_RETRIES; last: %s)"
                        % (self._retries, last_err))
                if isinstance(last_err, ReplicaConnectionLost):
                    raise last_err
                raise FleetError(
                    "retry budget %d exhausted (MXNET_FLEET_RETRIES; "
                    "last: %s)" % (self._retries, last_err))
            attempts_left -= 1
            profiler.fleet_record(retries=1)
            pause = min(
                self._backoff * (2 ** (self._retries - attempts_left - 1)),
                1.0, max(deadline - time.monotonic(), 0.0))
            if pause > 0:
                time.sleep(pause)

    predict = request

    def _mark_draining(self, handle, state="draining"):
        handle.state = state  # routed around until the next view says
        # otherwise (the replica re-publishes on resume)

    def _forward(self, h, model, wire, attempt_timeout, remaining,
                 tenant=None, priority=None):
        if chaos.router_fault("send"):
            raise _NeverSent("chaos: router drop (send)")
        try:
            sock = h.acquire(min(self._connect_deadline, attempt_timeout))
        except (TrackerError, OSError) as e:
            raise _NeverSent("connect to %s failed: %s" % (h.addr, e))
        with h._lock:
            h.inflight += 1
        sent = False
        try:
            try:
                sock.settimeout(attempt_timeout)
                _send_msg(sock, ("predict", {
                    "model": model, "inputs": wire,
                    "deadline": remaining, "tenant": tenant,
                    "priority": priority}))
                sent = True
                if chaos.router_fault("reply"):
                    raise ConnectionError("chaos: router drop (reply)")
                status, reply = _recv_msg(sock)
            except _TRANSPORT_ERRORS as e:
                try:
                    sock.close()
                except OSError:
                    pass
                if not sent:
                    raise _NeverSent(
                        "send to %s failed before the request left: %s"
                        % (h.addr, e))
                raise ReplicaConnectionLost(
                    "request to %s was sent but the connection died "
                    "before a reply (%s: %s) — the forward may have "
                    "executed" % (h.addr, type(e).__name__, e))
            h.release(sock)
        finally:
            with h._lock:
                h.inflight -= 1
        if status == "ok":
            return [_np_from_wire(w) for w in reply["outputs"]]
        kind = (reply or {}).get("kind", "error")
        msg = (reply or {}).get("msg", "replica error")
        err_cls = _KIND_TO_ERROR.get(kind)
        if err_cls is not None and kind in ("draining", "closed",
                                            "deadline", "overloaded",
                                            "quota"):
            raise err_cls("%s: %s" % (h.addr, msg))
        raise FleetRemoteError(kind, "%s: %s" % (h.addr, msg))

    # -- admin ----------------------------------------------------------------
    def _admin_rpc(self, addr, op, payload=None, timeout=None,
                   connect_deadline=None):
        timeout = (_knob_drain_timeout() + 15.0) if timeout is None \
            else float(timeout)
        try:
            sock = connect_with_backoff(
                addr, deadline=self._connect_deadline
                if connect_deadline is None else connect_deadline)
        except TrackerError as e:
            raise FleetError("admin %r: cannot reach %s (%s)"
                             % (op, addr, e))
        try:
            sock.settimeout(timeout)
            _send_msg(sock, (op, payload or {}))
            status, reply = _recv_msg(sock)
        except _TRANSPORT_ERRORS as e:
            raise FleetError("admin %r to %s failed: %s" % (op, addr, e))
        finally:
            try:
                sock.close()
            except OSError:
                pass
        if status != "ok":
            kind = (reply or {}).get("kind", "error") \
                if isinstance(reply, dict) else "error"
            msg = (reply or {}).get("msg", reply) \
                if isinstance(reply, dict) else reply
            err_cls = _KIND_TO_ERROR.get(kind, FleetRemoteError)
            if err_cls is FleetRemoteError:
                raise FleetRemoteError(kind, "%s: %s" % (addr, msg))
            raise err_cls("%s: %s" % (addr, msg))
        return reply

    def drain(self, addr, deregister=False, timeout=None):
        """Explicit drain RPC: blocks until the replica finished its
        queued + in-flight work. The local view is marked immediately
        so this router routes around the drain before the replica's
        next publish lands."""
        with self._view_lock:
            h = self._handles.get(addr)
        if h is not None:
            self._mark_draining(h)
        return self._admin_rpc(addr, "drain",
                               {"deregister": bool(deregister),
                                "timeout": timeout})

    def resume(self, addr):
        return self._admin_rpc(addr, "resume")

    def replica_stats(self, addr):
        return self._admin_rpc(addr, "stats", timeout=15.0)

    def fleet_swap(self, directory=None, prefix=None, epoch=None,
                   model=None):
        """Roll a checkpoint across the fleet ONE replica at a time
        with zero dropped requests: drain (typed rejections route the
        traffic to the other replicas) → quiesced swap → resume +
        re-publish. Returns the number of replicas swapped."""
        if (prefix is None) == (directory is None):
            raise FleetError("fleet_swap: pass exactly one of prefix= "
                             "or directory=")
        self.refresh_view(force=True)
        with self._view_lock:
            targets = sorted(
                (h for h in self._handles.values() if h.alive),
                key=lambda h: (h.rank, h.addr))
        if not any(h.state == "serving" for h in targets):
            raise NoLiveReplica("fleet_swap: no live serving replica")
        payload = {"directory": directory, "prefix": prefix,
                   "epoch": epoch, "model": model}
        swapped = 0
        for h in targets:
            if h.state == "serving":
                self._mark_draining(h)
                self.drain(h.addr)
                self._admin_rpc(h.addr, "swap", payload)
                self.resume(h.addr)
                h.state = "serving"
            else:
                # an operator-drained replica gets the NEW weights too
                # (a later resume must not serve a stale generation)
                # but stays paused — draining was someone's decision
                self._admin_rpc(h.addr, "swap", payload)
            swapped += 1
            profiler.fleet_record(swaps=1)
        self.refresh_view(force=True)
        return swapped

    def stop_fleet(self):
        """Best-effort ``stop`` to every known replica (graceful fleet
        teardown — each replica entrypoint exits 0)."""
        self.refresh_view(force=True)
        with self._view_lock:
            addrs = [h.addr for h in self._handles.values() if h.alive]
        stopped = 0
        for addr in addrs:
            try:
                self._admin_rpc(addr, "stop", timeout=10.0)
                stopped += 1
            except FleetError:
                continue
        return stopped

    def stats(self, reset=False):
        """Router-side fleet counters (profiler.fleet_stats)."""
        return profiler.fleet_stats(reset=reset)

    # -- lifecycle ------------------------------------------------------------
    def _check_open(self):
        if self._closed:
            raise FleetError("FleetRouter is closed")

    def close(self):
        if self._closed:
            return
        self._closed = True
        with self._view_lock:
            handles = list(self._handles.values())
        for h in handles:
            h.close()
        with self._tracker_lock:
            if self._tracker_sock is not None:
                try:
                    self._tracker_sock.close()
                except OSError:
                    pass
                self._tracker_sock = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# entrypoints (tools/launch.py --serve spawns the replica one)
# ---------------------------------------------------------------------------
def _env_tracker_uri(explicit=None):
    if explicit:
        return explicit
    host = os.environ.get("DMLC_PS_ROOT_URI")
    port = os.environ.get("DMLC_PS_ROOT_PORT")
    return "%s:%s" % (host, port) if host and port else None


def _parse_data_shapes(specs):
    shapes = {}
    for spec in specs:
        name, sep, dims = spec.partition(":")
        if not sep or not name:
            raise FleetError(
                "--data-shape %r: expected name:d0,d1,..." % spec)
        try:
            shapes[name] = tuple(int(d) for d in dims.split(","))
        except ValueError:
            raise FleetError(
                "--data-shape %r: dims must be integers" % spec)
    return shapes


def _replica_main(argv):
    ap = argparse.ArgumentParser(
        prog="mxnet_tpu.serving.fleet replica",
        description="Serving-fleet replica: ModelServer behind the "
                    "tracker-discovered wire endpoint")
    ap.add_argument("--model", default="model",
                    help="resident model name (default: model)")
    ap.add_argument("--prefix", required=True,
                    help="two-artifact checkpoint prefix to serve")
    ap.add_argument("--epoch", type=int, default=0)
    ap.add_argument("--data-shape", action="append", required=True,
                    help="input spec name:d0,d1,... (repeatable); the "
                         "leading dim is the batch axis")
    ap.add_argument("--ladder", default=None,
                    help="batch ladder override, e.g. 1,4,16")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--tracker", default=None,
                    help="scheduler URI (default: DMLC_PS_ROOT_URI/"
                         "PORT from the launch.py env)")
    ap.add_argument("--pin-core", type=int, default=None,
                    help="pin this process to one CPU core (bench "
                         "determinism on shared hosts)")
    ap.add_argument("--group", default=None,
                    help="sharded replica group name (ISSUE 20): all "
                         "members of one mesh publish the same group; "
                         "the router routes only to its rank-0 leader "
                         "while every member is alive")
    ap.add_argument("--group-size", type=int, default=1)
    ap.add_argument("--group-rank", type=int, default=0)
    args = ap.parse_args(argv)

    if args.pin_core is not None and hasattr(os, "sched_setaffinity"):
        try:
            os.sched_setaffinity(0, {args.pin_core})
        except OSError:
            pass

    from ..model import load_checkpoint

    symbol, arg_params, aux_params = load_checkpoint(args.prefix,
                                                     args.epoch)
    ladder = tuple(int(b) for b in args.ladder.split(",")) \
        if args.ladder else None
    server = ModelServer(ladder=ladder, dtype=args.dtype)
    server.add_model(args.model, symbol=symbol, arg_params=arg_params,
                     aux_params=aux_params,
                     data_shapes=_parse_data_shapes(args.data_shape))
    # compile the smallest bucket before admitting traffic so the
    # first routed request does not eat a cold jit
    shapes = _parse_data_shapes(args.data_shape)
    warm = {n: np.zeros((1,) + tuple(s[1:]), np.float32)
            for n, s in shapes.items()}
    server.predict(args.model, warm)

    rank = os.environ.get("DMLC_REPLICA_ID")
    restart = int(os.environ.get("DMLC_RESTART_COUNT", "0") or 0)
    replica = ReplicaServer(
        server, tracker_uri=_env_tracker_uri(args.tracker),
        host=args.host, port=args.port,
        rank=int(rank) if rank is not None else None, restart=restart,
        group=args.group, group_size=args.group_size,
        group_rank=args.group_rank)

    exit_code = [0]

    def _sigterm(signum, frame):
        # preemption contract (PR 9): exit with the resumable status so
        # launch.py --serve respawns this replica for FREE
        exit_code[0] = EXIT_PREEMPTED
        replica.shutdown()

    signal.signal(signal.SIGTERM, _sigterm)
    print("replica rank=%s listening on %s (model=%r pid=%d)"
          % (replica.rank, replica.addr, args.model, os.getpid()),
          flush=True)
    replica.serve_forever()
    replica.shutdown()
    return exit_code[0]


def _router_main(argv):
    ap = argparse.ArgumentParser(
        prog="mxnet_tpu.serving.fleet router",
        description="Fleet admin client: inspect/drain/swap/stop the "
                    "tracker-discovered replica fleet")
    ap.add_argument("command",
                    choices=("status", "drain", "resume", "swap",
                             "stop"))
    ap.add_argument("--tracker", default=None,
                    help="scheduler URI (default: DMLC_PS_ROOT_URI/"
                         "PORT)")
    ap.add_argument("--addr", default=None,
                    help="target replica for drain/resume")
    ap.add_argument("--deregister", action="store_true")
    ap.add_argument("--directory", default=None)
    ap.add_argument("--prefix", default=None)
    ap.add_argument("--epoch", type=int, default=None)
    ap.add_argument("--model", default=None)
    args = ap.parse_args(argv)
    uri = _env_tracker_uri(args.tracker)
    if uri is None:
        ap.error("no tracker: pass --tracker or set "
                 "DMLC_PS_ROOT_URI/PORT")
    with FleetRouter(tracker_uri=uri) as router:
        if args.command == "status":
            out = {"replicas": [
                {"addr": a, "state": s, "alive": al, "load": ld}
                for a, s, al, ld in router.replicas()]}
        elif args.command in ("drain", "resume"):
            if not args.addr:
                ap.error("%s needs --addr" % args.command)
            fn = router.drain if args.command == "drain" else \
                router.resume
            out = fn(args.addr) if args.command == "resume" else \
                router.drain(args.addr, deregister=args.deregister)
        elif args.command == "swap":
            out = {"swapped": router.fleet_swap(
                directory=args.directory, prefix=args.prefix,
                epoch=args.epoch, model=args.model)}
        else:
            out = {"stopped": router.stop_fleet()}
        print(json.dumps(out))
    return 0


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] not in ("replica", "router", "autoscaler"):
        print("usage: python -m mxnet_tpu.serving.fleet "
              "{replica|router|autoscaler} ...", file=sys.stderr)
        return 2
    if argv[0] == "replica":
        return _replica_main(argv[1:])
    if argv[0] == "autoscaler":
        from .autoscale import main as autoscale_main

        return autoscale_main(argv[1:])
    return _router_main(argv[1:])


if __name__ == "__main__":
    sys.exit(main())
