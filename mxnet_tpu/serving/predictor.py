"""AOT-compiled inference predictor (the serving half of ISSUE 6).

Reference counterpart: ``src/c_api/c_predict_api.cc`` binds a trained
symbol + params into a standalone inference executor (PAPER.md §layer
8). TPU-native design, grounded in the bind-time deployment
optimizations of Relay (arXiv:1810.00952 — fusion/layout/constant
folding compose at compile time) and nncase (arXiv:2512.21571):

- **Constant folding.** At bind time the symbol graph is split on data
  dependence (``Symbol.data_dependent_nodes``): every node that is a
  pure function of the weights is evaluated ONCE per parameter set by a
  jitted *fold* program, and its outputs enter the per-request program
  as plain array arguments. A request executes only the data-dependent
  suffix of the graph.
- **Weight layout freezing.** Parameters are converted exactly once to
  device-resident arrays in the serving dtype (fp32 default, bf16
  supported); XLA then lays them out for the compiled executable — no
  per-request host conversion or transfer.
- **Batch-size ladder.** Forwards are bound at a ladder of batch sizes
  (``MXNET_SERVE_BATCH_LADDER``, default 1/4/16/64); a request of n
  rows is padded up to the smallest bucket >= n and the pad rows are
  sliced away after the forward. Compiled executables are cached in an
  LRU keyed by ``(model, bucket, dtype)`` so many resident models share
  one bounded compile budget.
- **Donated input buffers.** Each bucket forward is jitted with
  ``donate_argnums`` on the batch so XLA may reuse the input HBM for
  activations/outputs (a no-op on the CPU test backend).
- **Hot swap.** :meth:`AOTPredictor.swap_params` refreezes the weights,
  re-runs the fold program, and atomically replaces the constant set —
  shapes/dtypes are validated equal, so every cached executable stays
  valid and in-flight requests never observe a half-swapped model.

The per-node op invocation is shared with the training executor
(``executor.eval_node``), so serving math is bit-identical to the
framework's own inference forward.
"""
from __future__ import annotations

import os
import threading
import warnings
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from .. import symbol as sym_mod
from ..base import MXNetError, dtype_name, dtype_np
from ..context import Context
from ..executor import eval_node



class ServingError(MXNetError):
    """Serving-tier failure (bad knob, bad request, closed server)."""


# ---------------------------------------------------------------------------
# MXNET_SERVE_* knob surface — validated loudly, the tracker/kvstore
# convention from PRs 2-4: a malformed value must raise at construction,
# never be silently coerced into a default.
# ---------------------------------------------------------------------------
DEFAULT_LADDER = (1, 4, 16, 64)


def validate_ladder(ladder, source="batch ladder"):
    """A ladder is a non-empty, strictly increasing tuple of positive
    ints; anything else raises :class:`ServingError` naming the source."""
    try:
        entries = tuple(int(str(b).strip()) for b in ladder)
    except (TypeError, ValueError):
        raise ServingError(
            "%s %r: every entry must be an integer batch size"
            % (source, ladder))
    if not entries:
        raise ServingError("%s is empty: need at least one batch size"
                           % source)
    for b in entries:
        if b < 1:
            raise ServingError(
                "%s %r: batch sizes must be >= 1 (got %d)"
                % (source, ladder, b))
    if any(b >= c for b, c in zip(entries, entries[1:])):
        raise ServingError(
            "%s %r must be strictly increasing" % (source, entries))
    return entries


def env_batch_ladder(default=DEFAULT_LADDER):
    raw = os.environ.get("MXNET_SERVE_BATCH_LADDER")
    if raw is None or raw == "":
        return tuple(default)
    return validate_ladder(raw.split(","),
                           source="MXNET_SERVE_BATCH_LADDER=%r" % raw)


def env_positive_int(name, default):
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return int(default)
    try:
        value = int(raw)
    except ValueError:
        raise ServingError("%s=%r is not an integer" % (name, raw))
    if value < 1:
        raise ServingError("%s=%r must be >= 1" % (name, raw))
    return value


def _resolve_quant(quant):
    """Serving quantization mode: the explicit argument wins, else the
    MXNET_SERVE_QUANT knob; 'none'/'int8' only, anything else raises
    naming its source."""
    from .. import config

    if quant is None:
        return config.get_choice("MXNET_SERVE_QUANT", ("none", "int8"))
    mode = str(quant).strip().lower()
    if mode not in ("none", "int8"):
        raise ServingError(
            "quant=%r: serving quantization must be 'none' or 'int8'"
            % (quant,))
    return mode


def env_positive_float(name, default):
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return float(default)
    try:
        value = float(raw)
    except ValueError:
        raise ServingError("%s=%r is not a number" % (name, raw))
    if not 0 < value < float("inf"):  # also rejects NaN
        raise ServingError("%s=%r must be a finite value > 0"
                           % (name, raw))
    return value


# ---------------------------------------------------------------------------
# compiled-executable residency
# ---------------------------------------------------------------------------
class ExecutableCache:
    """LRU of compiled bucket forwards keyed by (model, bucket, dtype).

    Multi-model residency (ISSUE 6): every resident model's buckets
    compile into one shared, bounded cache; evicting an executable only
    costs a recompile on next use — model *parameters* stay resident in
    the predictor, so eviction never loses state. ``capacity=None`` is
    unbounded (the standalone single-predictor default)."""

    def __init__(self, capacity=None):
        if capacity is not None:
            capacity = int(capacity)
            if capacity < 1:
                raise ServingError(
                    "ExecutableCache: capacity must be >= 1, got %d"
                    % capacity)
        self.capacity = capacity
        self.compiles = 0   # build count — the LRU-eviction observable
        self.evictions = 0
        self._lock = threading.Lock()
        self._entries = OrderedDict()

    def get_or_build(self, key, build):
        with self._lock:
            fn = self._entries.get(key)
            if fn is not None:
                self._entries.move_to_end(key)
                return fn
        fn = build()  # build outside the lock: compiles can be slow
        with self._lock:
            self._entries[key] = fn
            self._entries.move_to_end(key)
            self.compiles += 1
            while (self.capacity is not None
                   and len(self._entries) > self.capacity):
                self._entries.popitem(last=False)
                self.evictions += 1
        return fn

    def __len__(self):
        with self._lock:
            return len(self._entries)


def _pick_internals(sym, output_names):
    """Partial-output symbol selection (ref: c_predict_api.cc uses
    sym.GetInternals() so any layer can be an output) — THE bind path
    shared by the C predict ABI and the serving tier."""
    internals = sym.get_internals()
    outs = internals.list_outputs()
    picked = []
    for name in output_names:
        want = name if name in outs else name + "_output"
        if want not in outs:
            raise ValueError("unknown output %r (have %s)" % (name, outs))
        picked.append(internals[outs.index(want)])
    return sym_mod.Group(picked) if len(picked) > 1 else picked[0]


class AOTPredictor:
    """One model bound for inference at a ladder of batch sizes.

    Parameters
    ----------
    symbol : Symbol
        The inference graph (pass ``output_names`` to serve internal
        layers, ``get_internals`` semantics).
    arg_params, aux_params : dict, optional
        ``{name: array}`` (numpy or NDArray). Arguments that are
        neither data inputs nor present in ``arg_params`` are
        zero-filled (c_predict parity: loss labels, eval-only args).
    data_shapes : dict
        ``{input_name: shape}``. The leading dimension is the batch
        axis; with a ladder it is rebound per bucket, with
        ``ladder=None`` the predictor binds these exact shapes (the C
        ABI mode — no padding, no bucket selection).
    ladder : tuple of int, optional
        Batch-size buckets. Default ``MXNET_SERVE_BATCH_LADDER``
        (1/4/16/64). ``None`` = exact-shape bind.
    dtype : str or np.dtype
        Serving compute dtype; float params/inputs are frozen/cast to
        it, float outputs are cast back to fp32.
    device : Context or jax.Device, optional
        Where frozen weights (and therefore the computation) live.
    cache : ExecutableCache, optional
        Shared executable LRU; private unbounded cache by default.
    model_name : str, optional
        Cache-key namespace (the server passes its model name).
    mesh : jax.sharding.Mesh, optional
        Bind a SHARDED executable across a device group (ISSUE 20):
        weights are frozen with the NamedShardings that
        ``param_rules`` (regex → PartitionSpec, the
        ``parallel.spmd.param_shardings`` grammar) assign, requests
        enter replicated, and GSPMD partitions the per-request
        program across the group — per-chip parameter bytes drop to
        ~1/mp for the sharded layers. The group is ONE predictor in
        one process (the offline host-device half; a multi-process
        group is the on-chip follow-up). Mutually exclusive with
        ``device``. A matched rule that cannot apply raises
        (``ShardingRuleError``) — never a silent replication.
    param_rules : list of (regex, PartitionSpec) or str, optional
        Sharding rules for ``mesh``; a string is parsed with the
        ``MXNET_MP_RULES`` grammar (``regex:spec;regex:spec``).
        Default replicates everything.
    """

    def __init__(self, symbol, arg_params=None, aux_params=None,
                 data_shapes=None, ladder=DEFAULT_LADDER, dtype="float32",
                 device=None, output_names=None, cache=None,
                 model_name=None, rng_seed=0, quant=None, calib_data=None,
                 quant_exclude=(), mesh=None, param_rules=None):
        if not data_shapes:
            raise ServingError("AOTPredictor: data_shapes is required "
                               "({input_name: shape})")
        if output_names:
            symbol = _pick_internals(symbol, output_names)
        self._quant = _resolve_quant(quant)
        self._sym = symbol
        self._data_shapes = {k: tuple(v) for k, v in data_shapes.items()}
        self._data_names = sorted(self._data_shapes)
        if ladder is None:
            self._ladder = None
        elif ladder is DEFAULT_LADDER:
            self._ladder = env_batch_ladder()
        else:
            self._ladder = validate_ladder(ladder)
        self._np_dtype = dtype_np(dtype)
        self._dtype_name = dtype_name(self._np_dtype)
        if isinstance(device, Context):
            device = device.jax_device()
        if mesh is not None and device is not None:
            raise ServingError(
                "AOTPredictor: pass mesh= OR device=, not both (the "
                "mesh decides placement for a sharded bind)")
        self._device = device
        self._mesh = mesh
        if isinstance(param_rules, str):
            # accept the MXNET_MP_RULES string grammar directly
            from ..parallel.spmd import parse_rules

            param_rules = parse_rules(param_rules,
                                      knob="AOTPredictor param_rules")
        self._param_rules = list(param_rules or [])
        self._group_size = int(mesh.devices.size) if mesh is not None else 1
        self._cache = cache if cache is not None else ExecutableCache(None)
        self._cache_key = model_name if model_name is not None \
            else "pred-%d" % id(self)
        self._key = jax.random.PRNGKey(rng_seed)
        self._lock = threading.Lock()

        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        for name in self._data_names:
            if name not in arg_names:
                raise ServingError(
                    "AOTPredictor: data input %r is not an argument of "
                    "the symbol (arguments: %s)" % (name, arg_names))
        arg_params = dict(arg_params or {})
        aux_params = dict(aux_params or {})
        self._weight_names = [n for n in arg_names
                              if n not in self._data_names
                              and n in arg_params]
        self._bound_aux = [n for n in aux_names if n in aux_params]
        self._extra_names = sorted(
            [n for n in arg_names if n not in self._data_names
             and n not in arg_params]
            + [n for n in aux_names if n not in aux_params])
        if self._extra_names:
            # ref parity: c_predict_api.cc warns and zero-fills args
            # absent from the params file (loss labels, eval-only args)
            warnings.warn(
                "AOTPredictor: zero-filling arguments absent from the "
                "params: %s" % self._extra_names, stacklevel=2)

        # ---- int8 post-training quantization (ISSUE 13) -------------------
        # Applied as an IR pass BEFORE the fold split: weights route
        # through in-graph _quantize_rows_int8 nodes, which are pure
        # functions of the params — the shared fold pass below
        # evaluates them once per parameter set (and again on every
        # swap, requantizing the WEIGHTS), so weights are quantized
        # ahead of time while activations quantize at the bound
        # boundary inside the per-request program. Activation scales
        # are calibration-time constants: a swap to a distribution-
        # shifted checkpoint should rebind with fresh calib_data.
        # Argument/aux names are unchanged, so the ladder/cache/swap
        # machinery runs untouched.
        self.quant_report = None
        self._quant_fingerprint = "none"
        if self._quant == "int8":
            import hashlib
            import json as _json

            from .. import ir

            merged = {n: arg_params[n] for n in self._weight_names}
            merged.update({n: aux_params[n] for n in self._bound_aux})
            symbol, self.quant_report = ir.quantize_for_serving(
                symbol, merged, calib_data, self._data_names,
                exclude=quant_exclude)
            self._sym = symbol
            # the calibrated activation scales are baked into the
            # traced programs as graph attrs — two int8 binds with
            # different calibration (or an int8 and a float bind)
            # under one shared-cache model name must never resolve to
            # each other's executables (the PR 12 GenerativePredictor
            # key lesson)
            scales = {k: v["scale"] for k, v in
                      self.quant_report.get("calibration", {}).items()}
            self._quant_fingerprint = "int8-" + hashlib.sha1(
                _json.dumps(sorted(scales.items())).encode()
            ).hexdigest()[:12]

        # shape validation against one representative bind (weight/aux
        # shapes are batch-independent, so any bucket works)
        shapes0 = self._bucket_shapes(
            self._ladder[0] if self._ladder else None)
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shapes0)
        inferred = dict(zip(arg_names, arg_shapes))
        inferred.update(zip(aux_names, aux_shapes))
        params = {}
        for name in self._weight_names + self._bound_aux:
            src = arg_params.get(name, aux_params.get(name))
            arr = self._freeze_one(name, src)
            if tuple(arr.shape) != tuple(inferred[name]):
                raise ServingError(
                    "AOTPredictor: param %r has shape %s, the graph "
                    "needs %s" % (name, tuple(arr.shape),
                                  tuple(inferred[name])))
            params[name] = arr

        # ---- constant-fold split (ir/fold.py — ONE pass shared with
        # the C-predict ABI, which binds through this class) ----------------
        # extras are zero-filled per bucket IN the traced program (their
        # shapes may carry the batch dim), so for folding purposes they
        # are dynamic, exactly like real data
        from ..ir import FoldPlan

        self._plan = FoldPlan(
            symbol, set(self._data_names) | set(self._extra_names))
        self._fold_fn = self._plan.make_fold_fn(self._key)
        self._params = params
        self._consts = self._fold_fn(params)
        self.bind_stats = {
            "folded_nodes": self._plan.folded_nodes,
            "dynamic_nodes": self._plan.dynamic_nodes,
            "frozen_params": len(params),
            "zero_filled": list(self._extra_names),
            "ladder": self._ladder,
            "dtype": self._dtype_name,
            "quant": self._quant,
        }
        if self.quant_report is not None:
            self.bind_stats["quantized_ops"] = \
                self.quant_report["quantized_ops"]

    def _freeze_one(self, name, value):
        v = value.asnumpy() if hasattr(value, "asnumpy") else np.asarray(value)
        if np.issubdtype(v.dtype, np.floating) \
                and v.dtype != self._np_dtype:
            v = v.astype(self._np_dtype)
        if self._mesh is not None:
            # sharded bind (ISSUE 20): the rules decide this weight's
            # placement across the group; an inapplicable matched rule
            # raises ShardingRuleError (never silent replication)
            from ..parallel.spmd import param_shardings

            sh = param_shardings({name: v}, self._mesh,
                                 self._param_rules)[name]
            return jax.device_put(jnp.asarray(v), sh)
        arr = jnp.asarray(v)
        if self._device is not None:
            arr = jax.device_put(arr, self._device)
        return arr

    # -- per-bucket compilation ----------------------------------------------
    def _bucket_shapes(self, bucket):
        if bucket is None:  # exact-shape bind (the C ABI mode)
            return dict(self._data_shapes)
        return {name: (bucket,) + shape[1:]
                for name, shape in self._data_shapes.items()}

    def _build(self, bucket):
        shapes = self._bucket_shapes(bucket)
        arg_shapes, _, aux_shapes = self._sym.infer_shape(**shapes)
        extra_shapes = {
            n: tuple(s) for n, s in
            list(zip(self._sym.list_arguments(), arg_shapes))
            + list(zip(self._sym.list_auxiliary_states(), aux_shapes))
            if n in set(self._extra_names)}
        plan = self._plan
        nodes, node_ids, entries = plan.nodes, plan.node_ids, plan.entries
        dyn, const_index, key = plan.dyn, plan.const_index, self._key
        cast_back = self._np_dtype != np.float32

        def run(data_vals, consts):
            zeros = {n: jnp.zeros(s, jnp.float32)
                     for n, s in extra_shapes.items()}
            results = {}

            def val(entry):
                inp, idx = entry
                if inp.is_variable():
                    name = inp.name
                    if name in data_vals:
                        return data_vals[name]
                    if name in zeros:
                        return zeros[name]
                    return consts[const_index[("var", name)]]
                nid = node_ids[id(inp)]
                if nid in dyn:
                    return results[nid][idx]
                return consts[const_index[("node", nid, idx)]]

            for i, node in enumerate(nodes):
                if node.is_variable() or i not in dyn:
                    continue
                ins = [val(e) for e in node.inputs]
                results[i] = eval_node(node, ins, key, i, False)
            outs = [val(e) for e in entries]
            if cast_back:
                outs = [o.astype(jnp.float32)
                        if jnp.issubdtype(o.dtype, jnp.floating) else o
                        for o in outs]
            return outs

        # donation lets XLA reuse the request buffer's HBM for
        # activations/outputs; the CPU test backend can't honor it (and
        # warns per executable), so only ask where it means something
        if self._device is not None:
            platform = self._device.platform
        elif self._mesh is not None:
            platform = self._mesh.devices.flat[0].platform
        else:
            platform = jax.default_backend()
        donate = (0,) if platform != "cpu" else ()
        return jax.jit(run, donate_argnums=donate)

    def _executable(self, bucket):
        cache_key = (self._cache_key, bucket if bucket is not None
                     else "exact", self._dtype_name,
                     self._quant_fingerprint)
        return self._cache.get_or_build(cache_key,
                                        lambda: self._build(bucket))

    # -- request surface ----------------------------------------------------
    @property
    def ladder(self):
        return self._ladder

    @property
    def max_bucket(self):
        return self._ladder[-1] if self._ladder else None

    @property
    def data_names(self):
        return list(self._data_names)

    @property
    def output_names(self):
        return self._sym.list_outputs()

    @property
    def num_outputs(self):
        return len(self._plan.entries)

    def pick_bucket(self, rows):
        """Smallest ladder bucket >= rows (bucket selection)."""
        if self._ladder is None:
            raise ServingError("predictor was bound at exact shapes "
                               "(ladder=None); no bucket ladder exists")
        rows = int(rows)
        if rows < 1:
            raise ServingError("request needs >= 1 row, got %d" % rows)
        for b in self._ladder:
            if b >= rows:
                return b
        raise ServingError(
            "request of %d rows exceeds the largest batch bucket %d "
            "(MXNET_SERVE_BATCH_LADDER)" % (rows, self._ladder[-1]))

    def _cast_input(self, v):
        v = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
        if np.issubdtype(v.dtype, np.floating) \
                and v.dtype != self._np_dtype:
            v = v.astype(self._np_dtype)
        return v

    def _normalize(self, inputs):
        if not isinstance(inputs, dict):
            if len(self._data_names) != 1:
                raise ServingError(
                    "model has inputs %s: pass a {name: array} dict"
                    % self._data_names)
            inputs = {self._data_names[0]: inputs}
        unknown = sorted(set(inputs) - set(self._data_names))
        missing = sorted(set(self._data_names) - set(inputs))
        if unknown or missing:
            raise ServingError(
                "bad request inputs: unknown %s, missing %s (model "
                "inputs: %s)" % (unknown, missing, self._data_names))
        out, rows = {}, None
        for name in self._data_names:
            v = self._cast_input(inputs[name])
            want = self._data_shapes[name]
            if v.ndim != len(want) or tuple(v.shape[1:]) != tuple(want[1:]):
                raise ServingError(
                    "input %r has shape %s, expected (n,%s)"
                    % (name, tuple(v.shape),
                       ",".join(str(d) for d in want[1:])))
            if rows is None:
                rows = int(v.shape[0])
            elif int(v.shape[0]) != rows:
                raise ServingError(
                    "inputs disagree on the batch dim (%d vs %d rows)"
                    % (rows, int(v.shape[0])))
            out[name] = v
        return out, rows

    def run_bucket(self, inputs, bucket):
        """Run one already-assembled batch of EXACTLY ``bucket`` rows
        (or the exact bound shapes when ``bucket is None``); returns the
        outputs as host numpy arrays, unsliced. The broker assembles
        padded buckets and slices per request; :meth:`predict` wraps
        this for the single-request path."""
        fn = self._executable(bucket)
        with self._lock:
            consts = self._consts
        data = dict(inputs)
        if self._mesh is not None:
            # sharded bind: the request batch is replicated across the
            # group so every chip sees the full batch and GSPMD only
            # communicates over the weight shards (megatron-style)
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(self._mesh, PartitionSpec())
            data = {k: jax.device_put(jnp.asarray(v), rep)
                    for k, v in data.items()}
        outs = fn(data, consts)
        return [np.asarray(o) for o in outs]

    def predict(self, inputs):
        """Synchronous single-request forward: pads up to the nearest
        bucket, runs, slices the pad away. Returns a list of numpy
        outputs (one per symbol output) with the request's row count."""
        inputs, rows = self._normalize(inputs)
        if self._ladder is None:
            for name, v in inputs.items():
                if tuple(v.shape) != self._data_shapes[name]:
                    raise ServingError(
                        "input %r has shape %s; exact-bound predictor "
                        "expects %s" % (name, tuple(v.shape),
                                        self._data_shapes[name]))
            return self.run_bucket(inputs, None)
        bucket = self.pick_bucket(rows)
        padded = {}
        for name, v in inputs.items():
            if rows == bucket:
                padded[name] = v
            else:
                buf = np.zeros((bucket,) + v.shape[1:], dtype=v.dtype)
                buf[:rows] = v
                padded[name] = buf
        outs = self.run_bucket(padded, bucket)
        return [o[:rows] if o.ndim and o.shape[0] == bucket else o
                for o in outs]

    def sharded_stats(self):
        """Measured per-chip footprint of the frozen constants on a
        mesh bind (ISSUE 20): for the first mesh device, sum the bytes
        of each constant's shard actually resident there — a row- or
        column-sharded weight contributes 1/mp of itself, a replicated
        one contributes whole. Records the measurement into the
        profiler's ``mpStats`` gauge group and returns it. Raises on a
        single-device bind, where nothing is sharded."""
        if self._mesh is None:
            raise ServingError(
                "sharded_stats: predictor was not bound on a mesh "
                "(pass mesh= to the constructor)")
        dev0 = self._mesh.devices.flat[0]
        total = per_chip = 0
        with self._lock:
            consts = self._consts
        for arr in consts:
            if not hasattr(arr, "addressable_shards"):
                continue
            total += int(arr.nbytes)
            for sh in arr.addressable_shards:
                if sh.device == dev0:
                    per_chip += int(sh.data.nbytes)
        mp = int(dict(self._mesh.shape).get(
            "mp", dict(self._mesh.shape).get("tp", 1)))
        from .. import profiler

        profiler.mp_record(group_size=self._group_size, mp_size=mp,
                           param_bytes_per_chip=per_chip)
        return {"group_size": self._group_size, "mp_size": mp,
                "param_bytes_total": total,
                "param_bytes_per_chip": per_chip}

    # -- hot swap ------------------------------------------------------------
    def swap_params(self, arg_params=None, aux_params=None,
                    allow_extra=False):
        """Atomically replace (a subset of) the frozen weights:
        refreeze, re-run the fold program, publish the new constant set
        in one assignment. Shapes must match the bound ones — cached
        executables stay valid, so a swap never recompiles and requests
        racing the swap see either the old or the new model, never a
        mix."""
        known = set(self._weight_names) | set(self._bound_aux)
        updates = {}
        for d in (arg_params, aux_params):
            for name, value in (d or {}).items():
                if name not in known:
                    if allow_extra:
                        continue
                    raise ServingError(
                        "swap_params: %r is not a frozen parameter of "
                        "this predictor (use allow_extra=True to skip "
                        "unknown names)" % name)
                updates[name] = value
        if not updates:
            raise ServingError("swap_params: no parameters to swap")
        with self._lock:
            base = dict(self._params)
        for name, value in updates.items():
            arr = self._freeze_one(name, value)
            if tuple(arr.shape) != tuple(base[name].shape):
                raise ServingError(
                    "swap_params: %r has shape %s, bound shape is %s"
                    % (name, tuple(arr.shape), tuple(base[name].shape)))
            base[name] = arr
        consts = self._fold_fn(base)
        with self._lock:
            self._params = base
            self._consts = consts
        return len(updates)

    @classmethod
    def from_checkpoint(cls, prefix, epoch, data_shapes, **kwargs):
        """Bind from the two-artifact checkpoint format
        (``prefix-symbol.json`` + ``prefix-%04d.params``)."""
        from ..model import load_checkpoint

        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return cls(symbol, arg_params, aux_params,
                   data_shapes=data_shapes, **kwargs)
