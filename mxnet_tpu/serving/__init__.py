"""Serving tier (ISSUE 6): AOT-compiled predictor + dynamic-batching
async server for heavy online traffic.

Reference counterpart: the dedicated inference ABI the reference ships
as ``c_predict_api`` (PAPER.md §layer 8) — grown here into a full
serving subsystem: bind-time constant folding and weight layout
freezing (Relay, nncase), a batch-size ladder of donated-buffer jitted
forwards, a drain-and-coalesce request broker with backpressure,
multi-model residency behind one compiled-executable LRU, and
zero-drop checkpoint hot-swap. ``mxnet_tpu/c_predict.py`` (the C ABI
backend) binds through the same :class:`AOTPredictor` path.
"""
from .predictor import (  # noqa: F401
    AOTPredictor,
    DEFAULT_LADDER,
    ExecutableCache,
    ServingError,
    env_batch_ladder,
    validate_ladder,
)
from .broker import DeadlineExceeded, ModelServer  # noqa: F401
