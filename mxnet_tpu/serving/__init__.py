"""Serving tier (ISSUE 6): AOT-compiled predictor + dynamic-batching
async server for heavy online traffic.

Reference counterpart: the dedicated inference ABI the reference ships
as ``c_predict_api`` (PAPER.md §layer 8) — grown here into a full
serving subsystem: bind-time constant folding and weight layout
freezing (Relay, nncase), a batch-size ladder of donated-buffer jitted
forwards, a drain-and-coalesce request broker with backpressure,
multi-model residency behind one compiled-executable LRU, and
zero-drop checkpoint hot-swap. ``mxnet_tpu/c_predict.py`` (the C ABI
backend) binds through the same :class:`AOTPredictor` path.

The fleet tier (ISSUE 11, ``fleet.py``) scales this to N replica
processes: tracker-discovered :class:`ReplicaServer` endpoints, a
:class:`FleetRouter` with failure-classified bounded retry, typed
health-driven draining, and zero-drop rolling checkpoint swap.

The elastic tier (ISSUE 18, ``autoscale.py`` + ``qos.py``) makes the
fleet self-regulating: a :class:`FleetAutoscaler` controller that
grows/shrinks the replica set from tracker-published load signals
(fail-static when it dies — the fleet keeps serving at its current
size), and a :class:`QosPolicy` of per-tenant admission quotas and
priority classes so bulk traffic sheds before a latency tenant's p99
moves.

The generative tier (ISSUE 12, ``generate.py`` + ``broker.py``) opens
the autoregressive LLM decoding workload: KV-cache incremental decode
(prefill + single-token steps against a PAGED per-layer cache,
models/transformer.py), an exact-accounting :class:`PagePool` that
recycles a finished request's memory immediately, and a
:class:`GenerateServer` whose continuous-batching decode loop admits
new requests into vacated batch slots every step instead of draining
whole batches.
"""
from .predictor import (  # noqa: F401
    AOTPredictor,
    DEFAULT_LADDER,
    ExecutableCache,
    ServingError,
    env_batch_ladder,
    validate_ladder,
)
from .broker import (  # noqa: F401
    DeadlineExceeded,
    GenerateServer,
    ModelServer,
    ReplicaDraining,
    ServerClosed,
    ServerOverloaded,
)
from .generate import (  # noqa: F401
    GenerateError,
    GenerativePredictor,
    PagePool,
    PagePoolExhausted,
    PrefixIndex,
)
from .fleet import (  # noqa: F401
    FleetError,
    FleetOverloaded,
    FleetRemoteError,
    FleetRouter,
    NoLiveReplica,
    ReplicaConnectionLost,
    ReplicaServer,
)
from .qos import (  # noqa: F401
    QosPolicy,
    TenantQuotaExceeded,
    TokenBucket,
)
from .autoscale import (  # noqa: F401
    AutoscaleError,
    FleetAutoscaler,
)
