"""Paged KV-cache machinery for generative serving (ISSUE 12).

The workload nncase targets (PAPERS.md, arXiv:2512.21571) —
autoregressive LLM decoding — differs structurally from the one-shot
forwards the serving tier batched so far: every request carries
device-resident state (its KV cache), sequence lengths vary wildly, and
requests finish at different decode steps. Two pieces live here; the
continuous-batching decode loop (:class:`~.broker.GenerateServer`) owns
them from ``serving/broker.py``:

- :class:`PagePool` — an exact-accounting fixed-size-block allocator
  for KV-cache memory (vLLM's PagedAttention idea): a finished
  request's pages are recycled the moment it completes instead of
  pinning ``max_seq_len`` per batch slot. Exhaustion raises the typed
  :class:`PagePoolExhausted` — backpressure, never an OOM or a silent
  stall — and the accounting is asserted leak-free in tests.
- :class:`GenerativePredictor` — one transformer bound for incremental
  decode: a ladder of prefill programs (prompt padded to page-aligned
  power-of-two buckets, the PR 6 ladder idea) that fill per-layer K/V
  pages, plus ONE decode program (``slots`` queries, 1 token each)
  that attends against the pages named by each slot's block table.
  The big cache buffer is donated to every call on accelerators (the
  PR 6 donation rule: skipped on CPU where it only warns), compiled
  programs share the serving tier's :class:`ExecutableCache`, and the
  decode attention's ``block_k`` is consulted from the PR 10 schedule
  table at trace time (``tools/tune_kernels.py`` sweeps the
  decode shape).

Page 0 of the cache is the scratch page: never handed out, it absorbs
writes from inactive slots and padded prompt tails so the compiled
programs stay shape-static without ever corrupting live pages.
"""
from __future__ import annotations

import threading

import numpy as np

from .. import config
from ..base import MXNetError
from .predictor import ExecutableCache, ServingError


class GenerateError(ServingError):
    """Generative-serving failure (bad knob, bad request, dead loop)."""


class PagePoolExhausted(GenerateError):
    """The KV page pool has no free page for this allocation. Typed
    backpressure: at admission the request simply waits in the queue
    for completions to recycle pages; a request that could NEVER fit
    (or a mid-decode growth the pool cannot serve) fails fast with
    this error instead of stalling silently or OOMing the device."""


def _env_positive_int(name):
    if config.get(name) is None:
        raise GenerateError("unknown knob %s" % name)
    try:
        return config.get_positive_int(name)
    except MXNetError as e:
        raise GenerateError(str(e))


def _env_nonneg_int(name):
    try:
        return config.get_nonneg_int(name)
    except MXNetError as e:
        raise GenerateError(str(e))


def _env_strict_bool(name):
    try:
        return config.get_strict_bool(name)
    except MXNetError as e:
        raise GenerateError(str(e))


class PagePool:
    """Fixed-size-block allocator with exact accounting and per-page
    refcounts (ISSUE 16: copy-on-write prefix sharing).

    Page ids run 1..num_pages (0 is the cache's scratch page). ``alloc``
    raises :class:`PagePoolExhausted` when the request cannot be
    satisfied — it never partially allocates — and hands each page out
    at refcount 1. ``ref`` takes an extra reference on a live page (a
    second request sharing a cached prefix page, or the prefix index
    pinning one); ``unref`` drops one reference and only returns the
    page to the free list when the count reaches zero. ``free`` is the
    historical alias for ``unref``. Both reject double-drops and
    foreign ids loudly: a page leak (or double recycle) silently
    corrupts another request's KV state, so the accounting must be
    exact by construction — after every holder drops its reference,
    ``in_use == 0`` and ``allocs == frees`` (pages handed out == pages
    returned), asserted by the torture test."""

    def __init__(self, num_pages):
        num_pages = int(num_pages)
        if num_pages < 1:
            raise GenerateError("PagePool: need >= 1 page, got %d"
                                % num_pages)
        self.num_pages = num_pages
        self._free = list(range(num_pages, 0, -1))  # pop() hands out 1 first
        self._refcount = {}                         # page id -> live refs
        self._lock = threading.Lock()
        self.high_water = 0
        self.allocs = 0
        self.frees = 0
        self.refs = 0              # extra references taken (sharing events)
        self.ref_high_water = 0    # max refcount any single page reached

    def alloc(self, n):
        """n pages as a list of ids, or PagePoolExhausted (all-or-nothing).
        Each page comes out at refcount 1, owned by the caller."""
        n = int(n)
        if n < 0:
            raise GenerateError("PagePool.alloc: n must be >= 0, got %d" % n)
        with self._lock:
            if n > len(self._free):
                raise PagePoolExhausted(
                    "page pool exhausted: need %d page(s), %d free of %d "
                    "(MXNET_GENERATE_POOL_BYTES)"
                    % (n, len(self._free), self.num_pages))
            pages = [self._free.pop() for _ in range(n)]
            for p in pages:
                self._refcount[p] = 1
            self.allocs += n
            if len(self._refcount) > self.high_water:
                self.high_water = len(self._refcount)
            if n and self.ref_high_water < 1:
                self.ref_high_water = 1
            return pages

    def ref(self, pages):
        """Take one extra reference on each (live) page — sharing, not
        allocation: no free page is consumed. Foreign ids raise."""
        with self._lock:
            for p in pages:
                if p not in self._refcount:
                    raise GenerateError(
                        "PagePool.ref: page %r is not allocated "
                        "(cannot share a free or foreign page)" % (p,))
            for p in pages:
                rc = self._refcount[p] + 1
                self._refcount[p] = rc
                self.refs += 1
                if rc > self.ref_high_water:
                    self.ref_high_water = rc

    def unref(self, pages):
        """Drop one reference per page; a page whose count reaches zero
        returns to the free list. Double-drops and foreign ids raise."""
        with self._lock:
            for p in pages:
                if p not in self._refcount:
                    raise GenerateError(
                        "PagePool.free: page %r is not allocated "
                        "(double free or foreign id)" % (p,))
            for p in pages:
                rc = self._refcount[p] - 1
                if rc:
                    self._refcount[p] = rc
                else:
                    del self._refcount[p]
                    self._free.append(p)
                    self.frees += 1

    def free(self, pages):
        """Alias of :meth:`unref` (the pre-sharing name every holder —
        broker slot vacate, tests — already uses)."""
        self.unref(pages)

    def refcount(self, page):
        """Current reference count of ``page`` (0 when free)."""
        with self._lock:
            return self._refcount.get(page, 0)

    @property
    def in_use(self):
        with self._lock:
            return len(self._refcount)

    @property
    def free_pages(self):
        with self._lock:
            return len(self._free)

    def stats(self):
        with self._lock:
            shared = sum(1 for rc in self._refcount.values() if rc > 1)
            return {"num_pages": self.num_pages,
                    "in_use": len(self._refcount),
                    "free": len(self._free),
                    "high_water": self.high_water,
                    "allocs": self.allocs, "frees": self.frees,
                    "refs": self.refs, "shared": shared,
                    "ref_high_water": self.ref_high_water}


class _PrefixNode:
    __slots__ = ("page", "children", "last_used")

    def __init__(self, page, clock):
        self.page = page
        self.children = {}
        self.last_used = clock


class PrefixIndex:
    """Radix-tree index over full KV pages keyed by token-id page runs
    (ISSUE 16 prefix sharing).

    Each node maps one ``page_size``-token run to the pool page holding
    that run's K/V; a path from the root spells out a prompt prefix in
    whole pages. The index itself holds ONE pool reference per indexed
    page (taken at :meth:`insert`, dropped at eviction), so an indexed
    page stays alive after the request that prefilled it finishes —
    that reference is what turns a private page into a shareable one.

    - :meth:`match` walks the longest indexed prefix of a prompt,
      capped at ``(prompt_len - 1) // page_size`` pages so the tail
      prefill always has >= 1 token — the structural form of the
      copy-on-write rule: a partial (or final) page is always
      re-prefilled privately, never shared, hence shared pages are
      never written. Matched pages are ref'd on the caller's behalf
      (the caller unrefs them exactly once, same as its private pages).
    - :meth:`insert` indexes a just-prefilled prompt's full pages,
      taking an extra reference on each newly indexed page; runs
      already indexed are only LRU-touched (the request keeps its
      private duplicate — dedup happens for FUTURE requests via match).
    - :meth:`evict_lru` drops the least-recently-matched leaf —
      called under pool pressure so sharing never causes a
      :class:`PagePoolExhausted` a no-sharing run would avoid, and to
      keep the index under ``max_pages`` when one is set.
    """

    def __init__(self, page_size, max_pages=0):
        self.page_size = int(page_size)
        if self.page_size < 1:
            raise GenerateError("PrefixIndex: page_size must be >= 1, "
                                "got %d" % self.page_size)
        self.max_pages = int(max_pages or 0)
        self._root = {}
        self._clock = 0
        self._pages = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.insertions = 0
        self.evictions = 0

    def _runs(self, tokens, n):
        ps = self.page_size
        return [tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])
                for i in range(n)]

    def match(self, tokens, pool):
        """Longest indexed full-page prefix of ``tokens`` — at most
        ``(len(tokens) - 1) // page_size`` pages (see class docstring).
        Returns the page-id list with one reference per page taken on
        ``pool`` for the caller; the whole path is LRU-touched."""
        limit = max(0, (len(tokens) - 1) // self.page_size)
        pages = []
        with self._lock:
            self._clock += 1
            node_map, touched = self._root, []
            for run in self._runs(tokens, limit):
                node = node_map.get(run)
                if node is None:
                    break
                touched.append(node)
                pages.append(node.page)
                node_map = node.children
            for node in touched:
                node.last_used = self._clock
            if pages:
                pool.ref(pages)
                self.hits += 1
            else:
                self.misses += 1
        return pages

    def insert(self, tokens, pages, pool):
        """Index the full pages of a just-prefilled prompt: run i →
        ``pages[i]``. Only runs fully covered by the prompt are indexed
        (``len(tokens) // page_size`` of them — a final page that the
        decode loop will keep writing is still mutable and stays
        private). Newly indexed pages cost one extra pool reference;
        existing runs keep their already-indexed page. Returns the
        number of pages newly indexed."""
        n = min(len(tokens) // self.page_size, len(pages))
        added = 0
        with self._lock:
            self._clock += 1
            node_map = self._root
            for i, run in enumerate(self._runs(tokens, n)):
                node = node_map.get(run)
                if node is None:
                    pool.ref([pages[i]])
                    node = _PrefixNode(pages[i], self._clock)
                    node_map[run] = node
                    self._pages += 1
                    self.insertions += 1
                    added += 1
                else:
                    node.last_used = self._clock
                node_map = node.children
        if self.max_pages:
            while self.pages > self.max_pages:
                if not self.evict_lru(pool):
                    break
        return added

    def evict_lru(self, pool):
        """Drop the least-recently-matched LEAF node (leaves first so a
        prefix chain stays contiguous) and release the index's
        reference on its page — the page only becomes free once no
        live request shares it. Returns True when a node was evicted,
        False on an empty index."""
        with self._lock:
            victim = None          # (last_used, parent_map, run, node)
            stack = [(self._root, run, node)
                     for run, node in self._root.items()]
            while stack:
                parent, run, node = stack.pop()
                if node.children:
                    stack.extend((node.children, r, ch)
                                 for r, ch in node.children.items())
                elif victim is None or node.last_used < victim[0]:
                    victim = (node.last_used, parent, run, node)
            if victim is None:
                return False
            _, parent, run, node = victim
            del parent[run]
            self._pages -= 1
            self.evictions += 1
            page = node.page
        pool.unref([page])
        return True

    def clear(self, pool):
        """Evict everything (release every index reference)."""
        while self.evict_lru(pool):
            pass

    @property
    def pages(self):
        with self._lock:
            return self._pages

    def stats(self):
        with self._lock:
            return {"pages": self._pages, "hits": self.hits,
                    "misses": self.misses, "insertions": self.insertions,
                    "evictions": self.evictions,
                    "max_pages": self.max_pages}


class GenerativePredictor:
    """One transformer bound for prefill + single-token decode.

    Parameters
    ----------
    config_ : models.transformer.TransformerConfig
        The model architecture (``dtype`` is the cache/compute dtype).
    params : dict
        ``init_params``-layout arrays (numpy or jax); frozen onto the
        device once.
    slots : int, optional
        Batch-slot count of the decode program
        (``MXNET_GENERATE_SLOTS``).
    page_size : int, optional
        Tokens per KV page (``MXNET_GENERATE_PAGE_SIZE``).
    pool_bytes : int, optional
        KV page-pool budget in bytes (``MXNET_GENERATE_POOL_BYTES``);
        0/None auto-sizes to ``slots * max_pages_per_slot`` pages —
        every slot can hold a full-context request, so decode-time
        exhaustion is impossible and paging only buys recycling speed.
        A smaller explicit budget oversubscribes: admission
        backpressures on :class:`PagePoolExhausted`.
    max_ctx : int, optional
        Per-slot context bound (prompt + generated), default
        ``config.max_len``; rounded down to a whole page count.
    block_k : int, optional
        Decode attention chunk override; default consults the schedule
        table at :func:`models.transformer.decode_schedule_shape`.
    cache : ExecutableCache, optional
        Shared compiled-program LRU (the serving tier's); private
        unbounded cache by default.
    mesh : jax.sharding.Mesh, optional
        Bind the model SHARDED across a replica group (ISSUE 20):
        weights placed per ``models.transformer.param_specs`` (megatron
        column/row over the mesh's ``mp``/``tp`` axis) and the paged KV
        cache sharded over its heads axis (``kv_cache_spec``) so every
        chip holds 1/mp of every page. Mutually exclusive with
        ``device``; the pure-jnp prefill/decode/extend programs are
        GSPMD-partitioned automatically.
    """

    def __init__(self, config_, params, *, slots=None, page_size=None,
                 pool_bytes=None, max_ctx=None, block_k=None, device=None,
                 cache=None, model_name=None, mesh=None):
        import jax
        import jax.numpy as jnp

        from ..models import transformer as tfm

        self.config = config_
        self.slots = _env_positive_int("MXNET_GENERATE_SLOTS") \
            if slots is None else int(slots)
        if self.slots < 1:
            raise GenerateError("GenerativePredictor: slots must be >= 1, "
                                "got %d" % self.slots)
        self.page_size = _env_positive_int("MXNET_GENERATE_PAGE_SIZE") \
            if page_size is None else int(page_size)
        if self.page_size < 1:
            raise GenerateError("GenerativePredictor: page_size must be "
                                ">= 1, got %d" % self.page_size)
        ctx_bound = config_.max_len if max_ctx is None \
            else min(int(max_ctx), config_.max_len)
        self.max_pages_per_slot = ctx_bound // self.page_size
        if self.max_pages_per_slot < 1:
            raise GenerateError(
                "GenerativePredictor: page_size %d exceeds the context "
                "bound %d" % (self.page_size, ctx_bound))
        self.max_ctx = self.max_pages_per_slot * self.page_size

        c = config_
        dh = c.d_model // c.n_heads
        cdt = jnp.dtype(c.dtype)
        self.page_bytes = (c.n_layers * 2 * self.page_size * c.n_heads * dh
                           * cdt.itemsize)
        if pool_bytes is None:
            pool_bytes = _env_nonneg_int("MXNET_GENERATE_POOL_BYTES")
        pool_bytes = int(pool_bytes or 0)
        if pool_bytes > 0:
            num_pages = pool_bytes // self.page_bytes
            if num_pages < self.max_pages_per_slot:
                raise GenerateError(
                    "MXNET_GENERATE_POOL_BYTES=%d holds %d page(s) of %d "
                    "bytes — smaller than one full-context request "
                    "(%d pages); raise the budget or shrink max_ctx/"
                    "page_size" % (pool_bytes, num_pages, self.page_bytes,
                                   self.max_pages_per_slot))
        else:
            num_pages = self.slots * self.max_pages_per_slot
        self.pool = PagePool(num_pages)

        if device is not None and hasattr(device, "jax_device"):
            device = device.jax_device()
        if mesh is not None and device is not None:
            raise GenerateError(
                "GenerativePredictor: pass mesh= OR device=, not both "
                "(a sharded bind owns the whole group's placement)")
        self._device = device
        self._mesh = mesh
        self._group_size = int(mesh.devices.size) if mesh is not None else 1
        if device is not None:
            platform = device.platform
        elif mesh is not None:
            platform = mesh.devices.flat[0].platform
        else:
            platform = jax.default_backend()
        self._donate = platform != "cpu"
        self._exec_cache = cache if cache is not None \
            else ExecutableCache(None)
        self._cache_key = model_name if model_name is not None \
            else "gen-%d" % id(self)
        self._dtype_name = str(cdt)

        if mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            pspecs = tfm.param_specs(c, mesh)

            def put(a, spec=None):
                return jax.device_put(
                    jnp.asarray(np.asarray(a)),
                    NamedSharding(mesh, spec if spec is not None else P()))

            self._params = {k: put(v, pspecs.get(k))
                            for k, v in params.items()}
            self._kv = put(tfm.init_kv_cache(c, num_pages, self.page_size),
                           tfm.kv_cache_spec(mesh))
        else:
            def put(a):
                a = jnp.asarray(np.asarray(a))
                return jax.device_put(a, device) if device is not None else a

            self._params = {k: put(v) for k, v in params.items()}
            self._kv = put(tfm.init_kv_cache(c, num_pages, self.page_size))
        self.block_k = int(block_k) if block_k is not None \
            else tfm._decode_block_k(c, self.slots, self.max_ctx)

        # prefill bucket ladder: page-aligned powers of two up to the
        # context bound (the PR 6 ladder idea at page granularity)
        buckets, b = [], self.page_size
        while b < self.max_ctx:
            buckets.append(b)
            b *= 2
        buckets.append(self.max_ctx)
        self.prefill_buckets = tuple(buckets)
        self._lock = threading.Lock()

    # -- compiled programs ---------------------------------------------------
    def _jit(self, fn):
        import jax

        return jax.jit(fn, donate_argnums=(1,) if self._donate else ())

    def _config_fingerprint(self):
        """Everything a compiled program's closure bakes in besides the
        bucket/slot tag: the model architecture and the page geometry.
        Part of every cache key so two predictors sharing one
        ExecutableCache under the same model name can never reuse each
        other's programs."""
        import dataclasses

        return (tuple(sorted(dataclasses.asdict(self.config).items())),
                self.page_size, self.max_pages_per_slot, self.block_k)

    def _prefill_exec(self, bucket):
        from ..models import transformer as tfm

        key = (self._cache_key, ("prefill", bucket),
               self._config_fingerprint(), self._dtype_name)
        return self._exec_cache.get_or_build(
            key, lambda: self._jit(tfm.make_prefill_fn(self.config,
                                                       self.page_size)))

    def _decode_exec(self):
        from ..models import transformer as tfm

        key = (self._cache_key, ("decode", self.slots),
               self._config_fingerprint(), self._dtype_name)
        return self._exec_cache.get_or_build(
            key, lambda: self._jit(tfm.make_decode_fn(
                self.config, self.slots, self.max_pages_per_slot,
                self.page_size, block_k=self.block_k)))

    def _extend_exec(self, batch, steps):
        from ..models import transformer as tfm

        key = (self._cache_key, ("extend", batch, steps),
               self._config_fingerprint(), self._dtype_name)
        return self._exec_cache.get_or_build(
            key, lambda: self._jit(tfm.make_extend_fn(
                self.config, batch, steps, self.max_pages_per_slot,
                self.page_size, block_k=self.block_k)))

    # -- request surface -----------------------------------------------------
    def pages_needed(self, prompt_len):
        return -(-int(prompt_len) // self.page_size)

    def pick_bucket(self, prompt_len):
        for b in self.prefill_buckets:
            if b >= prompt_len:
                return b
        raise GenerateError(
            "prompt of %d tokens exceeds the per-slot context bound %d"
            % (prompt_len, self.max_ctx))

    def prefill(self, tokens, pages):
        """Run one prompt (1-D int array) through the prefill program,
        scattering K/V into ``pages`` (ids from :attr:`pool`); returns
        the last position's logits as numpy (V,)."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        length = int(tokens.shape[0])
        if length < 1:
            raise GenerateError("prefill: empty prompt")
        if self.pages_needed(length) != len(pages):
            raise GenerateError(
                "prefill: %d-token prompt needs %d page(s), got %d"
                % (length, self.pages_needed(length), len(pages)))
        bucket = self.pick_bucket(length)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :length] = tokens
        page_arr = np.zeros((bucket // self.page_size,), np.int32)
        page_arr[:len(pages)] = pages   # tail pages hit scratch (0)
        fn = self._prefill_exec(bucket)
        with self._lock:
            self._kv, logits = fn(self._params, self._kv, padded,
                                  np.int32(length), page_arr)
        return np.asarray(logits)

    def decode(self, tokens, positions, block_tables, active):
        """One decode step over all ``slots``; returns numpy logits
        (slots, V). ``tokens[b]`` is written at ``positions[b]`` into
        the page its slot's ``block_tables`` row names; inactive slots
        write to scratch and return zero logits."""
        fn = self._decode_exec()
        with self._lock:
            self._kv, logits = fn(
                self._params, self._kv,
                np.asarray(tokens, np.int32),
                np.asarray(positions, np.int32),
                np.asarray(block_tables, np.int32),
                np.asarray(active, bool))
        return np.asarray(logits)

    def extend(self, tokens, positions, block_tables, valid):
        """Multi-token append (ISSUE 16): run ``tokens`` (S, T) at
        ``positions`` (S, T) against each slot's cached pages in one
        compiled call; returns numpy logits (S, T, V). Invalid entries
        write to scratch and return zero logits. Serves both the
        shared-prefix tail prefill (S = 1, T = a prefill bucket) and
        the speculative verify step (S = slots, T = k + 1)."""
        tokens = np.asarray(tokens, np.int32)
        if tokens.ndim != 2:
            raise GenerateError("extend: tokens must be (batch, steps), "
                                "got shape %r" % (tokens.shape,))
        S, T = tokens.shape
        fn = self._extend_exec(S, T)
        with self._lock:
            self._kv, logits = fn(
                self._params, self._kv, tokens,
                np.asarray(positions, np.int32),
                np.asarray(block_tables, np.int32),
                np.asarray(valid, bool))
        return np.asarray(logits)

    def extend_tail(self, tokens, start_pos, pages):
        """Prefill the uncovered TAIL of a prefix-matched prompt:
        ``tokens`` (the tail, 1-D) start at absolute position
        ``start_pos`` and attend the full block table ``pages``
        (shared prefix pages + the request's private tail pages).
        Tail length is padded up the same prefill bucket ladder.
        Returns the last tail position's logits as numpy (V,) — the
        request's first generated token, same contract as
        :meth:`prefill`. Every tail position lies at or past
        ``start_pos`` >= the shared region, so shared pages are never
        written (copy-on-write by construction)."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = int(tokens.shape[0])
        if n < 1:
            raise GenerateError("extend_tail: empty tail")
        if start_pos % self.page_size != 0:
            raise GenerateError(
                "extend_tail: start_pos %d is not page-aligned (the "
                "shared prefix covers whole pages)" % start_pos)
        if start_pos + n > self.max_ctx:
            raise GenerateError(
                "extend_tail: tail of %d token(s) at position %d exceeds "
                "the per-slot context bound %d" % (n, start_pos,
                                                  self.max_ctx))
        bucket = self.pick_bucket(n)
        tok = np.zeros((1, bucket), np.int32)
        tok[0, :n] = tokens
        pos = np.arange(start_pos, start_pos + bucket,
                        dtype=np.int32)[None, :]
        valid = np.zeros((1, bucket), bool)
        valid[0, :n] = True
        bt = np.zeros((1, self.max_pages_per_slot), np.int32)
        bt[0, :len(pages)] = pages
        logits = self.extend(tok, pos, bt, valid)
        return logits[0, n - 1]

    def pool_stats(self):
        return self.pool.stats()

    def sharded_stats(self):
        """Measured per-chip bytes of the sharded bind (ISSUE 20):
        params and the paged KV cache, counting only shards resident on
        the first mesh device — the KV pages split over heads, so each
        chip holds ~1/mp of every page. Records into the profiler's
        ``mpStats`` gauge group. Raises on a single-device bind."""
        if self._mesh is None:
            raise GenerateError(
                "sharded_stats: predictor was not bound on a mesh "
                "(pass mesh= to the constructor)")
        dev0 = self._mesh.devices.flat[0]

        def chip_bytes(arr):
            return sum(int(s.data.nbytes) for s in arr.addressable_shards
                       if s.device == dev0)

        with self._lock:
            kv = self._kv
        param_chip = sum(chip_bytes(v) for v in self._params.values())
        kv_chip = chip_bytes(kv)
        mp = int(dict(self._mesh.shape).get(
            "mp", dict(self._mesh.shape).get("tp", 1)))
        from .. import profiler

        profiler.mp_record(group_size=self._group_size, mp_size=mp,
                           param_bytes_per_chip=param_chip,
                           live_bytes_per_chip=param_chip + kv_chip)
        return {"group_size": self._group_size, "mp_size": mp,
                "param_bytes_per_chip": param_chip,
                "kv_bytes_per_chip": kv_chip,
                "kv_bytes_total": int(kv.nbytes)}
