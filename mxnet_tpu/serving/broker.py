"""Dynamic-batching async request broker (the server half of ISSUE 6).

Design: one worker thread per resident model, the PR-4 ``_ShardSender``
drain-and-coalesce pattern turned 90°: clients enqueue single requests
and get a Future back immediately; the worker drains everything queued
up to the model's largest batch bucket into ONE padded forward, then
slices results back per request. Under light load a request rides alone
in the smallest bucket (lowest latency); under heavy load the queue
refills while a batch computes, so the next drain coalesces into the
largest ready bucket (highest throughput) — no artificial batching
delay in either regime.

Bounded queue depth gives backpressure: ``submit`` blocks (up to
``MXNET_SERVE_SUBMIT_TIMEOUT``) while a model's queue holds
``MXNET_SERVE_QUEUE_DEPTH`` requests, then raises. A worker-thread
death is sticky and surfaces on the next submit (the kvstore async
convention). ``close()`` stops and joins every worker with a bounded
deadline (the PR-5 ``PrefetchingIter.close`` lesson: no leaked
daemons) and fails still-queued futures loudly.

Checkpoint hot-swap reuses the PR-3/PR-5 quiesce choreography in
miniature: the swap takes the model's execution lock (waits out the
in-flight batch = drain), refreezes + refolds the weights, and
publishes them in one assignment — queued and future requests are
served by the new model, in-flight ones complete on the old one, and
nothing is dropped.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from .. import profiler
from .predictor import (
    AOTPredictor,
    ExecutableCache,
    ServingError,
    env_batch_ladder,
    env_positive_float,
    env_positive_int,
)


class DeadlineExceeded(ServingError):
    """A request's deadline expired before it was dispatched: it was
    SHED at dequeue (ISSUE 9 overload shedding) instead of occupying a
    batch slot its client had already given up on. Its future fails
    fast with this error."""


class ServerClosed(ServingError):
    """The request was REJECTED (or failed while still queued) because
    the server is shutting down — it never executed, so a fleet router
    may safely resubmit it to a different replica. Distinct from
    :class:`DeadlineExceeded` (the client gave up) and from genuine
    request failures (which must not be retried blindly)."""


class ReplicaDraining(ServerClosed):
    """Admission-time rejection from a replica in the ``draining``
    state (explicit drain RPC or rolling ``fleet_swap``): nothing was
    executed, in-flight work continues to completion, and the router is
    expected to retry the request on a different replica."""


class ServerOverloaded(ServingError):
    """Backpressure rejection: the bounded request queue stayed full
    past the submit timeout. The request never entered the queue, so
    routing it to a less-loaded replica is always safe."""


class _Request:
    __slots__ = ("inputs", "rows", "future", "t_submit", "deadline")

    def __init__(self, inputs, rows, deadline=None):
        self.inputs = inputs
        self.rows = rows
        self.future = Future()
        self.t_submit = time.perf_counter()
        self.deadline = deadline  # absolute time.monotonic(), or None


class _ModelWorker:
    """One model's queue + serving thread (drain-and-coalesce)."""

    def __init__(self, name, predictor, queue_depth):
        self.name = name
        self.predictor = predictor
        self._depth = queue_depth
        self._cond = threading.Condition()
        self._q = deque()
        self._stopped = False
        self._error = None       # sticky worker-death error
        self._busy = False       # a batch is executing right now
        # quiesce lock: held around every batch forward; swap() takes it
        # to wait out the in-flight batch before republishing weights
        self._exec_lock = threading.Lock()
        self._batch_hook = None  # test seam: called before each forward
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="serve-%s" % name)
        self._thread.start()

    # -- producer side -------------------------------------------------------
    def enqueue(self, req, timeout):
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                if self._stopped:
                    if self._error is not None:
                        raise ServingError(
                            "model %r: worker died: %r"
                            % (self.name, self._error))
                    raise ServerClosed(
                        "model %r: worker is stopped" % self.name)
                if len(self._q) < self._depth:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ServerOverloaded(
                        "model %r: request queue full (%d queued, "
                        "MXNET_SERVE_QUEUE_DEPTH=%d) — backpressure "
                        "timeout" % (self.name, len(self._q), self._depth))
                self._cond.wait(min(remaining, 0.1))
            self._q.append(req)
            depth = len(self._q)
            self._cond.notify_all()
        return depth

    # -- worker side ---------------------------------------------------------
    def _drain_locked(self):
        """Pop the largest ready batch: requests in FIFO order while the
        running row total still fits the biggest bucket. Requests whose
        deadline already expired are SHED here — at dequeue, before
        they can occupy a batch slot (their clients have given up; an
        overloaded server must spend its forwards on requests that are
        still wanted). Returns (reqs, rows, shed); reqs may be empty
        when everything queued had expired."""
        cap = self.predictor.max_bucket
        now = time.monotonic()
        shed, reqs, total = [], [], 0
        while self._q:
            r = self._q[0]
            if r.deadline is not None and now > r.deadline:
                shed.append(self._q.popleft())
                continue
            if reqs and total + r.rows > cap:
                break
            reqs.append(self._q.popleft())
            total += r.rows
        return reqs, total, shed

    def _run(self):
        try:
            while True:
                with self._cond:
                    while not self._q and not self._stopped:
                        self._cond.wait()
                    if self._stopped:
                        return
                    reqs, rows, shed = self._drain_locked()
                    if reqs:
                        self._busy = True
                    self._cond.notify_all()  # queue space freed
                if shed:
                    # futures fail OUTSIDE the lock: done-callbacks run
                    # inline on set_exception and must not deadlock a
                    # client that re-submits from one
                    exc = DeadlineExceeded(
                        "model %r: deadline expired before dispatch "
                        "(shed at dequeue)" % self.name)
                    for r in shed:
                        if not r.future.done():
                            r.future.set_exception(exc)
                    profiler.serving_record(self.name, shed=len(shed))
                if not reqs:
                    continue
                try:
                    self._execute(reqs, rows)
                except BaseException as e:  # bad batch — fail ITS futures,
                    for r in reqs:          # keep serving the next ones
                        if not r.future.done():
                            r.future.set_exception(e)
                    profiler.serving_record(self.name, errors=len(reqs))
                finally:
                    with self._cond:
                        self._busy = False
                        self._cond.notify_all()
        except BaseException as e:  # worker death: sticky, fail the queue
            with self._cond:
                self._error = e
                self._stopped = True
                pending = list(self._q)
                self._q.clear()
                self._cond.notify_all()
            for r in pending:
                if not r.future.done():
                    r.future.set_exception(e)

    def _execute(self, reqs, rows):
        pred = self.predictor
        bucket = pred.pick_bucket(rows)
        with self._exec_lock:
            if self._batch_hook is not None:
                self._batch_hook(reqs)
            if len(reqs) == 1 and reqs[0].rows == bucket:
                inputs = reqs[0].inputs  # exact fit: no assembly copy
            else:
                inputs = {}
                for name in pred.data_names:
                    first = reqs[0].inputs[name]
                    buf = np.zeros((bucket,) + first.shape[1:],
                                   dtype=first.dtype)
                    ofs = 0
                    for r in reqs:
                        buf[ofs:ofs + r.rows] = r.inputs[name]
                        ofs += r.rows
                    inputs[name] = buf
            outs = pred.run_bucket(inputs, bucket)
        now = time.perf_counter()
        lats, ofs = [], 0
        for r in reqs:
            res = [o[ofs:ofs + r.rows]
                   if o.ndim and o.shape[0] == bucket else o
                   for o in outs]
            ofs += r.rows
            r.future.set_result(res)
            lats.append(now - r.t_submit)
        profiler.serving_record(self.name, batches=1, rows=rows,
                                capacity=bucket, latencies=lats)

    # -- lifecycle -----------------------------------------------------------
    def stop(self):
        with self._cond:
            self._stopped = True
            self._cond.notify_all()

    def join(self, timeout):
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def fail_pending(self, exc):
        with self._cond:
            pending = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        for r in pending:
            if not r.future.done():
                r.future.set_exception(exc)
        return len(pending)


class ModelServer:
    """Multi-model dynamic-batching inference server.

    ::

        with ModelServer() as srv:
            srv.add_model("resnet", symbol=sym, arg_params=args,
                          aux_params=auxs,
                          data_shapes={"data": (1, 3, 224, 224)})
            fut = srv.submit("resnet", batch_np)   # -> Future
            probs = fut.result()[0]

    All resident models share one LRU of compiled executables
    (``MXNET_SERVE_MAX_EXECUTABLES``) keyed by (model, bucket, dtype);
    evictions recompile on next use, parameters stay resident.
    """

    def __init__(self, ladder=None, queue_depth=None, cache_capacity=None,
                 submit_timeout=None, dtype="float32", device=None):
        from .predictor import validate_ladder

        self._ladder = env_batch_ladder() if ladder is None \
            else validate_ladder(ladder)
        self._queue_depth = env_positive_int(
            "MXNET_SERVE_QUEUE_DEPTH", 256) if queue_depth is None \
            else int(queue_depth)
        if self._queue_depth < 1:
            raise ServingError("ModelServer: queue_depth must be >= 1, "
                               "got %d" % self._queue_depth)
        capacity = env_positive_int("MXNET_SERVE_MAX_EXECUTABLES", 32) \
            if cache_capacity is None else cache_capacity
        self._cache = ExecutableCache(capacity)
        self._submit_timeout = env_positive_float(
            "MXNET_SERVE_SUBMIT_TIMEOUT", 60.0) if submit_timeout is None \
            else float(submit_timeout)
        self._dtype = dtype
        self._device = device
        self._workers = {}
        self._lock = threading.Lock()
        self._closed = False

    # -- model residency -----------------------------------------------------
    def add_model(self, name, symbol=None, arg_params=None, aux_params=None,
                  data_shapes=None, predictor=None, **predictor_kwargs):
        """Make ``name`` resident: either hand in a prebuilt
        :class:`AOTPredictor`, or a symbol + params + data_shapes and
        the server binds one on its shared executable cache."""
        self._check_open()
        if predictor is None:
            if symbol is None or data_shapes is None:
                raise ServingError(
                    "add_model(%r): need either predictor= or "
                    "symbol=/data_shapes= (+params)" % name)
            predictor_kwargs.setdefault("ladder", self._ladder)
            predictor_kwargs.setdefault("dtype", self._dtype)
            predictor_kwargs.setdefault("device", self._device)
            predictor = AOTPredictor(
                symbol, arg_params, aux_params, data_shapes=data_shapes,
                cache=self._cache, model_name=name, **predictor_kwargs)
        if predictor.ladder is None:
            raise ServingError(
                "add_model(%r): exact-bound predictors (ladder=None) "
                "cannot serve coalesced traffic" % name)
        with self._lock:
            if name in self._workers:
                raise ServingError("model %r is already resident; use "
                                   "swap() to update its weights" % name)
            self._workers[name] = _ModelWorker(name, predictor,
                                               self._queue_depth)
        return predictor

    def models(self):
        with self._lock:
            return sorted(self._workers)

    def _worker(self, name):
        with self._lock:
            worker = self._workers.get(name)
        if worker is None:
            raise ServingError("unknown model %r (resident: %s)"
                               % (name, self.models()))
        return worker

    def _check_open(self):
        if self._closed:
            raise ServerClosed("ModelServer is closed")

    # -- request surface -----------------------------------------------------
    def submit(self, name, inputs, timeout=None, deadline=None):
        """Enqueue one request; returns a ``concurrent.futures.Future``
        resolving to the list of output arrays (request row count).
        Blocks for queue space up to ``timeout`` (backpressure), then
        raises :class:`ServingError`. ``deadline`` (seconds from now,
        > 0) marks the request sheddable: if it is still queued when
        the deadline passes, the worker drops it at dequeue and its
        future fails fast with :class:`DeadlineExceeded` instead of
        occupying a batch slot — overload protection for clients that
        time out anyway (counted as ``shed`` in serving_stats)."""
        self._check_open()
        worker = self._worker(name)
        pred = worker.predictor
        inputs, rows = pred._normalize(inputs)
        pred.pick_bucket(rows)  # reject oversized requests in the caller
        if deadline is not None:
            deadline = float(deadline)
            if not deadline > 0:
                raise ServingError("submit: deadline must be > 0 "
                                   "seconds, got %r" % deadline)
            deadline = time.monotonic() + deadline
        req = _Request(inputs, rows, deadline=deadline)
        depth = worker.enqueue(
            req, self._submit_timeout if timeout is None else timeout)
        profiler.serving_record(name, requests=1, queue_depth=depth)
        return req.future

    def predict(self, name, inputs, timeout=None):
        """Synchronous convenience wrapper around :meth:`submit`."""
        return self.submit(name, inputs, timeout=timeout).result()

    # -- hot swap ------------------------------------------------------------
    def swap(self, name, arg_params=None, aux_params=None,
             allow_extra=False):
        """Atomically replace a resident model's weights without
        dropping requests: waits out the in-flight batch (quiesce),
        swaps, releases — queued requests are served by the new model."""
        self._check_open()
        worker = self._worker(name)
        with worker._exec_lock:
            return worker.predictor.swap_params(
                arg_params, aux_params, allow_extra=allow_extra)

    def swap_from_checkpoint(self, name, prefix=None, epoch=None,
                             directory=None):
        """Hot-swap from a checkpoint: either the two-artifact format
        (``prefix``/``epoch``) or the newest committed checkpoint of an
        elastic-training ``CheckpointManager`` ``directory``
        (``CheckpointManager.latest()``)."""
        if (prefix is None) == (directory is None):
            raise ServingError("swap_from_checkpoint: pass exactly one "
                               "of prefix= or directory=")
        if prefix is not None:
            from ..model import load_checkpoint

            _, arg_params, aux_params = load_checkpoint(
                prefix, 0 if epoch is None else int(epoch))
        else:
            from ..checkpoint import CheckpointManager

            ckpt = CheckpointManager(directory).latest()
            if ckpt is None:
                raise ServingError(
                    "swap_from_checkpoint: no committed checkpoint "
                    "under %r" % directory)
            arg_params, aux_params = ckpt.split_weights()
        return self.swap(name, arg_params, aux_params, allow_extra=True)

    # -- observability -------------------------------------------------------
    def stats(self, reset=False):
        """Per-model serving counters (see profiler.serving_stats)."""
        return profiler.serving_stats(reset=reset)

    def pending(self):
        """Queued requests plus in-flight batches across all resident
        models — the drain observable: a draining replica admits
        nothing and waits for this to reach 0 before swapping or
        deregistering (serving/fleet.py)."""
        with self._lock:
            workers = list(self._workers.values())
        total = 0
        for w in workers:
            with w._cond:
                total += len(w._q) + (1 if w._busy else 0)
        return total

    @property
    def executable_cache(self):
        return self._cache

    # -- lifecycle -----------------------------------------------------------
    def close(self, timeout=5.0):
        """Stop and join every worker (bounded — no leaked daemons),
        fail still-queued requests with the typed :class:`ServerClosed`
        (they never executed — a router may retry them elsewhere).
        Idempotent; submits after close raise :class:`ServerClosed`."""
        if self._closed:
            return
        self._closed = True
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            w.stop()
        deadline = time.monotonic() + timeout
        for w in workers:
            w.join(max(0.0, deadline - time.monotonic()))
        exc = ServerClosed("ModelServer closed before the request was "
                           "dispatched")
        for w in workers:
            w.fail_pending(exc)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
