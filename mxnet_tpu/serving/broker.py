"""Dynamic-batching async request broker (the server half of ISSUE 6).

Design: one worker thread per resident model, the PR-4 ``_ShardSender``
drain-and-coalesce pattern turned 90°: clients enqueue single requests
and get a Future back immediately; the worker drains everything queued
up to the model's largest batch bucket into ONE padded forward, then
slices results back per request. Under light load a request rides alone
in the smallest bucket (lowest latency); under heavy load the queue
refills while a batch computes, so the next drain coalesces into the
largest ready bucket (highest throughput) — no artificial batching
delay in either regime.

Bounded queue depth gives backpressure: ``submit`` blocks (up to
``MXNET_SERVE_SUBMIT_TIMEOUT``) while a model's queue holds
``MXNET_SERVE_QUEUE_DEPTH`` requests, then raises. A worker-thread
death is sticky and surfaces on the next submit (the kvstore async
convention). ``close()`` stops and joins every worker with a bounded
deadline (the PR-5 ``PrefetchingIter.close`` lesson: no leaked
daemons) and fails still-queued futures loudly.

Checkpoint hot-swap reuses the PR-3/PR-5 quiesce choreography in
miniature: the swap takes the model's execution lock (waits out the
in-flight batch = drain), refreezes + refolds the weights, and
publishes them in one assignment — queued and future requests are
served by the new model, in-flight ones complete on the old one, and
nothing is dropped.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from .. import chaos, profiler
from .generate import (
    GenerateError,
    GenerativePredictor,
    PagePoolExhausted,
    PrefixIndex,
    _env_nonneg_int,
    _env_strict_bool,
    _env_positive_int,
)
from .predictor import (
    AOTPredictor,
    ExecutableCache,
    ServingError,
    env_batch_ladder,
    env_positive_float,
    env_positive_int,
)


class DeadlineExceeded(ServingError):
    """A request's deadline expired before it was dispatched: it was
    SHED at dequeue (ISSUE 9 overload shedding) instead of occupying a
    batch slot its client had already given up on. Its future fails
    fast with this error."""


class ServerClosed(ServingError):
    """The request was REJECTED (or failed while still queued) because
    the server is shutting down — it never executed, so a fleet router
    may safely resubmit it to a different replica. Distinct from
    :class:`DeadlineExceeded` (the client gave up) and from genuine
    request failures (which must not be retried blindly)."""


class ReplicaDraining(ServerClosed):
    """Admission-time rejection from a replica in the ``draining``
    state (explicit drain RPC or rolling ``fleet_swap``): nothing was
    executed, in-flight work continues to completion, and the router is
    expected to retry the request on a different replica."""


class ServerOverloaded(ServingError):
    """Backpressure rejection: the bounded request queue stayed full
    past the submit timeout. The request never entered the queue, so
    routing it to a less-loaded replica is always safe."""


class _Request:
    __slots__ = ("inputs", "rows", "future", "t_submit", "deadline",
                 "tenant", "priority")

    def __init__(self, inputs, rows, deadline=None, tenant=None,
                 priority=1):
        self.inputs = inputs
        self.rows = rows
        self.future = Future()
        self.t_submit = time.perf_counter()
        self.deadline = deadline  # absolute time.monotonic(), or None
        self.tenant = tenant      # QoS label (ISSUE 18), or None
        self.priority = 1 if priority is None else int(priority)


class _ModelWorker:
    """One model's queue + serving thread (drain-and-coalesce)."""

    def __init__(self, name, predictor, queue_depth):
        self.name = name
        self.predictor = predictor
        self._depth = queue_depth
        self._cond = threading.Condition()
        self._q = deque()
        self._stopped = False
        self._error = None       # sticky worker-death error
        self._busy = False       # a batch is executing right now
        # quiesce lock: held around every batch forward; swap() takes it
        # to wait out the in-flight batch before republishing weights
        self._exec_lock = threading.Lock()
        self._batch_hook = None  # test seam: called before each forward
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="serve-%s" % name)
        self._thread.start()

    # -- producer side -------------------------------------------------------
    def enqueue(self, req, timeout):
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                if self._stopped:
                    if self._error is not None:
                        raise ServingError(
                            "model %r: worker died: %r"
                            % (self.name, self._error))
                    raise ServerClosed(
                        "model %r: worker is stopped" % self.name)
                if len(self._q) < self._depth:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ServerOverloaded(
                        "model %r: request queue full (%d queued, "
                        "MXNET_SERVE_QUEUE_DEPTH=%d) — backpressure "
                        "timeout" % (self.name, len(self._q), self._depth))
                self._cond.wait(min(remaining, 0.1))
            self._q.append(req)
            depth = len(self._q)
            self._cond.notify_all()
        return depth

    # -- worker side ---------------------------------------------------------
    def _drain_locked(self):
        """Pop the largest ready batch: requests in priority-then-FIFO
        order while the running row total still fits the biggest
        bucket. Requests whose deadline already expired are SHED here —
        at dequeue, before they can occupy a batch slot (their clients
        have given up; an overloaded server must spend its forwards on
        requests that are still wanted). Priority classes (ISSUE 18)
        reorder only when classes actually mix: a latency request jumps
        queued bulk work, so under overload bulk waits, expires, and is
        shed by this same discipline before a latency p99 moves. The
        sort is stable — FIFO within a class — and the all-one-class
        fast path is byte-identical to the PR 9 behavior. Returns
        (reqs, rows, shed); reqs may be empty when everything queued
        had expired."""
        cap = self.predictor.max_bucket
        now = time.monotonic()
        shed, reqs, total = [], [], 0
        queue = self._q
        if len({r.priority for r in queue}) > 1:
            queue = sorted(queue, key=lambda r: r.priority)
        taken = set()
        for r in queue:
            if r.deadline is not None and now > r.deadline:
                shed.append(r)
                taken.add(id(r))
                continue
            if reqs and total + r.rows > cap:
                break
            reqs.append(r)
            taken.add(id(r))
            total += r.rows
        if taken:
            if len(taken) == len(self._q):
                self._q.clear()
            else:
                remaining = [r for r in self._q if id(r) not in taken]
                self._q.clear()
                self._q.extend(remaining)
        return reqs, total, shed

    def _run(self):
        try:
            while True:
                with self._cond:
                    while not self._q and not self._stopped:
                        self._cond.wait()
                    if self._stopped:
                        return
                    reqs, rows, shed = self._drain_locked()
                    if reqs:
                        self._busy = True
                    self._cond.notify_all()  # queue space freed
                if shed:
                    # futures fail OUTSIDE the lock: done-callbacks run
                    # inline on set_exception and must not deadlock a
                    # client that re-submits from one
                    exc = DeadlineExceeded(
                        "model %r: deadline expired before dispatch "
                        "(shed at dequeue)" % self.name)
                    for r in shed:
                        if not r.future.done():
                            r.future.set_exception(exc)
                        if r.tenant is not None:
                            profiler.qos_record(r.tenant, shed=1)
                    profiler.serving_record(self.name, shed=len(shed))
                if not reqs:
                    continue
                try:
                    self._execute(reqs, rows)
                except BaseException as e:  # bad batch — fail ITS futures,
                    for r in reqs:          # keep serving the next ones
                        if not r.future.done():
                            r.future.set_exception(e)
                    profiler.serving_record(self.name, errors=len(reqs))
                finally:
                    with self._cond:
                        self._busy = False
                        self._cond.notify_all()
        except BaseException as e:  # worker death: sticky, fail the queue
            with self._cond:
                self._error = e
                self._stopped = True
                pending = list(self._q)
                self._q.clear()
                self._cond.notify_all()
            for r in pending:
                if not r.future.done():
                    r.future.set_exception(e)

    def _execute(self, reqs, rows):
        pred = self.predictor
        bucket = pred.pick_bucket(rows)
        with self._exec_lock:
            if self._batch_hook is not None:
                self._batch_hook(reqs)
            if len(reqs) == 1 and reqs[0].rows == bucket:
                inputs = reqs[0].inputs  # exact fit: no assembly copy
            else:
                inputs = {}
                for name in pred.data_names:
                    first = reqs[0].inputs[name]
                    buf = np.zeros((bucket,) + first.shape[1:],
                                   dtype=first.dtype)
                    ofs = 0
                    for r in reqs:
                        buf[ofs:ofs + r.rows] = r.inputs[name]
                        ofs += r.rows
                    inputs[name] = buf
            outs = pred.run_bucket(inputs, bucket)
        now = time.perf_counter()
        lats, ofs = [], 0
        for r in reqs:
            res = [o[ofs:ofs + r.rows]
                   if o.ndim and o.shape[0] == bucket else o
                   for o in outs]
            ofs += r.rows
            r.future.set_result(res)
            lats.append(now - r.t_submit)
        profiler.serving_record(self.name, batches=1, rows=rows,
                                capacity=bucket, latencies=lats)

    # -- lifecycle -----------------------------------------------------------
    def stop(self):
        with self._cond:
            self._stopped = True
            self._cond.notify_all()

    def join(self, timeout):
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def fail_pending(self, exc):
        with self._cond:
            pending = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        for r in pending:
            if not r.future.done():
                r.future.set_exception(exc)
        return len(pending)


class ModelServer:
    """Multi-model dynamic-batching inference server.

    ::

        with ModelServer() as srv:
            srv.add_model("resnet", symbol=sym, arg_params=args,
                          aux_params=auxs,
                          data_shapes={"data": (1, 3, 224, 224)})
            fut = srv.submit("resnet", batch_np)   # -> Future
            probs = fut.result()[0]

    All resident models share one LRU of compiled executables
    (``MXNET_SERVE_MAX_EXECUTABLES``) keyed by (model, bucket, dtype);
    evictions recompile on next use, parameters stay resident.
    """

    def __init__(self, ladder=None, queue_depth=None, cache_capacity=None,
                 submit_timeout=None, dtype="float32", device=None):
        from .predictor import validate_ladder

        self._ladder = env_batch_ladder() if ladder is None \
            else validate_ladder(ladder)
        self._queue_depth = env_positive_int(
            "MXNET_SERVE_QUEUE_DEPTH", 256) if queue_depth is None \
            else int(queue_depth)
        if self._queue_depth < 1:
            raise ServingError("ModelServer: queue_depth must be >= 1, "
                               "got %d" % self._queue_depth)
        capacity = env_positive_int("MXNET_SERVE_MAX_EXECUTABLES", 32) \
            if cache_capacity is None else cache_capacity
        self._cache = ExecutableCache(capacity)
        self._submit_timeout = env_positive_float(
            "MXNET_SERVE_SUBMIT_TIMEOUT", 60.0) if submit_timeout is None \
            else float(submit_timeout)
        self._dtype = dtype
        self._device = device
        self._workers = {}
        self._lock = threading.Lock()
        self._closed = False

    # -- model residency -----------------------------------------------------
    def add_model(self, name, symbol=None, arg_params=None, aux_params=None,
                  data_shapes=None, predictor=None, **predictor_kwargs):
        """Make ``name`` resident: either hand in a prebuilt
        :class:`AOTPredictor`, or a symbol + params + data_shapes and
        the server binds one on its shared executable cache."""
        self._check_open()
        if predictor is None:
            if symbol is None or data_shapes is None:
                raise ServingError(
                    "add_model(%r): need either predictor= or "
                    "symbol=/data_shapes= (+params)" % name)
            predictor_kwargs.setdefault("ladder", self._ladder)
            predictor_kwargs.setdefault("dtype", self._dtype)
            predictor_kwargs.setdefault("device", self._device)
            predictor = AOTPredictor(
                symbol, arg_params, aux_params, data_shapes=data_shapes,
                cache=self._cache, model_name=name, **predictor_kwargs)
        if predictor.ladder is None:
            raise ServingError(
                "add_model(%r): exact-bound predictors (ladder=None) "
                "cannot serve coalesced traffic" % name)
        with self._lock:
            if name in self._workers:
                raise ServingError("model %r is already resident; use "
                                   "swap() to update its weights" % name)
            self._workers[name] = _ModelWorker(name, predictor,
                                               self._queue_depth)
        return predictor

    def models(self):
        with self._lock:
            return sorted(self._workers)

    def _worker(self, name):
        with self._lock:
            worker = self._workers.get(name)
        if worker is None:
            raise ServingError("unknown model %r (resident: %s)"
                               % (name, self.models()))
        return worker

    def _check_open(self):
        if self._closed:
            raise ServerClosed("ModelServer is closed")

    # -- request surface -----------------------------------------------------
    def submit(self, name, inputs, timeout=None, deadline=None,
               tenant=None, priority=None):
        """Enqueue one request; returns a ``concurrent.futures.Future``
        resolving to the list of output arrays (request row count).
        Blocks for queue space up to ``timeout`` (backpressure), then
        raises :class:`ServingError`. ``deadline`` (seconds from now,
        > 0) marks the request sheddable: if it is still queued when
        the deadline passes, the worker drops it at dequeue and its
        future fails fast with :class:`DeadlineExceeded` instead of
        occupying a batch slot — overload protection for clients that
        time out anyway (counted as ``shed`` in serving_stats).
        ``tenant``/``priority`` (ISSUE 18) label the request for QoS:
        lower priority dequeues first (see qos.PRIORITIES), and sheds
        of a labelled request are counted per tenant in qos_stats."""
        self._check_open()
        worker = self._worker(name)
        pred = worker.predictor
        inputs, rows = pred._normalize(inputs)
        pred.pick_bucket(rows)  # reject oversized requests in the caller
        if deadline is not None:
            deadline = float(deadline)
            if not deadline > 0:
                raise ServingError("submit: deadline must be > 0 "
                                   "seconds, got %r" % deadline)
            deadline = time.monotonic() + deadline
        req = _Request(inputs, rows, deadline=deadline, tenant=tenant,
                       priority=priority)
        depth = worker.enqueue(
            req, self._submit_timeout if timeout is None else timeout)
        profiler.serving_record(name, requests=1, queue_depth=depth)
        return req.future

    def predict(self, name, inputs, timeout=None):
        """Synchronous convenience wrapper around :meth:`submit`."""
        return self.submit(name, inputs, timeout=timeout).result()

    # -- hot swap ------------------------------------------------------------
    def swap(self, name, arg_params=None, aux_params=None,
             allow_extra=False):
        """Atomically replace a resident model's weights without
        dropping requests: waits out the in-flight batch (quiesce),
        swaps, releases — queued requests are served by the new model."""
        self._check_open()
        worker = self._worker(name)
        with worker._exec_lock:
            return worker.predictor.swap_params(
                arg_params, aux_params, allow_extra=allow_extra)

    def swap_from_checkpoint(self, name, prefix=None, epoch=None,
                             directory=None):
        """Hot-swap from a checkpoint: either the two-artifact format
        (``prefix``/``epoch``) or the newest committed checkpoint of an
        elastic-training ``CheckpointManager`` ``directory``
        (``CheckpointManager.latest()``)."""
        if (prefix is None) == (directory is None):
            raise ServingError("swap_from_checkpoint: pass exactly one "
                               "of prefix= or directory=")
        if prefix is not None:
            from ..model import load_checkpoint

            _, arg_params, aux_params = load_checkpoint(
                prefix, 0 if epoch is None else int(epoch))
        else:
            from ..checkpoint import CheckpointManager

            ckpt = CheckpointManager(directory).latest()
            if ckpt is None:
                raise ServingError(
                    "swap_from_checkpoint: no committed checkpoint "
                    "under %r" % directory)
            arg_params, aux_params = ckpt.split_weights()
        return self.swap(name, arg_params, aux_params, allow_extra=True)

    # -- observability -------------------------------------------------------
    def stats(self, reset=False):
        """Per-model serving counters (see profiler.serving_stats)."""
        return profiler.serving_stats(reset=reset)

    def pending(self):
        """Queued requests plus in-flight batches across all resident
        models — the drain observable: a draining replica admits
        nothing and waits for this to reach 0 before swapping or
        deregistering (serving/fleet.py)."""
        with self._lock:
            workers = list(self._workers.values())
        total = 0
        for w in workers:
            with w._cond:
                total += len(w._q) + (1 if w._busy else 0)
        return total

    @property
    def executable_cache(self):
        return self._cache

    # -- lifecycle -----------------------------------------------------------
    def close(self, timeout=5.0):
        """Stop and join every worker (bounded — no leaked daemons),
        fail still-queued requests with the typed :class:`ServerClosed`
        (they never executed — a router may retry them elsewhere).
        Idempotent; submits after close raise :class:`ServerClosed`."""
        if self._closed:
            return
        self._closed = True
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            w.stop()
        deadline = time.monotonic() + timeout
        for w in workers:
            w.join(max(0.0, deadline - time.monotonic()))
        exc = ServerClosed("ModelServer closed before the request was "
                           "dispatched")
        for w in workers:
            w.fail_pending(exc)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# generative serving (ISSUE 12): continuous-batching decode loop
# ---------------------------------------------------------------------------
class _GenRequest:
    __slots__ = ("tokens", "max_new", "eos_id", "future", "stream_fn",
                 "t_submit", "deadline", "no_eos", "out", "pages",
                 "slot", "ttft", "unflushed", "prefix_len", "shared",
                 "draft_pages", "draft_pos")

    def __init__(self, tokens, max_new, eos_id, deadline, stream_fn):
        self.tokens = tokens
        self.max_new = max_new
        self.eos_id = eos_id
        self.future = Future()
        self.stream_fn = stream_fn
        self.t_submit = time.perf_counter()
        self.deadline = deadline     # absolute time.monotonic(), or None
        self.no_eos = False          # chaos generate:stall — never sees EOS
        self.out = []
        self.pages = []
        self.slot = None
        self.ttft = None
        self.unflushed = []
        self.prefix_len = 0          # tokens covered by shared prefix pages
        self.shared = 0              # pages borrowed from the prefix index
        self.draft_pages = []        # draft predictor's pages (spec decode)
        self.draft_pos = 0           # next position the draft cache needs


class GenerateServer:
    """Continuous-batching autoregressive decode server (ISSUE 12).

    The structural difference from :class:`ModelServer`: a generate
    request is not one forward but a *prefill* plus an open-ended run
    of single-token decode steps, and requests finish at different
    steps. Draining whole batches would leave finished slots idle for
    the remainder of the longest request — so the decode loop here
    admits new requests into vacated batch slots EVERY decode step
    (continuous batching): admit (shedding deadline-expired requests
    at dequeue, the PR 9 rule) → prefill admitted prompts into freshly
    allocated KV pages → one decode step over all active slots →
    sample, stream, finish, recycle pages. ``admit_policy="drain"``
    keeps the old drain-whole-batch behavior for the bench comparison.

    Memory is paged (:class:`~.generate.PagePool`): each slot holds a
    block table naming its pages; completion returns the pages
    immediately. Pool exhaustion at admission backpressures (the
    request waits in queue); a request that can never fit — or a
    mid-decode page the pool cannot provide — fails fast with the
    typed :class:`~.generate.PagePoolExhausted`.

    Tokens stream back through the request future (resolves to
    ``{"tokens", "finish_reason", "ttft_s", "latency_s"}``); a
    ``stream_fn`` callback additionally receives token chunks every
    ``MXNET_GENERATE_STREAM_FLUSH`` decode steps.
    """

    FINISH_EOS = "eos"
    FINISH_LENGTH = "length"

    def __init__(self, config=None, params=None, predictor=None, *,
                 slots=None, page_size=None, pool_bytes=None,
                 max_steps=None, stream_flush=None, queue_depth=None,
                 submit_timeout=None, admit_policy="continuous",
                 prefix_cache=None, prefix_evict=None, spec_k=None,
                 draft=None, draft_config=None, draft_params=None,
                 device=None, cache=None, name="generate", **pred_kwargs):
        if predictor is None and (config is None or params is None):
            raise GenerateError(
                "GenerateServer: need either predictor= or "
                "config=/params=")
        # knob parsing first: a malformed knob must raise (naming the
        # knob) before any device work or thread starts
        self._prefix_on = _env_strict_bool("MXNET_GENERATE_PREFIX_CACHE") \
            if prefix_cache is None else bool(prefix_cache)
        prefix_bound = _env_nonneg_int("MXNET_GENERATE_PREFIX_EVICT") \
            if prefix_evict is None else int(prefix_evict)
        self._spec_k = _env_nonneg_int("MXNET_GENERATE_SPEC_K") \
            if spec_k is None else int(spec_k)
        draft_layers = _env_nonneg_int("MXNET_GENERATE_DRAFT") \
            if draft is None else int(draft)
        if predictor is None:
            predictor = GenerativePredictor(
                config, params, slots=slots, page_size=page_size,
                pool_bytes=pool_bytes, device=device, cache=cache,
                model_name=name, **pred_kwargs)
        self.predictor = predictor
        self.name = name
        self._prefix = PrefixIndex(predictor.page_size, prefix_bound) \
            if self._prefix_on else None
        self._draft = None
        if self._spec_k > 0:
            if draft_config is None or draft_params is None:
                if draft_layers < 1:
                    raise GenerateError(
                        "GenerateServer: speculative decoding "
                        "(MXNET_GENERATE_SPEC_K=%d) needs a draft model: "
                        "set MXNET_GENERATE_DRAFT >= 1 (self-draft layer "
                        "count) or pass draft_config=/draft_params="
                        % self._spec_k)
                from ..models.transformer import draft_from_layers

                try:
                    draft_config, draft_params = draft_from_layers(
                        predictor.config, predictor._params, draft_layers)
                except ValueError as e:
                    raise GenerateError("GenerateServer: %s" % e)
            self._draft = GenerativePredictor(
                draft_config, draft_params, slots=predictor.slots,
                page_size=predictor.page_size, pool_bytes=0,
                max_ctx=predictor.max_ctx, block_k=predictor.block_k,
                device=device, cache=cache, model_name="%s-draft" % name)
        if admit_policy not in ("continuous", "drain"):
            raise GenerateError("GenerateServer: admit_policy must be "
                                "continuous|drain, got %r" % admit_policy)
        self._policy = admit_policy
        self._max_steps = _env_positive_int("MXNET_GENERATE_MAX_STEPS") \
            if max_steps is None else int(max_steps)
        if self._max_steps < 1:
            raise GenerateError("GenerateServer: max_steps must be >= 1, "
                                "got %d" % self._max_steps)
        self._flush_every = _env_positive_int("MXNET_GENERATE_STREAM_FLUSH") \
            if stream_flush is None else int(stream_flush)
        if self._flush_every < 1:
            raise GenerateError("GenerateServer: stream_flush must be "
                                ">= 1, got %d" % self._flush_every)
        self._depth = env_positive_int("MXNET_SERVE_QUEUE_DEPTH", 256) \
            if queue_depth is None else int(queue_depth)
        self._submit_timeout = env_positive_float(
            "MXNET_SERVE_SUBMIT_TIMEOUT", 60.0) if submit_timeout is None \
            else float(submit_timeout)

        S, MP = predictor.slots, predictor.max_pages_per_slot
        self._slot_req = [None] * S
        self._block_tables = np.zeros((S, MP), np.int32)
        self._positions = np.zeros((S,), np.int32)
        self._tokens = np.zeros((S,), np.int32)
        self._active = np.zeros((S,), bool)
        # the draft model's own block tables (its pool is auto-sized to
        # slots x max-context pages, so draft growth can never exhaust)
        self._draft_bt = np.zeros((S, MP), np.int32) \
            if self._draft is not None else None

        self._cond = threading.Condition()
        self._q = deque()
        self._stopped = False
        self._error = None
        self._step_hook = None       # test seam: called before each decode
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="generate-%s" % name)
        self._thread.start()

    # -- producer side -------------------------------------------------------
    def submit(self, tokens, max_new_tokens=None, eos_id=None,
               deadline=None, stream_fn=None, timeout=None):
        """Enqueue one generate request; returns a Future resolving to
        ``{"tokens": [int], "finish_reason": "eos"|"length",
        "ttft_s", "latency_s", "prompt_tokens"}``. ``deadline``
        (seconds from now) marks it sheddable at dequeue (PR 9) AND
        bounds the decode run itself — a mid-generation expiry fails
        the future with :class:`DeadlineExceeded` and recycles the
        slot + pages. ``max_new_tokens`` is capped by
        ``MXNET_GENERATE_MAX_STEPS`` and the per-slot context bound."""
        pred = self.predictor
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if tokens.shape[0] < 1:
            raise GenerateError("submit: empty prompt")
        if int(tokens.min()) < 0 or int(tokens.max()) >= pred.config.vocab:
            # the compiled programs CLAMP ids (shape-static gather);
            # serving a clamped id would silently diverge from the
            # zero-masking one-shot forward, so reject at the door
            raise GenerateError(
                "submit: prompt token ids must lie in [0, %d), got "
                "range [%d, %d]" % (pred.config.vocab, tokens.min(),
                                    tokens.max()))
        if tokens.shape[0] > pred.max_ctx - 1:
            raise GenerateError(
                "submit: %d-token prompt exceeds the per-slot context "
                "bound %d (need room for >= 1 generated token)"
                % (tokens.shape[0], pred.max_ctx))
        if pred.pages_needed(tokens.shape[0]) > pred.pool.num_pages:
            raise PagePoolExhausted(
                "submit: prompt needs %d pages, the whole pool holds %d"
                % (pred.pages_needed(tokens.shape[0]),
                   pred.pool.num_pages))
        max_new = self._max_steps if max_new_tokens is None \
            else int(max_new_tokens)
        if max_new < 1:
            raise GenerateError("submit: max_new_tokens must be >= 1, "
                                "got %d" % max_new)
        max_new = min(max_new, self._max_steps,
                      pred.max_ctx - int(tokens.shape[0]))
        if deadline is not None:
            deadline = float(deadline)
            if not deadline > 0:
                raise GenerateError("submit: deadline must be > 0 "
                                    "seconds, got %r" % deadline)
            deadline = time.monotonic() + deadline
        req = _GenRequest(tokens, max_new, eos_id, deadline, stream_fn)
        wait_until = time.monotonic() + (
            self._submit_timeout if timeout is None else float(timeout))
        with self._cond:
            while True:
                if self._stopped:
                    if self._error is not None:
                        raise ServingError("GenerateServer %r: worker "
                                           "died: %r" % (self.name,
                                                         self._error))
                    raise ServerClosed("GenerateServer %r is closed"
                                       % self.name)
                if len(self._q) < self._depth:
                    break
                remaining = wait_until - time.monotonic()
                if remaining <= 0:
                    raise ServerOverloaded(
                        "GenerateServer %r: request queue full (%d "
                        "queued, MXNET_SERVE_QUEUE_DEPTH=%d)"
                        % (self.name, len(self._q), self._depth))
                self._cond.wait(min(remaining, 0.1))
            self._q.append(req)
            depth = len(self._q)
            self._cond.notify_all()
        profiler.generate_record(requests=1, queue_depth=depth)
        return req.future

    def generate(self, tokens, **kw):
        """Synchronous convenience wrapper around :meth:`submit`."""
        return self.submit(tokens, **kw).result()

    # -- worker side ---------------------------------------------------------
    def _active_count(self):
        return int(self._active.sum())

    def _alloc_pages(self, n):
        """``pool.alloc`` with prefix-index pressure relief: under
        exhaustion, evict least-recently-matched index entries until
        the allocation fits or the index is empty — so sharing never
        causes a :class:`PagePoolExhausted` a no-sharing run would
        avoid. (An evicted page only becomes free once no live request
        still shares it, hence the loop.)"""
        pred = self.predictor
        while True:
            try:
                return pred.pool.alloc(n)
            except PagePoolExhausted:
                if self._prefix is None or \
                        not self._prefix.evict_lru(pred.pool):
                    raise
                profiler.generate_record(prefix_evictions=1)

    def _reserve_pages(self, r):
        """Reserve a request's KV pages at admission: match the longest
        cached prefix (those pages are shared copy-on-write — the match
        already took the request's reference on each) and allocate
        private pages for the remainder. On exhaustion the match
        references are released and the FULL allocation is retried
        unshared — sharing must never block an admission the unshared
        path could serve — before the exhaustion propagates."""
        pred = self.predictor
        need = pred.pages_needed(r.tokens.shape[0])
        matched = []
        if self._prefix is not None:
            matched = self._prefix.match([int(t) for t in r.tokens],
                                         pred.pool)
        try:
            tail = self._alloc_pages(need - len(matched))
        except PagePoolExhausted:
            if not matched:
                raise
            pred.pool.unref(matched)
            matched, tail = [], self._alloc_pages(need)
        r.pages = matched + tail
        r.shared = len(matched)
        r.prefix_len = len(matched) * pred.page_size

    def _admit_locked(self):
        """Pop admissible requests into slots (shedding expired ones at
        dequeue); pages are reserved here so a request is only popped
        when its prompt fits. Returns (admitted, shed, starved) —
        ``starved`` is a request that can NEVER be admitted (no active
        slots to recycle pages from and nothing else admitted this
        round): it must fail typed instead of stalling forever."""
        pred = self.predictor
        admitted, shed = [], []
        if self._policy == "drain" and self._active_count() > 0:
            return admitted, shed, None
        free = [i for i in range(pred.slots) if self._slot_req[i] is None]
        now = time.monotonic()
        while free and self._q:
            r = self._q[0]
            if r.deadline is not None and now > r.deadline:
                shed.append(self._q.popleft())
                continue
            try:
                self._reserve_pages(r)
            except PagePoolExhausted:
                if not admitted and self._active_count() == 0:
                    return admitted, shed, self._q.popleft()
                break     # backpressure: completions will recycle pages
            self._q.popleft()
            r.slot = free.pop(0)
            self._slot_req[r.slot] = r
            admitted.append(r)
        if shed or admitted:
            self._cond.notify_all()   # queue space freed
        return admitted, shed, None

    def _record_pool(self):
        s = self.predictor.pool.stats()
        profiler.generate_record(pages_in_use=s["in_use"],
                                 pages_high_water=s["high_water"],
                                 pool_pages=s["num_pages"],
                                 page_ref_high_water=s["ref_high_water"])
        if self._prefix is not None:
            profiler.generate_record(prefix_pages=self._prefix.pages)

    def _vacate(self, r):
        slot = r.slot
        with self._cond:
            self._slot_req[slot] = None
            self._active[slot] = False
            self._block_tables[slot, :] = 0
            self._positions[slot] = 0
            self._tokens[slot] = 0
            if self._draft_bt is not None:
                self._draft_bt[slot, :] = 0
            self._cond.notify_all()
        if r.pages:
            # drops ONE reference per page: private pages free, shared
            # prefix pages just decrement (the index and/or other
            # requests still hold theirs)
            self.predictor.pool.free(r.pages)
            r.pages = []
        if r.draft_pages:
            self._draft.pool.free(r.draft_pages)
            r.draft_pages = []
        self._record_pool()

    def _flush_stream(self, r, final=False):
        if r.stream_fn is None:
            r.unflushed = []
            return
        if r.unflushed and (final or len(r.unflushed) >= self._flush_every):
            chunk, r.unflushed = r.unflushed, []
            try:
                r.stream_fn(chunk)
            except Exception:
                pass     # a broken stream consumer must not kill the loop

    def _finish(self, r, reason):
        self._vacate(r)
        self._flush_stream(r, final=True)
        profiler.generate_record(finished=1, **{reason: 1})
        r.future.set_result({
            "tokens": list(r.out),
            "finish_reason": reason,
            "prompt_tokens": int(r.tokens.shape[0]),
            "ttft_s": r.ttft,
            "latency_s": time.perf_counter() - r.t_submit,
        })

    def _fail(self, r, exc, counter=None):
        self._vacate(r)
        self._flush_stream(r, final=True)
        profiler.generate_record(finished=1, **{counter or "errors": 1})
        if not r.future.done():
            r.future.set_exception(exc)

    def _check_done(self, r, tok):
        """EOS / length / deadline disposition for a just-produced
        token; returns True when the request left its slot."""
        if (r.eos_id is not None and tok == r.eos_id and not r.no_eos):
            self._finish(r, self.FINISH_EOS)
            return True
        if len(r.out) >= r.max_new:
            self._finish(r, self.FINISH_LENGTH)
            return True
        if r.deadline is not None and time.monotonic() > r.deadline:
            self._fail(r, DeadlineExceeded(
                "generate: deadline expired after %d token(s); slot and "
                "pages recycled" % len(r.out)), counter="deadline")
            return True
        return False

    def _prefill_one(self, r):
        pred = self.predictor
        if chaos.generate_fault() == "stall":
            r.no_eos = True    # the request that never emits EOS
        t0 = time.perf_counter()
        try:
            if r.prefix_len:
                # shared-prefix admission: the first prefix_len tokens'
                # K/V already live in the matched (shared) pages — run
                # only the uncovered tail, which attends the shared
                # pages but writes exclusively the private ones (COW)
                logits = pred.extend_tail(r.tokens[r.prefix_len:],
                                          r.prefix_len, r.pages)
            else:
                logits = pred.prefill(r.tokens, r.pages)
            if self._draft is not None:
                r.draft_pages = self._draft.pool.alloc(
                    self._draft.pages_needed(r.tokens.shape[0]))
                self._draft.prefill(r.tokens, r.draft_pages)
        except BaseException as e:
            self._fail(r, e)
            return
        now = time.perf_counter()
        profiler.generate_record(busy_seconds=now - t0)
        r.ttft = now - r.t_submit
        tok = int(np.argmax(logits))
        r.out.append(tok)
        r.unflushed.append(tok)
        # tokens counts every GENERATED token; the first one comes out
        # of prefill, the rest out of decode steps. prefill_tokens
        # counts tokens actually RUN — a matched prefix's tokens land
        # in prefill_tokens_saved instead (their sum is the prompt)
        profiler.generate_record(prefills=1, tokens=1,
                                 prefill_tokens=int(r.tokens.shape[0])
                                 - r.prefix_len,
                                 ttfts=[r.ttft])
        if r.prefix_len:
            profiler.generate_record(prefix_hits=1,
                                     shared_pages=r.shared,
                                     prefill_tokens_saved=r.prefix_len)
        if self._prefix is not None:
            # index this prompt's full pages for future admissions (the
            # index takes its own reference on newly indexed pages, so
            # they outlive this request)
            self._prefix.insert([int(t) for t in r.tokens], r.pages,
                                pred.pool)
        self._record_pool()
        slot = r.slot
        self._block_tables[slot, :len(r.pages)] = r.pages
        self._positions[slot] = r.tokens.shape[0]
        self._tokens[slot] = tok
        if self._draft is not None:
            self._draft_bt[slot, :len(r.draft_pages)] = r.draft_pages
            r.draft_pos = int(r.tokens.shape[0])
        self._flush_stream(r)
        if not self._check_done(r, tok):
            self._active[slot] = True

    def _grow_pages(self, headroom=0):
        """Before a decode step, make sure every active slot owns the
        page(s) its next write positions land in — up to ``headroom``
        extra positions past the pending one for a speculative round's
        verify writes; a pool that cannot grow a mid-flight request
        fails it typed (never a silent stall)."""
        pred = self.predictor
        for slot in np.flatnonzero(self._active):
            r = self._slot_req[slot]
            upto = min(int(self._positions[slot]) + headroom,
                       pred.max_ctx - 1)
            try:
                for pidx in range(upto // pred.page_size + 1):
                    if self._block_tables[slot, pidx] != 0:
                        continue
                    page, = self._alloc_pages(1)
                    r.pages.append(page)
                    self._block_tables[slot, pidx] = page
                if self._draft is not None:
                    for pidx in range(upto // pred.page_size + 1):
                        if self._draft_bt[slot, pidx] != 0:
                            continue
                        page, = self._draft.pool.alloc(1)
                        r.draft_pages.append(page)
                        self._draft_bt[slot, pidx] = page
            except PagePoolExhausted as e:
                self._fail(r, PagePoolExhausted(
                    "generate: pool exhausted growing a mid-flight "
                    "request past %d token(s): %s" % (len(r.out), e)),
                    counter="exhausted")
                continue

    def _decode_step(self):
        pred = self.predictor
        if self._step_hook is not None:
            self._step_hook()
        t0 = time.perf_counter()
        logits = pred.decode(self._tokens, self._positions,
                             self._block_tables, self._active)
        active = np.flatnonzero(self._active)
        self._positions[active] += 1
        profiler.generate_record(decode_steps=1, tokens=len(active),
                                 slot_steps=pred.slots,
                                 active_slot_steps=len(active),
                                 busy_seconds=time.perf_counter() - t0)
        for slot in active:
            r = self._slot_req[slot]
            tok = int(np.argmax(logits[slot]))
            r.out.append(tok)
            r.unflushed.append(tok)
            self._tokens[slot] = tok
            self._flush_stream(r)
            self._check_done(r, tok)

    def _spec_step(self):
        """One speculative-decoding round (ISSUE 16), replacing one
        single-token decode step when ``spec_k > 0``:

        1. the DRAFT predictor catches its KV cache up to each slot's
           committed chain, then autoregressively proposes up to k
           tokens per slot (batched single-token draft steps with
           per-slot feed cursors — slots needing fewer sub-steps go
           inactive early);
        2. ONE batched ``extend`` of the TARGET verifies, per slot, the
           pending token plus the k proposals (k+1 rows, one program);
        3. the longest proposal prefix agreeing with the target's
           argmax chain is accepted and emitted, plus the target's own
           next token (the replacement on first disagreement, the bonus
           token on full acceptance).

        Every emitted token IS the argmax of the target's logits given
        the tokens before it — acceptance is argmax equality — so the
        emitted chain is token-for-token the non-speculative greedy
        chain, and EOS / length / deadline disposition runs per emitted
        token in order (truncation parity). Rejected proposals leave
        K/V garbage at positions past the accepted prefix in both
        caches; the next round's writes land there before any query
        attends them (the padded-prefill-tail invariant)."""
        pred, draft, k = self.predictor, self._draft, self._spec_k
        if self._step_hook is not None:
            self._step_hook()
        t0 = time.perf_counter()
        active = [int(s) for s in np.flatnonzero(self._active)]
        if not active:
            return
        S = pred.slots

        chain_len, k_i, feed, props = {}, {}, {}, {}
        for s in active:
            r = self._slot_req[s]
            chain = [int(t) for t in r.tokens] + r.out
            L = len(chain)                    # pending sits at L - 1
            chain_len[s] = L
            k_i[s] = max(0, min(k, pred.max_ctx - L))
            # tokens the draft cache hasn't ingested yet (committed
            # chain only; proposals are appended as they are drafted)
            feed[s] = [(chain[p], p) for p in range(r.draft_pos, L)]
            props[s] = []

        # -- draft phase: batched single-token steps ------------------
        while True:
            todo = [s for s in active if len(props[s]) < k_i[s]]
            if not todo:
                break
            toks = np.zeros((S,), np.int32)
            poss = np.zeros((S,), np.int32)
            act = np.zeros((S,), bool)
            fed = {}
            for s in todo:
                if feed[s]:
                    t, p = feed[s].pop(0)
                else:
                    j = len(props[s])
                    t, p = props[s][j - 1], chain_len[s] + j - 1
                toks[s], poss[s], act[s] = t, p, True
                fed[s] = p
            logits = draft.decode(toks, poss, self._draft_bt, act)
            for s in todo:
                # feeding position p yields the draft's prediction for
                # p + 1; only positions at/past the chain end propose
                if fed[s] >= chain_len[s] - 1:
                    props[s].append(int(np.argmax(logits[s])))
                r = self._slot_req[s]
                r.draft_pos = max(r.draft_pos, fed[s] + 1)

        # -- verify phase: one batched target extend ------------------
        T = k + 1
        vt = np.zeros((S, T), np.int32)
        vp = np.zeros((S, T), np.int32)
        vv = np.zeros((S, T), bool)
        for s in active:
            n = 1 + k_i[s]
            vt[s, :n] = [vtok for vtok in
                         ([self._tokens[s]] + props[s])[:n]]
            vp[s, :n] = np.arange(chain_len[s] - 1,
                                  chain_len[s] - 1 + n)
            vv[s, :n] = True
        logits = pred.extend(vt, vp, self._block_tables, vv)

        # -- accept phase ---------------------------------------------
        emitted_total = 0
        for s in active:
            r = self._slot_req[s]
            L, ks = chain_len[s], k_i[s]
            accepted, emit = 0, []
            for j in range(ks + 1):
                t_target = int(np.argmax(logits[s, j]))
                emit.append(t_target)
                if j < ks and props[s][j] == t_target:
                    accepted += 1
                    continue
                break
            # draft cache is correct up to position L + accepted - 1
            # (chain[L-1] + the accepted proposals); anything it wrote
            # past that is a rejected token's K/V — rewind the cursor
            # so the next round overwrites it
            r.draft_pos = min(r.draft_pos, L + accepted)
            profiler.generate_record(draft_proposed=ks,
                                     draft_accepted=accepted)
            done = False
            for t in emit:
                r.out.append(t)
                r.unflushed.append(t)
                emitted_total += 1
                self._tokens[s] = t
                self._flush_stream(r)
                if self._check_done(r, t):
                    done = True
                    break
            if not done:
                self._positions[s] = L - 1 + len(emit)
        profiler.generate_record(
            decode_steps=1, spec_rounds=1, tokens=emitted_total,
            slot_steps=S, active_slot_steps=len(active),
            busy_seconds=time.perf_counter() - t0)

    def _run(self):
        try:
            while True:
                with self._cond:
                    while (not self._q and not self._active_count()
                           and not self._stopped):
                        self._cond.wait()
                    if self._stopped:
                        return
                    admitted, shed, starved = self._admit_locked()
                if shed:
                    exc = DeadlineExceeded(
                        "generate: deadline expired before admission "
                        "(shed at dequeue)")
                    for r in shed:
                        if not r.future.done():
                            r.future.set_exception(exc)
                    profiler.generate_record(shed=len(shed))
                if starved is not None:
                    self._fail(starved, PagePoolExhausted(
                        "generate: prompt of %d token(s) cannot be "
                        "admitted — pool empty with no requests in "
                        "flight to recycle from"
                        % starved.tokens.shape[0]), counter="exhausted")
                for r in admitted:
                    self._prefill_one(r)
                if not self._active_count():
                    continue
                if self._draft is not None:
                    # speculative round: verify writes up to spec_k
                    # positions past the pending token
                    self._grow_pages(headroom=self._spec_k)
                    if self._active_count():
                        self._spec_step()
                else:
                    self._grow_pages()
                    if self._active_count():
                        self._decode_step()
        except BaseException as e:   # loop death: sticky, fail everything
            with self._cond:
                self._error = e
                self._stopped = True
                pending = list(self._q)
                self._q.clear()
                inflight = [r for r in self._slot_req if r is not None]
                self._cond.notify_all()
            for r in pending:
                if not r.future.done():
                    r.future.set_exception(e)
            for r in inflight:
                self._fail(r, e)

    # -- observability / lifecycle -------------------------------------------
    def stats(self, reset=False):
        """Generative-serving counters (see profiler.generate_stats)."""
        return profiler.generate_stats(reset=reset)

    @property
    def prefix(self):
        """The :class:`~.generate.PrefixIndex` (None when sharing is
        off)."""
        return self._prefix

    @property
    def draft_predictor(self):
        """The draft :class:`~.generate.GenerativePredictor` (None when
        speculative decoding is off)."""
        return self._draft

    def prefix_stats(self):
        """Prefix-index counters, or None when sharing is off."""
        return None if self._prefix is None else self._prefix.stats()

    def clear_prefix(self):
        """Evict every prefix-index entry, releasing the index's page
        references — after the last in-flight request finishes the pool
        then drains to ``in_use == 0`` (the leak-check hook)."""
        if self._prefix is not None:
            self._prefix.clear(self.predictor.pool)
            self._record_pool()

    @property
    def admit_policy(self):
        return self._policy

    def pending(self):
        with self._cond:
            return len(self._q) + sum(1 for r in self._slot_req
                                      if r is not None)

    def close(self, timeout=5.0):
        """Stop the decode loop, fail queued AND in-flight requests
        with the typed :class:`ServerClosed` (a router may retry them
        elsewhere), recycle every page. Idempotent."""
        with self._cond:
            if self._stopped and self._error is None and \
                    not any(self._slot_req) and not self._q:
                return
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout)
        exc = ServerClosed("GenerateServer closed before the request "
                           "finished")
        with self._cond:
            pending = list(self._q)
            self._q.clear()
            inflight = [r for r in self._slot_req if r is not None]
        for r in pending:
            if not r.future.done():
                r.future.set_exception(exc)
        for r in inflight:
            self._fail(r, exc)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
