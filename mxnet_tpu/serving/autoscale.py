"""Elastic autoscaling for the serving fleet (ISSUE 18).

The fleet (PR 11) keeps whatever N an operator picked; this module
closes the control loop the reference delegated to its ps-lite
scheduler (PAPER.md layer 6). A :class:`FleetAutoscaler` polls the
signals replicas ALREADY publish through the tracker on every
heartbeat — queue depth + in-flight (the router's load gauge), the
serving p99 reservoir, generate-slot occupancy (PR 12) — and writes a
*scale directive* (desired size + retired ranks) to a tracker mailbox
(``scale_set``/``scale_get`` ops) that the ``tools/launch.py``
supervisor polls:

- **scale-up**: bump ``desired``; the launcher spawns fresh replica
  ranks under the same supervision (restart budget + the exit-75
  free-respawn slot discipline) as the original topology.
- **scale-down**: pick the highest-rank serving replica, publish it as
  *retired* FIRST (so the supervisor never respawns it, whatever its
  exit looks like), then ride the PR 11 zero-drop drain state machine
  (``drain`` empties queued + in-flight with typed rejections routing
  traffic away, ``deregister`` removes it from discovery) and finally
  ``stop`` it. A replica SIGKILLed *mid-drain* is already in the
  retired set, so the race resolves to a clean retire — counted as
  ``retire_races``, never a double-retire or a zombie respawn.

Robustness contract — **fail-static**: nothing in the serving path
depends on this controller. Replicas serve, the router routes, and the
launcher supervises whether or not the autoscaler is alive; a crashed
or wedged controller simply leaves the last directive (or none) in the
tracker and the fleet keeps serving at its current size. That is
chaos-tested (``autoscaler:crash@tick=N`` in ``chaos.py`` /
``tools/chaos_check.py``). Flapping is prevented by hysteresis (a
scale decision needs ``MXNET_FLEET_AUTOSCALE_HYSTERESIS`` consecutive
agreeing ticks) plus a post-action cooldown. Every decision is logged
on stdout (``[autoscale]``), as a typed tracker lifecycle event, and
in ``profiler.autoscale_stats`` riding ``dump_profile``.

The controller is deliberately registration-free: it talks to the
tracker over a thin raw-socket link without joining the job, so its
death leaves zero tracker state behind.
"""
from __future__ import annotations

import argparse
import signal
import sys
import threading
import time

from .. import chaos, config, profiler
from ..tracker import (TrackerError, _recv_msg, _send_msg,
                       connect_with_backoff)

_TRANSPORT_ERRORS = (OSError, ConnectionError, EOFError)


class AutoscaleError(RuntimeError):
    """Controller-local failure (bad config, unreachable peer)."""


def _knobs():
    """All MXNET_FLEET_AUTOSCALE_* knobs through the strict accessors
    (malformed raises MXNetError naming the knob)."""
    from ..base import MXNetError

    k = {
        "interval": config.get_positive_float(
            "MXNET_FLEET_AUTOSCALE_INTERVAL"),
        "min_replicas": config.get_positive_int(
            "MXNET_FLEET_AUTOSCALE_MIN"),
        "max_replicas": config.get_positive_int(
            "MXNET_FLEET_AUTOSCALE_MAX"),
        "up_load": config.get_positive_float(
            "MXNET_FLEET_AUTOSCALE_UP_LOAD"),
        "down_load": config.get_nonneg_float(
            "MXNET_FLEET_AUTOSCALE_DOWN_LOAD"),
        "hysteresis": config.get_positive_int(
            "MXNET_FLEET_AUTOSCALE_HYSTERESIS"),
        "cooldown": config.get_nonneg_float(
            "MXNET_FLEET_AUTOSCALE_COOLDOWN"),
        "slo_ms": config.get_nonneg_float(
            "MXNET_FLEET_AUTOSCALE_SLO_MS"),
    }
    if k["min_replicas"] > k["max_replicas"]:
        raise MXNetError(
            "MXNET_FLEET_AUTOSCALE_MIN=%d > MXNET_FLEET_AUTOSCALE_MAX=%d"
            % (k["min_replicas"], k["max_replicas"]))
    if k["down_load"] >= k["up_load"]:
        raise MXNetError(
            "MXNET_FLEET_AUTOSCALE_DOWN_LOAD=%g must be below "
            "MXNET_FLEET_AUTOSCALE_UP_LOAD=%g (the dead band between "
            "them is the flap guard)" % (k["down_load"], k["up_load"]))
    return k


class _TrackerLink:
    """Registration-free raw-socket tracker client (one persistent
    connection, reconnect on error). The autoscaler must not *join*
    the job — its crash has to be invisible to the tracker's liveness
    machinery for the fail-static contract to hold."""

    def __init__(self, uri, connect_deadline=15.0, timeout=10.0):
        self.uri = uri
        self._deadline = float(connect_deadline)
        self._timeout = float(timeout)
        self._sock = None
        self._lock = threading.Lock()

    def rpc(self, op, payload=None):
        with self._lock:
            for attempt in (0, 1):
                if self._sock is None:
                    self._sock = connect_with_backoff(
                        self.uri, deadline=self._deadline)
                    self._sock.settimeout(self._timeout)
                try:
                    _send_msg(self._sock, (op, payload or {}))
                    status, reply = _recv_msg(self._sock)
                    break
                except _TRANSPORT_ERRORS:
                    self.close(locked=True)
                    if attempt:
                        raise
        if status == "err":
            raise TrackerError("tracker %s: %s" % (op, reply))
        return reply

    def close(self, locked=False):
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


def _replica_admin(addr, op, payload=None, timeout=None,
                   connect_deadline=5.0):
    """One admin RPC straight at a replica (drain / stop). Unlike the
    router's version this maps nothing to typed serving errors — the
    autoscaler only cares about ok vs failed."""
    timeout = 60.0 if timeout is None else float(timeout)
    sock = connect_with_backoff(addr, deadline=connect_deadline)
    try:
        sock.settimeout(timeout)
        _send_msg(sock, (op, payload or {}))
        status, reply = _recv_msg(sock)
    finally:
        try:
            sock.close()
        except OSError:
            pass
    if status != "ok":
        raise AutoscaleError("replica %s %s: %s" % (addr, op, reply))
    return reply


class FleetAutoscaler:
    """The fleet's scale controller.

    All effectful edges are injectable for subprocess-free tests:
    ``members_fn()`` -> tracker members view, ``actuate_fn(directive)``
    publishes a scale directive, ``admin_fn(addr, op, payload)`` talks
    to a replica, ``event_fn(event, **fields)`` logs to the tracker
    timeline. With only ``tracker_uri`` given, all four ride the real
    tracker link. ``tick()`` is one control step; ``run_forever()``
    loops it. Ticks swallow their own errors (counted as ``errors``) —
    a flaky tracker degrades the *controller*, never the fleet."""

    #: generate-tier slot occupancy above which the fleet counts as
    #: saturated even if the dense queue looks calm (PR 12 slots are
    #: held for a whole decode, so occupancy IS the capacity signal)
    GEN_OCCUPANCY_HIGH = 0.9

    def __init__(self, tracker_uri=None, members_fn=None, actuate_fn=None,
                 admin_fn=None, event_fn=None, min_replicas=None,
                 max_replicas=None, interval=None, up_load=None,
                 down_load=None, hysteresis=None, cooldown=None,
                 slo_ms=None, now_fn=time.monotonic):
        if tracker_uri is None and (members_fn is None
                                    or actuate_fn is None):
            raise AutoscaleError(
                "FleetAutoscaler needs tracker_uri= (production) or "
                "members_fn= + actuate_fn= (tests)")
        k = _knobs()
        self.interval = k["interval"] if interval is None \
            else float(interval)
        self.min_replicas = k["min_replicas"] if min_replicas is None \
            else int(min_replicas)
        self.max_replicas = k["max_replicas"] if max_replicas is None \
            else int(max_replicas)
        self.up_load = k["up_load"] if up_load is None else float(up_load)
        self.down_load = k["down_load"] if down_load is None \
            else float(down_load)
        self.hysteresis = k["hysteresis"] if hysteresis is None \
            else int(hysteresis)
        self.cooldown = k["cooldown"] if cooldown is None \
            else float(cooldown)
        self.slo_ms = k["slo_ms"] if slo_ms is None else float(slo_ms)
        if self.min_replicas > self.max_replicas:
            raise AutoscaleError("min_replicas %d > max_replicas %d"
                                 % (self.min_replicas, self.max_replicas))
        self._link = _TrackerLink(tracker_uri) if tracker_uri else None
        self._members = members_fn or \
            (lambda: self._link.rpc("members", {"role": "replica"}))
        self._actuate = actuate_fn or \
            (lambda d: self._link.rpc("scale_set", d))
        self._admin = admin_fn or _replica_admin
        self._event = event_fn or self._tracker_event
        self._now = now_fn
        self.desired = None         # learned from the fleet on first tick
        self.retired = set()        # ranks never to respawn
        self._up_streak = 0
        self._down_streak = 0
        self._last_action = None    # monotonic time of last scale action
        self._stop = threading.Event()

    # -- logging ------------------------------------------------------------
    def _say(self, msg):
        print("[autoscale] %s" % msg, flush=True)

    def _tracker_event(self, event, **fields):
        if self._link is None:
            return
        try:
            self._link.rpc("event", {
                "event": str(event),
                "fields": {str(k): str(v) for k, v in fields.items()}})
        except (TrackerError,) + _TRANSPORT_ERRORS:
            pass                    # timeline is telemetry, not control

    # -- one control step ----------------------------------------------------
    def _observe(self, members):
        """Fold the members view into (serving list, load, p99, occ).
        ``load`` is mean queued+in-flight per serving replica — the
        same gauge the router balances on."""
        serving, q = [], 0
        p99 = 0.0
        occ = 0.0
        for m in members:
            if not m.get("alive") or m.get("done"):
                continue
            if int(m.get("rank", -1)) in self.retired:
                continue
            info = m.get("info") or {}
            if info.get("state") != "serving":
                continue
            serving.append(m)
            q += int(info.get("queued", 0)) + int(info.get("inflight", 0))
            p99 = max(p99, float(info.get("p99_ms") or 0.0))
            occ = max(occ, float(info.get("gen_occupancy") or 0.0))
        load = (q / float(len(serving))) if serving else 0.0
        return serving, load, p99, occ

    def tick(self, now=None):
        """One control step. Returns "up"/"down" when a scale action
        was taken, else None."""
        chaos.autoscaler_fault()    # chaos: may hard-exit the controller
        now = self._now() if now is None else float(now)
        try:
            members = self._members()
        except Exception as e:      # noqa: BLE001 — fleet must outlive us
            profiler.autoscale_record(ticks=1, errors=1)
            self._say("members poll failed (%s: %s); fleet stays at "
                      "current size" % (type(e).__name__, e))
            return None
        serving, load, p99, occ = self._observe(members)
        if self.desired is None:
            self.desired = min(
                max(len(serving), self.min_replicas), self.max_replicas)
            self._say("adopted fleet: %d serving, desired=%d"
                      % (len(serving), self.desired))
        profiler.autoscale_record(ticks=1, replicas=len(serving),
                                  desired=self.desired)
        if not serving:
            return None             # nothing to read; launcher recovers
        slo_breach = self.slo_ms > 0 and p99 >= self.slo_ms
        over = (load >= self.up_load or slo_breach
                or occ >= self.GEN_OCCUPANCY_HIGH)
        under = not over and load <= self.down_load and not slo_breach
        if over:
            self._up_streak += 1
            self._down_streak = 0
        elif under:
            self._down_streak += 1
            self._up_streak = 0
        else:
            # dead band between the thresholds: reset both streaks so
            # a load oscillating around one threshold never acts
            self._up_streak = self._down_streak = 0
        if over and self.desired < self.max_replicas:
            return self._maybe(now, self._up_streak, self._scale_up,
                               load, p99)
        if under and self.desired > self.min_replicas \
                and len(serving) > self.min_replicas:
            return self._maybe(now, self._down_streak,
                               lambda n, l, p: self._scale_down(
                                   n, serving, l, p), load, p99)
        return None

    def _maybe(self, now, streak, action, load, p99):
        if streak < self.hysteresis:
            profiler.autoscale_record(holds_hysteresis=1)
            return None
        if self._last_action is not None \
                and now - self._last_action < self.cooldown:
            profiler.autoscale_record(holds_cooldown=1)
            return None
        return action(now, load, p99)

    def _push(self):
        self._actuate({"role": "replica", "desired": int(self.desired),
                       "retired": sorted(self.retired)})

    def _scale_up(self, now, load, p99):
        self.desired += 1
        self._push()
        self._last_action = now
        self._up_streak = self._down_streak = 0
        profiler.autoscale_record(decisions=1, scale_ups=1,
                                  desired=self.desired)
        self._say("scale-up -> desired=%d (load=%.2f p99=%.1fms)"
                  % (self.desired, load, p99))
        self._event("scale-up", desired=self.desired,
                    load="%.2f" % load, p99_ms="%.1f" % p99)
        return "up"

    def _scale_down(self, now, serving, load, p99):
        victim = max(serving, key=lambda m: int(m.get("rank", -1)))
        rank = int(victim["rank"])
        addr = victim.get("addr")
        # retire BEFORE touching the replica: once the launcher has
        # seen the rank in the directive it will never respawn it, so
        # any exit — clean stop or a SIGKILL mid-drain — is final
        self.retired.add(rank)
        self.desired -= 1
        self._push()
        self._last_action = now
        self._up_streak = self._down_streak = 0
        profiler.autoscale_record(decisions=1, scale_downs=1,
                                  desired=self.desired)
        self._say("scale-down -> desired=%d retiring rank=%d addr=%s "
                  "(load=%.2f p99=%.1fms)"
                  % (self.desired, rank, addr, load, p99))
        self._event("scale-down", desired=self.desired, rank=rank,
                    load="%.2f" % load, p99_ms="%.1f" % p99)
        try:
            self._admin(addr, "drain", {"deregister": True})
            self._admin(addr, "stop", {})
            profiler.autoscale_record(retires=1)
            self._say("retired rank=%d (drained, zero dropped)" % rank)
            self._event("scale-retired", rank=rank)
        except Exception as e:      # noqa: BLE001
            # the replica died under us (e.g. SIGKILL mid-drain). It is
            # already in the retired directive, so the launcher lets it
            # go — one retire, no respawn, no double-retire.
            profiler.autoscale_record(retire_races=1)
            self._say("retire race: rank=%d died mid-drain (%s: %s); "
                      "already retired, no respawn"
                      % (rank, type(e).__name__, e))
            self._event("scale-retire-race", rank=rank)
        return "down"

    # -- loop ---------------------------------------------------------------
    def run_forever(self):
        self._say("controller up: min=%d max=%d interval=%.2fs "
                  "up_load=%.2f down_load=%.2f hysteresis=%d "
                  "cooldown=%.1fs slo_ms=%.1f"
                  % (self.min_replicas, self.max_replicas, self.interval,
                     self.up_load, self.down_load, self.hysteresis,
                     self.cooldown, self.slo_ms))
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — keep ticking
                profiler.autoscale_record(errors=1)
                self._say("tick failed (%s: %s); continuing"
                          % (type(e).__name__, e))
            self._stop.wait(self.interval)

    def stop(self):
        self._stop.set()

    def close(self):
        self.stop()
        if self._link is not None:
            self._link.close()


# ---------------------------------------------------------------------------
# entrypoint: `fleet.main ["autoscaler", ...]` / python -m ... autoscale
# ---------------------------------------------------------------------------
def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="mxnet_tpu.serving.autoscale",
        description="Fleet autoscale controller (fail-static: killing "
                    "it leaves the fleet serving at its current size)")
    ap.add_argument("--tracker", required=True,
                    help="tracker URI host:port (the launch.py scheduler)")
    ap.add_argument("--min", type=int, default=None, dest="min_replicas")
    ap.add_argument("--max", type=int, default=None, dest="max_replicas")
    ap.add_argument("--interval", type=float, default=None)
    ap.add_argument("--up-load", type=float, default=None)
    ap.add_argument("--down-load", type=float, default=None)
    ap.add_argument("--hysteresis", type=int, default=None)
    ap.add_argument("--cooldown", type=float, default=None)
    ap.add_argument("--slo-ms", type=float, default=None)
    args = ap.parse_args(argv)
    scaler = FleetAutoscaler(
        tracker_uri=args.tracker, min_replicas=args.min_replicas,
        max_replicas=args.max_replicas, interval=args.interval,
        up_load=args.up_load, down_load=args.down_load,
        hysteresis=args.hysteresis, cooldown=args.cooldown,
        slo_ms=args.slo_ms)

    def _term(signum, frame):
        scaler.stop()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    try:
        scaler.run_forever()
    finally:
        scaler.close()
    print("[autoscale] controller stopped (fleet keeps serving)",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
