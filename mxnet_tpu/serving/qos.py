"""Multi-tenant admission QoS for the serving fleet (ISSUE 18).

The fleet (PR 11) treats every request identically — one overloaded
bulk consumer can push a latency-sensitive tenant's p99 over its SLO
before the router's overload machinery reacts. This module adds the
two controls production serving puts in front of a shared fleet:

- **Admission quotas.** Each tenant may carry a request-rate and a
  token-rate budget (tokens == rows for the dense tier: the leading
  batch dimension a request occupies in the bucket ladder). Budgets
  are token buckets refilled continuously and enforced at admission —
  the router/broker boundary — with the typed
  :class:`TenantQuotaExceeded`. A rejected request NEVER queues and is
  NEVER retried: the quota is the tenant's contract, not replica
  state, so retrying elsewhere would just spend the fleet's capacity
  circumventing it.
- **Priority classes.** ``latency`` < ``normal`` < ``bulk`` (lower
  sorts first). The class rides the wire with the request and decides
  *dequeue order* in the broker (``serving/broker.py``): a latency
  request jumps the queue ahead of queued bulk work, so under overload
  the bulk tenant's requests wait, expire, and are shed at dequeue
  (the PR 9 deadline discipline) before the latency tenant's p99
  moves. Nothing is preempted mid-batch — the isolation comes from
  ordering plus deadline shedding, both of which already existed.

Tenants are configured through ``MXNET_QOS_TENANTS`` (or the
equivalent ``tenants=`` dict)::

    MXNET_QOS_TENANTS="bulk:prio=bulk,req_rate=50,tok_rate=2000;\
interactive:prio=latency"

Grammar: ``tenant (';' tenant)*`` where ``tenant`` is
``name[:key=value(,key=value)*]`` and keys are ``prio``/``priority``
(latency|normal|bulk), ``req_rate`` (requests/s, float > 0) and
``tok_rate`` (rows/s, float > 0). An omitted budget is unlimited; an
unknown tenant gets the default priority and no quota. A malformed
spec raises :class:`~mxnet_tpu.base.MXNetError` naming the knob —
never a silently unprotected fleet.

Per-tenant counters (requests/admitted/quota_rejections/shed/rows and
a latency reservoir) ride ``profiler.qos_stats`` → ``dump_profile``
as ``qosStats``.
"""
from __future__ import annotations

import threading
import time

from .. import config, profiler
from .predictor import ServingError

#: priority classes, lower = served first. The broker sorts its queue
#: by this value at dequeue (stable — FIFO within a class).
PRIORITIES = {"latency": 0, "normal": 1, "bulk": 2}
DEFAULT_PRIORITY = PRIORITIES["normal"]


class TenantQuotaExceeded(ServingError):
    """A tenant's admission budget (request-rate or token-rate) is
    exhausted. Typed and TERMINAL: the request was never queued, and
    the router must not retry it on another replica — the quota is
    fleet-wide per tenant, not a property of the replica that said
    no. Wire kind: ``quota``."""

    def __init__(self, msg, tenant=None):
        super().__init__(msg)
        self.tenant = tenant


def _knob_burst():
    return config.get_positive_float("MXNET_QOS_BURST_SECONDS")


def _knob_default_priority():
    return PRIORITIES[config.get_choice("MXNET_QOS_DEFAULT_PRIORITY",
                                        tuple(PRIORITIES))]


class TokenBucket:
    """Continuous-refill token bucket: ``rate`` units/second with a
    burst capacity of ``rate * burst_seconds`` (>= 1 so a rate below
    1/burst still admits single requests eventually)."""

    __slots__ = ("rate", "capacity", "level", "t_last")

    def __init__(self, rate, burst_seconds=1.0):
        self.rate = float(rate)
        self.capacity = max(self.rate * float(burst_seconds), 1.0)
        self.level = self.capacity
        self.t_last = None

    def try_take(self, n, now):
        """Refill to ``now`` and take ``n`` units; False when the
        bucket cannot cover them (nothing is taken)."""
        if self.t_last is not None and now > self.t_last:
            self.level = min(self.capacity,
                             self.level + (now - self.t_last) * self.rate)
        self.t_last = now
        if n <= self.level + 1e-9:
            self.level -= n
            return True
        return False


class _Tenant:
    __slots__ = ("name", "priority", "req_bucket", "tok_bucket")

    def __init__(self, name, priority, req_rate, tok_rate, burst):
        self.name = name
        self.priority = priority
        self.req_bucket = TokenBucket(req_rate, burst) \
            if req_rate is not None else None
        self.tok_bucket = TokenBucket(tok_rate, burst) \
            if tok_rate is not None else None


def _spec_error(detail):
    from ..base import MXNetError

    raise MXNetError("MXNET_QOS_TENANTS: %s" % detail)


def parse_tenants(text):
    """``MXNET_QOS_TENANTS`` grammar -> {name: {"priority", "req_rate",
    "tok_rate"}}. Raises MXNetError naming the knob on any malformed
    piece — a fleet that silently dropped a tenant's quota would
    certify isolation that does not exist."""
    tenants = {}
    for chunk in (text or "").split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        name, _, tail = chunk.partition(":")
        name = name.strip()
        if not name:
            _spec_error("empty tenant name in %r" % chunk)
        if name in tenants:
            _spec_error("tenant %r configured twice" % name)
        spec = {"priority": None, "req_rate": None, "tok_rate": None}
        for kv in filter(None, (s.strip() for s in tail.split(","))):
            k, sep, v = kv.partition("=")
            if not sep or not k.strip() or not v.strip():
                _spec_error("bad parameter %r for tenant %r "
                            "(expected key=value)" % (kv, name))
            k, v = k.strip(), v.strip()
            if k in ("prio", "priority"):
                if v not in PRIORITIES:
                    _spec_error("tenant %r: priority %r not one of %s"
                                % (name, v, "|".join(PRIORITIES)))
                spec["priority"] = PRIORITIES[v]
            elif k in ("req_rate", "tok_rate"):
                try:
                    rate = float(v)
                except ValueError:
                    rate = float("nan")
                if not rate > 0:
                    _spec_error("tenant %r: %s=%r must be a float > 0"
                                % (name, k, v))
                spec[k] = rate
            else:
                _spec_error("tenant %r: unknown key %r (expected "
                            "prio|req_rate|tok_rate)" % (name, k))
        tenants[name] = spec
    return tenants


class QosPolicy:
    """Per-tenant admission policy: quotas + priority classes.

    Thread-safe; one instance guards one admission boundary (a
    FleetRouter, or a ReplicaServer for deployments with several
    routers). ``tenants`` maps name -> dict with optional ``priority``
    (int or class name), ``req_rate``, ``tok_rate``; when None the
    ``MXNET_QOS_TENANTS`` knob is parsed instead."""

    def __init__(self, tenants=None, default_priority=None,
                 burst_seconds=None):
        burst = _knob_burst() if burst_seconds is None \
            else float(burst_seconds)
        if not burst > 0:
            _spec_error("burst_seconds must be > 0, got %r" % burst_seconds)
        self._default_priority = _knob_default_priority() \
            if default_priority is None else self._as_priority(
                default_priority)
        if tenants is None:
            tenants = parse_tenants(config.get("MXNET_QOS_TENANTS"))
        self._lock = threading.Lock()
        self._tenants = {}
        for name, spec in tenants.items():
            prio = spec.get("priority")
            self._tenants[str(name)] = _Tenant(
                str(name),
                self._default_priority if prio is None
                else self._as_priority(prio),
                spec.get("req_rate"), spec.get("tok_rate"), burst)

    @staticmethod
    def _as_priority(value):
        if isinstance(value, str):
            if value not in PRIORITIES:
                _spec_error("priority %r not one of %s"
                            % (value, "|".join(PRIORITIES)))
            return PRIORITIES[value]
        v = int(value)
        if v not in PRIORITIES.values():
            _spec_error("priority %r not one of %r"
                        % (value, sorted(PRIORITIES.values())))
        return v

    @classmethod
    def from_env(cls):
        """Policy from ``MXNET_QOS_TENANTS``, or None when the knob is
        empty (no QoS boundary configured — zero per-request cost)."""
        tenants = parse_tenants(config.get("MXNET_QOS_TENANTS"))
        return cls(tenants=tenants) if tenants else None

    def tenants(self):
        with self._lock:
            return sorted(self._tenants)

    def priority_of(self, tenant):
        """The tenant's dequeue class (unknown tenants: the default)."""
        if tenant is None:
            return self._default_priority
        with self._lock:
            t = self._tenants.get(str(tenant))
        return self._default_priority if t is None else t.priority

    def admit(self, tenant, rows=1, now=None):
        """Charge one request of ``rows`` tokens against the tenant's
        budgets; returns the tenant's priority class. Raises the typed
        :class:`TenantQuotaExceeded` when either budget is exhausted —
        the caller must surface it, never queue or retry. Counted per
        tenant in ``qosStats``."""
        label = None if tenant is None else str(tenant)
        if label is not None:
            profiler.qos_record(label, requests=1)
        with self._lock:
            t = None if label is None else self._tenants.get(label)
            if t is None:
                if label is not None:
                    profiler.qos_record(label, admitted=1,
                                        rows=int(rows))
                return self._default_priority
            now = time.monotonic() if now is None else float(now)
            exhausted = None
            if t.req_bucket is not None and \
                    not t.req_bucket.try_take(1, now):
                exhausted = "request-rate (req_rate=%g/s)" \
                    % t.req_bucket.rate
            elif t.tok_bucket is not None and \
                    not t.tok_bucket.try_take(int(rows), now):
                exhausted = "token-rate (tok_rate=%g rows/s)" \
                    % t.tok_bucket.rate
            priority = t.priority
        if exhausted is not None:
            profiler.qos_record(label, quota_rejections=1)
            raise TenantQuotaExceeded(
                "tenant %r over its %s budget: request rejected at "
                "admission (never queued; do not retry elsewhere)"
                % (label, exhausted), tenant=label)
        profiler.qos_record(label, admitted=1, rows=int(rows))
        return priority
