"""PRNG resource management.

Parity surface: ``src/resource.cc`` (ResourceRequest::kRandom — per-context
PRNG engines handed to ops) and ``mx.random.seed``. TPU-native design: a
per-context splittable JAX PRNG key chain; every random op invocation draws
a fresh subkey, so imperative sampling is reproducible after
``mx.random.seed(n)`` and device-parallel sampling can fold in device ids.
"""
from __future__ import annotations

import threading

_STATE = threading.local()
_DEFAULT_SEED = 0


def _chains():
    if not hasattr(_STATE, "chains"):
        _STATE.chains = {}
    return _STATE.chains


def seed(seed_state, ctx=None):
    """Seed the framework RNG (parity: python/mxnet/random.py seed())."""
    global _DEFAULT_SEED
    import jax

    if ctx is None:
        _DEFAULT_SEED = int(seed_state)
        _chains().clear()
    else:
        _chains()[ctx] = jax.random.PRNGKey(int(seed_state))


def push_trace_key(key):
    """Install a traced PRNG key (used while tracing a hybridized block so
    random ops consume traced subkeys instead of concrete ones)."""
    if not hasattr(_STATE, "trace_stack"):
        _STATE.trace_stack = []
    _STATE.trace_stack.append(key)
    return len(_STATE.trace_stack) - 1


def pop_trace_key(token):
    _STATE.trace_stack.pop()


def next_key(ctx=None):
    """Draw a fresh PRNG key from the context's chain (or the active traced
    key inside a hybridize trace)."""
    import jax

    from .context import current_context

    stack = getattr(_STATE, "trace_stack", None)
    if stack:
        k1, k2 = jax.random.split(stack[-1])
        stack[-1] = k2
        return k1

    ctx = ctx or current_context()
    chains = _chains()
    if ctx not in chains:
        base = jax.random.PRNGKey(_DEFAULT_SEED)
        chains[ctx] = jax.random.fold_in(base, hash(ctx) % (2**31))
    key, chains[ctx] = jax.random.split(chains[ctx])
    return key


def current_key_state(ctx=None):
    from .context import current_context

    ctx = ctx or current_context()
    return _chains().get(ctx)
