"""PRNG resource management.

Parity surface: ``src/resource.cc`` (ResourceRequest::kRandom — per-context
PRNG engines handed to ops) and ``mx.random.seed``. TPU-native design: a
per-context splittable JAX PRNG key chain; every random op invocation draws
a fresh subkey, so imperative sampling is reproducible after
``mx.random.seed(n)`` and device-parallel sampling can fold in device ids.
"""
from __future__ import annotations

import threading

_STATE = threading.local()
_DEFAULT_SEED = 0


def _chains():
    if not hasattr(_STATE, "chains"):
        _STATE.chains = {}
    return _STATE.chains


def seed(seed_state, ctx=None):
    """Seed the framework RNG (parity: python/mxnet/random.py seed())."""
    global _DEFAULT_SEED
    import jax

    if ctx is None:
        global _INIT_RNG
        _DEFAULT_SEED = int(seed_state)
        _chains().clear()
        # the initializer zoo draws from a module-owned numpy RNG (the
        # reference's initializers run on the engine RNG that
        # mx.random.seed controls); reseeding it here makes seeded runs
        # reproducible end to end — including across processes — without
        # clobbering the user's global numpy RNG
        import numpy as _np

        _INIT_RNG = _np.random.RandomState(int(seed_state) & 0x7FFFFFFF)
    else:
        _chains()[ctx] = jax.random.PRNGKey(int(seed_state))


_INIT_RNG = None


def initializer_rng():
    """The numpy RandomState behind the initializer zoo. Unseeded runs
    draw fresh entropy; ``mx.random.seed`` reseeds it (reference
    parity: initializers follow the engine RNG that seed() controls)."""
    global _INIT_RNG
    if _INIT_RNG is None:
        import numpy as _np

        _INIT_RNG = _np.random.RandomState()
    return _INIT_RNG


def push_trace_key(key):
    """Install a traced PRNG key (used while tracing a hybridized block so
    random ops consume traced subkeys instead of concrete ones)."""
    if not hasattr(_STATE, "trace_stack"):
        _STATE.trace_stack = []
    _STATE.trace_stack.append(key)
    return len(_STATE.trace_stack) - 1


def pop_trace_key(token):
    _STATE.trace_stack.pop()


def next_key(ctx=None):
    """Draw a fresh PRNG key from the context's chain (or the active traced
    key inside a hybridize trace)."""
    import jax

    from .context import current_context

    stack = getattr(_STATE, "trace_stack", None)
    if stack:
        k1, k2 = jax.random.split(stack[-1])
        stack[-1] = k2
        return k1

    ctx = ctx or current_context()
    chains = _chains()
    if ctx not in chains:
        import zlib

        base = jax.random.PRNGKey(_DEFAULT_SEED)
        # deterministic per-context fold: python's hash() is salted per
        # process (PYTHONHASHSEED), which would make seeded runs diverge
        # across processes/restarts — crc32 of the stable repr is not
        chains[ctx] = jax.random.fold_in(
            base, zlib.crc32(repr(ctx).encode()) % (2**31))
    key, chains[ctx] = jax.random.split(chains[ctx])
    return key


def current_key_state(ctx=None):
    from .context import current_context

    ctx = ctx or current_context()
    return _chains().get(ctx)
