"""Automatic naming of symbols/blocks (``mx.name``).

Reference counterpart: ``python/mxnet/name.py`` — ``NameManager`` context
assigns ``convolution0``-style names; ``Prefix`` prepends a scope prefix.
``base.auto_name`` consults the innermost active manager.
"""
from __future__ import annotations

import threading

from . import base

__all__ = ["NameManager", "Prefix", "current"]

_TLS = threading.local()


def _stack():
    if not hasattr(_TLS, "stack"):
        _TLS.stack = []
    return _TLS.stack


def current():
    """The innermost active NameManager, or None."""
    st = _stack()
    return st[-1] if st else None


class NameManager:
    """Assigns per-prefix sequential names (ref name.py NameManager)."""

    def __init__(self):
        self._counter = {}
        self._old_scope = None

    def get(self, name, hint):
        """Explicit name wins; otherwise hint + counter."""
        if name:
            return name
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return "%s%d" % (hint, idx)

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, ptype, value, trace):
        _stack().pop()
        return False


class Prefix(NameManager):
    """Prefixes every auto name (ref name.py Prefix)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return name if name else self._prefix + super().get(None, hint)


def _auto_name(hint):
    """Hook used by base.auto_name: route through the active manager."""
    mgr = current()
    if mgr is not None:
        return mgr.get(None, hint.lower())
    return base._NAME_COUNTER.get(hint.lower())
