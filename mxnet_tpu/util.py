"""Misc utilities (parity shims for python/mxnet/util.py)."""


def is_np_array():
    return False


def is_np_shape():
    return False


def makedirs(d):
    import os

    os.makedirs(d, exist_ok=True)


def get_gpu_count():
    from .context import num_tpus

    return num_tpus()


def shard_map(f, mesh, in_specs, out_specs, check_vma=None):
    """Version-portable ``shard_map``.

    Newer jax exposes ``jax.shard_map(..., check_vma=)``; 0.4.x only
    has ``jax.experimental.shard_map.shard_map(..., check_rep=)``.
    Every shard_map in this codebase goes through here so the library
    imports (and the CPU test mesh runs) on both."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)
    from jax.experimental.shard_map import shard_map as legacy

    kw = {} if check_vma is None else {"check_rep": check_vma}
    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)
