"""Misc utilities (parity shims for python/mxnet/util.py)."""


def is_np_array():
    return False


def is_np_shape():
    return False


def makedirs(d):
    import os

    os.makedirs(d, exist_ok=True)


def get_gpu_count():
    from .context import num_tpus

    return num_tpus()
