"""ResNeXt symbolic builder (aggregated residual transforms).

Reference counterpart: ``example/image-classification/symbols/resnext.py``
(the 0.7911 top-1 resnext-101-64x4d row, README.md:131). Grouped 3x3
convs carry the cardinality (Xie 2016).
"""
from .. import symbol as sym
from ..base import MXNetError


def _unit(data, num_filter, stride, dim_match, name, num_group=32,
          bottle_mult=0.5, bn_mom=0.9):
    mid = int(num_filter * bottle_mult)
    c1 = sym.Convolution(data=data, num_filter=mid, kernel=(1, 1),
                         no_bias=True, name=name + "_conv1")
    b1 = sym.BatchNorm(data=c1, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                       name=name + "_bn1")
    a1 = sym.Activation(data=b1, act_type="relu", name=name + "_relu1")
    c2 = sym.Convolution(data=a1, num_filter=mid, kernel=(3, 3),
                         stride=stride, pad=(1, 1), num_group=num_group,
                         no_bias=True, name=name + "_conv2")
    b2 = sym.BatchNorm(data=c2, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                       name=name + "_bn2")
    a2 = sym.Activation(data=b2, act_type="relu", name=name + "_relu2")
    c3 = sym.Convolution(data=a2, num_filter=num_filter, kernel=(1, 1),
                         no_bias=True, name=name + "_conv3")
    b3 = sym.BatchNorm(data=c3, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                       name=name + "_bn3")
    if dim_match:
        sc = data
    else:
        sc = sym.Convolution(data=data, num_filter=num_filter, kernel=(1, 1),
                             stride=stride, no_bias=True, name=name + "_sc")
        sc = sym.BatchNorm(data=sc, fix_gamma=False, eps=2e-5,
                           momentum=bn_mom, name=name + "_sc_bn")
    return sym.Activation(data=b3 + sc, act_type="relu", name=name + "_out")


def get_symbol(num_classes=1000, num_layers=50, num_group=32,
               image_shape=(3, 224, 224), **kwargs):
    if num_layers == 50:
        units = [3, 4, 6, 3]
    elif num_layers == 101:
        units = [3, 4, 23, 3]
    elif num_layers == 152:
        units = [3, 8, 36, 3]
    else:
        raise MXNetError("resnext: unsupported depth %d" % num_layers)
    filters = [256, 512, 1024, 2048]

    data = sym.var("data")
    x = sym.Convolution(data=data, num_filter=64, kernel=(7, 7),
                        stride=(2, 2), pad=(3, 3), no_bias=True, name="conv0")
    x = sym.BatchNorm(data=x, fix_gamma=False, eps=2e-5, name="bn0")
    x = sym.Activation(data=x, act_type="relu", name="relu0")
    x = sym.Pooling(data=x, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                    pool_type="max")
    for stage, (n, f) in enumerate(zip(units, filters), 1):
        for i in range(1, n + 1):
            stride = (1, 1) if stage == 1 or i > 1 else (2, 2)
            x = _unit(x, f, stride, dim_match=(i > 1),
                      name="stage%d_unit%d" % (stage, i),
                      num_group=num_group)
    x = sym.Pooling(data=x, global_pool=True, kernel=(7, 7), pool_type="avg")
    fc = sym.FullyConnected(data=sym.Flatten(data=x),
                            num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=fc, name="softmax")
