"""MobileNet v1 symbolic builder.

Reference counterpart: ``example/image-classification/symbols/
mobilenet.py`` (Howard 2017). Depthwise convs use num_group=channels —
XLA lowers these to feature-group convolutions on the MXU.
"""
from .. import symbol as sym


def _conv_bn(data, num_filter, kernel, stride, pad, name, num_group=1):
    c = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, num_group=num_group,
                        no_bias=True, name=name)
    b = sym.BatchNorm(data=c, fix_gamma=False, name=name + "_bn")
    return sym.Activation(data=b, act_type="relu", name=name + "_relu")


def _dw_sep(data, in_ch, out_ch, stride, name):
    dw = _conv_bn(data, in_ch, (3, 3), stride, (1, 1), name + "_dw",
                  num_group=in_ch)
    return _conv_bn(dw, out_ch, (1, 1), (1, 1), (0, 0), name + "_pw")


def get_symbol(num_classes=1000, multiplier=1.0, **kwargs):
    def ch(n):
        return max(8, int(n * multiplier))

    data = sym.var("data")
    x = _conv_bn(data, ch(32), (3, 3), (2, 2), (1, 1), "conv1")
    cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
           (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
          [(512, 1024, 2), (1024, 1024, 1)]
    for i, (cin, cout, s) in enumerate(cfg, 2):
        x = _dw_sep(x, ch(cin), ch(cout), (s, s), "conv%d" % i)
    x = sym.Pooling(data=x, global_pool=True, kernel=(7, 7), pool_type="avg")
    fc = sym.FullyConnected(data=sym.Flatten(data=x),
                            num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=fc, name="softmax")
