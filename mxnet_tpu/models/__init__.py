"""Symbolic model builders (ref: example/image-classification/symbols/).

Each module exposes ``get_symbol(num_classes, ...)`` returning a Symbol
with a ``SoftmaxOutput`` head, matching the reference example zoo that the
Module training scripts consume. The Gluon model zoo lives separately in
``gluon/model_zoo``.
"""
from . import lenet, mlp, resnet  # noqa: F401

_BUILDERS = {
    "mlp": mlp,
    "lenet": lenet,
    "resnet": resnet,
}


def get_symbol(network, **kwargs):
    """Dispatch like the reference's train scripts:
    ``importlib.import_module('symbols.' + args.network).get_symbol(...)``."""
    if network not in _BUILDERS:
        raise ValueError("unknown network %r; have %s" % (network, sorted(_BUILDERS)))
    return _BUILDERS[network].get_symbol(**kwargs)
