"""Symbolic model builders (ref: example/image-classification/symbols/).

Each module exposes ``get_symbol(num_classes, ...)`` returning a Symbol
with a ``SoftmaxOutput`` head, matching the reference example zoo that the
Module training scripts consume. The Gluon model zoo lives separately in
``gluon/model_zoo``; the transformer/LLM family (the TPU-native
long-context flagship) in ``transformer.py``.
"""
from . import (  # noqa: F401
    alexnet, bench_transformer, inception, lenet, mlp, mobilenet, resnet,
    resnext, ssd, vgg,
)

_BUILDERS = {
    "mlp": mlp,
    "bench-transformer": bench_transformer,
    "lenet": lenet,
    "resnet": resnet,
    "resnext": resnext,
    "alexnet": alexnet,
    "vgg": vgg,
    "mobilenet": mobilenet,
    "inception-v3": inception,
    "inception-bn": inception,
    "googlenet": inception,
    "ssd": ssd,
}
_VERSION_KW = {"inception-v3": "v3", "inception-bn": "bn",
               "googlenet": "v1"}


def get_symbol(network, **kwargs):
    """Dispatch like the reference's train scripts:
    ``importlib.import_module('symbols.' + args.network).get_symbol(...)``."""
    if network not in _BUILDERS:
        raise ValueError("unknown network %r; have %s" % (network, sorted(_BUILDERS)))
    if network in _VERSION_KW:
        kwargs.setdefault("version", _VERSION_KW[network])
    return _BUILDERS[network].get_symbol(**kwargs)
