"""Inception family: GoogLeNet (v1), Inception-BN (v2), Inception-v3.

Reference counterparts: ``example/image-classification/symbols/
{googlenet.py, inception-bn.py, inception-v3.py}`` — inception-bn is the
152 img/s K80 baseline row (README.md:152), inception-v3 the 30.4→6,661
img/s scaling row. Architectures per Szegedy 2014/2015; rebuilt with
the same factorized-conv structure (all convs MXU-shaped).
"""
from .. import symbol as sym


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------
def _conv(data, num_filter, kernel, stride=(1, 1), pad=(0, 0), name=None,
          with_bn=True, suffix=""):
    conv = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad, no_bias=with_bn,
                           name="%s%s_conv" % (name, suffix))
    if with_bn:
        conv = sym.BatchNorm(data=conv, fix_gamma=False, eps=1e-3,
                             name="%s%s_bn" % (name, suffix))
    return sym.Activation(data=conv, act_type="relu",
                          name="%s%s_relu" % (name, suffix))


# ---------------------------------------------------------------------------
# GoogLeNet (v1, no BN)
# ---------------------------------------------------------------------------
def _v1_block(data, name, f1, f3r, f3, f5r, f5, proj):
    p1 = _conv(data, f1, (1, 1), name=name + "_1x1", with_bn=False)
    p3 = _conv(data, f3r, (1, 1), name=name + "_3x3r", with_bn=False)
    p3 = _conv(p3, f3, (3, 3), pad=(1, 1), name=name + "_3x3", with_bn=False)
    p5 = _conv(data, f5r, (1, 1), name=name + "_5x5r", with_bn=False)
    p5 = _conv(p5, f5, (5, 5), pad=(2, 2), name=name + "_5x5", with_bn=False)
    pp = sym.Pooling(data=data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                     pool_type="max", name=name + "_pool")
    pp = _conv(pp, proj, (1, 1), name=name + "_proj", with_bn=False)
    return sym.Concat(p1, p3, p5, pp, dim=1, name=name + "_concat")


def get_googlenet(num_classes=1000, **kwargs):
    data = sym.var("data")
    x = _conv(data, 64, (7, 7), (2, 2), (3, 3), name="conv1", with_bn=False)
    x = sym.Pooling(data=x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = _conv(x, 64, (1, 1), name="conv2r", with_bn=False)
    x = _conv(x, 192, (3, 3), pad=(1, 1), name="conv2", with_bn=False)
    x = sym.Pooling(data=x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = _v1_block(x, "in3a", 64, 96, 128, 16, 32, 32)
    x = _v1_block(x, "in3b", 128, 128, 192, 32, 96, 64)
    x = sym.Pooling(data=x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = _v1_block(x, "in4a", 192, 96, 208, 16, 48, 64)
    x = _v1_block(x, "in4b", 160, 112, 224, 24, 64, 64)
    x = _v1_block(x, "in4c", 128, 128, 256, 24, 64, 64)
    x = _v1_block(x, "in4d", 112, 144, 288, 32, 64, 64)
    x = _v1_block(x, "in4e", 256, 160, 320, 32, 128, 128)
    x = sym.Pooling(data=x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = _v1_block(x, "in5a", 256, 160, 320, 32, 128, 128)
    x = _v1_block(x, "in5b", 384, 192, 384, 48, 128, 128)
    x = sym.Pooling(data=x, global_pool=True, kernel=(7, 7), pool_type="avg")
    x = sym.Dropout(data=sym.Flatten(data=x), p=0.4)
    fc = sym.FullyConnected(data=x, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=fc, name="softmax")


# ---------------------------------------------------------------------------
# Inception-BN (v2)
# ---------------------------------------------------------------------------
def _bn_block(data, name, f1, f3r, f3, d3r, d3, proj, pool="avg",
              stride=(1, 1)):
    parts = []
    if f1 > 0:
        parts.append(_conv(data, f1, (1, 1), name=name + "_1x1"))
    p3 = _conv(data, f3r, (1, 1), name=name + "_3x3r")
    parts.append(_conv(p3, f3, (3, 3), stride=stride, pad=(1, 1),
                       name=name + "_3x3"))
    pd = _conv(data, d3r, (1, 1), name=name + "_d3x3r")
    pd = _conv(pd, d3, (3, 3), pad=(1, 1), name=name + "_d3x3a")
    parts.append(_conv(pd, d3, (3, 3), stride=stride, pad=(1, 1),
                       name=name + "_d3x3b"))
    pp = sym.Pooling(data=data, kernel=(3, 3), stride=stride, pad=(1, 1),
                     pool_type=pool, name=name + "_pool")
    if proj > 0:
        pp = _conv(pp, proj, (1, 1), name=name + "_proj")
    parts.append(pp)
    return sym.Concat(*parts, dim=1, name=name + "_concat")


def get_inception_bn(num_classes=1000, **kwargs):
    data = sym.var("data")
    x = _conv(data, 64, (7, 7), (2, 2), (3, 3), name="conv1")
    x = sym.Pooling(data=x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = _conv(x, 64, (1, 1), name="conv2r")
    x = _conv(x, 192, (3, 3), pad=(1, 1), name="conv2")
    x = sym.Pooling(data=x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = _bn_block(x, "in3a", 64, 64, 64, 64, 96, 32)
    x = _bn_block(x, "in3b", 64, 64, 96, 64, 96, 64)
    x = _bn_block(x, "in3c", 0, 128, 160, 64, 96, 0, pool="max",
                  stride=(2, 2))
    x = _bn_block(x, "in4a", 224, 64, 96, 96, 128, 128)
    x = _bn_block(x, "in4b", 192, 96, 128, 96, 128, 128)
    x = _bn_block(x, "in4c", 160, 128, 160, 128, 160, 128)
    x = _bn_block(x, "in4d", 96, 128, 192, 160, 192, 128)
    x = _bn_block(x, "in4e", 0, 128, 192, 192, 256, 0, pool="max",
                  stride=(2, 2))
    x = _bn_block(x, "in5a", 352, 192, 320, 160, 224, 128)
    x = _bn_block(x, "in5b", 352, 192, 320, 192, 224, 128, pool="max")
    x = sym.Pooling(data=x, global_pool=True, kernel=(7, 7), pool_type="avg")
    fc = sym.FullyConnected(data=sym.Flatten(data=x),
                            num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=fc, name="softmax")


# ---------------------------------------------------------------------------
# Inception-v3
# ---------------------------------------------------------------------------
def _v3_a(data, name, proj):
    p1 = _conv(data, 64, (1, 1), name=name + "_1x1")
    p5 = _conv(data, 48, (1, 1), name=name + "_5x5r")
    p5 = _conv(p5, 64, (5, 5), pad=(2, 2), name=name + "_5x5")
    p3 = _conv(data, 64, (1, 1), name=name + "_3x3r")
    p3 = _conv(p3, 96, (3, 3), pad=(1, 1), name=name + "_3x3a")
    p3 = _conv(p3, 96, (3, 3), pad=(1, 1), name=name + "_3x3b")
    pp = sym.Pooling(data=data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                     pool_type="avg", name=name + "_pool")
    pp = _conv(pp, proj, (1, 1), name=name + "_proj")
    return sym.Concat(p1, p5, p3, pp, dim=1, name=name + "_concat")


def _v3_b(data, name):  # grid reduction 35→17
    p3 = _conv(data, 384, (3, 3), (2, 2), name=name + "_3x3")
    pd = _conv(data, 64, (1, 1), name=name + "_d3x3r")
    pd = _conv(pd, 96, (3, 3), pad=(1, 1), name=name + "_d3x3a")
    pd = _conv(pd, 96, (3, 3), (2, 2), name=name + "_d3x3b")
    pp = sym.Pooling(data=data, kernel=(3, 3), stride=(2, 2),
                     pool_type="max", name=name + "_pool")
    return sym.Concat(p3, pd, pp, dim=1, name=name + "_concat")


def _v3_c(data, name, f7):  # factorized 7x7
    p1 = _conv(data, 192, (1, 1), name=name + "_1x1")
    p7 = _conv(data, f7, (1, 1), name=name + "_7x7r")
    p7 = _conv(p7, f7, (1, 7), pad=(0, 3), name=name + "_1x7")
    p7 = _conv(p7, 192, (7, 1), pad=(3, 0), name=name + "_7x1")
    pd = _conv(data, f7, (1, 1), name=name + "_d7r")
    pd = _conv(pd, f7, (7, 1), pad=(3, 0), name=name + "_d7x1a")
    pd = _conv(pd, f7, (1, 7), pad=(0, 3), name=name + "_d1x7a")
    pd = _conv(pd, f7, (7, 1), pad=(3, 0), name=name + "_d7x1b")
    pd = _conv(pd, 192, (1, 7), pad=(0, 3), name=name + "_d1x7b")
    pp = sym.Pooling(data=data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                     pool_type="avg", name=name + "_pool")
    pp = _conv(pp, 192, (1, 1), name=name + "_proj")
    return sym.Concat(p1, p7, pd, pp, dim=1, name=name + "_concat")


def _v3_d(data, name):  # grid reduction 17→8
    p3 = _conv(data, 192, (1, 1), name=name + "_3x3r")
    p3 = _conv(p3, 320, (3, 3), (2, 2), name=name + "_3x3")
    p7 = _conv(data, 192, (1, 1), name=name + "_7x7r")
    p7 = _conv(p7, 192, (1, 7), pad=(0, 3), name=name + "_1x7")
    p7 = _conv(p7, 192, (7, 1), pad=(3, 0), name=name + "_7x1")
    p7 = _conv(p7, 192, (3, 3), (2, 2), name=name + "_3x3b")
    pp = sym.Pooling(data=data, kernel=(3, 3), stride=(2, 2),
                     pool_type="max", name=name + "_pool")
    return sym.Concat(p3, p7, pp, dim=1, name=name + "_concat")


def _v3_e(data, name):  # expanded filter bank
    p1 = _conv(data, 320, (1, 1), name=name + "_1x1")
    p3 = _conv(data, 384, (1, 1), name=name + "_3x3r")
    p3a = _conv(p3, 384, (1, 3), pad=(0, 1), name=name + "_1x3")
    p3b = _conv(p3, 384, (3, 1), pad=(1, 0), name=name + "_3x1")
    pd = _conv(data, 448, (1, 1), name=name + "_d3r")
    pd = _conv(pd, 384, (3, 3), pad=(1, 1), name=name + "_d3")
    pda = _conv(pd, 384, (1, 3), pad=(0, 1), name=name + "_d1x3")
    pdb = _conv(pd, 384, (3, 1), pad=(1, 0), name=name + "_d3x1")
    pp = sym.Pooling(data=data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                     pool_type="avg", name=name + "_pool")
    pp = _conv(pp, 192, (1, 1), name=name + "_proj")
    return sym.Concat(p1, p3a, p3b, pda, pdb, pp, dim=1,
                      name=name + "_concat")


def get_inception_v3(num_classes=1000, **kwargs):
    data = sym.var("data")
    x = _conv(data, 32, (3, 3), (2, 2), name="conv1")
    x = _conv(x, 32, (3, 3), name="conv2")
    x = _conv(x, 64, (3, 3), pad=(1, 1), name="conv3")
    x = sym.Pooling(data=x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = _conv(x, 80, (1, 1), name="conv4")
    x = _conv(x, 192, (3, 3), name="conv5")
    x = sym.Pooling(data=x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = _v3_a(x, "in_a1", 32)
    x = _v3_a(x, "in_a2", 64)
    x = _v3_a(x, "in_a3", 64)
    x = _v3_b(x, "in_b")
    x = _v3_c(x, "in_c1", 128)
    x = _v3_c(x, "in_c2", 160)
    x = _v3_c(x, "in_c3", 160)
    x = _v3_c(x, "in_c4", 192)
    x = _v3_d(x, "in_d")
    x = _v3_e(x, "in_e1")
    x = _v3_e(x, "in_e2")
    x = sym.Pooling(data=x, global_pool=True, kernel=(8, 8), pool_type="avg")
    x = sym.Dropout(data=sym.Flatten(data=x), p=0.5)
    fc = sym.FullyConnected(data=x, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=fc, name="softmax")


def get_symbol(num_classes=1000, version="v3", **kwargs):
    if version in ("v1", "googlenet"):
        return get_googlenet(num_classes, **kwargs)
    if version in ("bn", "v2"):
        return get_inception_bn(num_classes, **kwargs)
    return get_inception_v3(num_classes, **kwargs)
