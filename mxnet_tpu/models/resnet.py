"""ResNet v2 (pre-activation) symbolic builder.

Reference counterpart: ``example/image-classification/symbols/resnet.py``
(the network behind the 109 img/s K80 baseline and the 0.7527 top-1
target, example/image-classification/README.md:121-156). Same stage/unit
structure ("resnet-N" per depth table) rebuilt TPU-first: every conv lands
on the MXU via XLA; BatchNorm uses fix_gamma=False and eps/momentum parity.
"""
from .. import symbol as sym


def residual_unit(data, num_filter, stride, dim_match, name, bottle_neck=True,
                  bn_mom=0.9):
    if bottle_neck:
        bn1 = sym.BatchNorm(data=data, fix_gamma=False, eps=2e-5, momentum=bn_mom, name=name + "_bn1")
        act1 = sym.Activation(data=bn1, act_type="relu", name=name + "_relu1")
        conv1 = sym.Convolution(data=act1, num_filter=int(num_filter * 0.25), kernel=(1, 1),
                                stride=(1, 1), pad=(0, 0), no_bias=True, name=name + "_conv1")
        bn2 = sym.BatchNorm(data=conv1, fix_gamma=False, eps=2e-5, momentum=bn_mom, name=name + "_bn2")
        act2 = sym.Activation(data=bn2, act_type="relu", name=name + "_relu2")
        conv2 = sym.Convolution(data=act2, num_filter=int(num_filter * 0.25), kernel=(3, 3),
                                stride=stride, pad=(1, 1), no_bias=True, name=name + "_conv2")
        bn3 = sym.BatchNorm(data=conv2, fix_gamma=False, eps=2e-5, momentum=bn_mom, name=name + "_bn3")
        act3 = sym.Activation(data=bn3, act_type="relu", name=name + "_relu3")
        conv3 = sym.Convolution(data=act3, num_filter=num_filter, kernel=(1, 1), stride=(1, 1),
                                pad=(0, 0), no_bias=True, name=name + "_conv3")
        if dim_match:
            shortcut = data
        else:
            shortcut = sym.Convolution(data=act1, num_filter=num_filter, kernel=(1, 1),
                                       stride=stride, no_bias=True, name=name + "_sc")
        return conv3 + shortcut
    bn1 = sym.BatchNorm(data=data, fix_gamma=False, eps=2e-5, momentum=bn_mom, name=name + "_bn1")
    act1 = sym.Activation(data=bn1, act_type="relu", name=name + "_relu1")
    conv1 = sym.Convolution(data=act1, num_filter=num_filter, kernel=(3, 3), stride=stride,
                            pad=(1, 1), no_bias=True, name=name + "_conv1")
    bn2 = sym.BatchNorm(data=conv1, fix_gamma=False, eps=2e-5, momentum=bn_mom, name=name + "_bn2")
    act2 = sym.Activation(data=bn2, act_type="relu", name=name + "_relu2")
    conv2 = sym.Convolution(data=act2, num_filter=num_filter, kernel=(3, 3), stride=(1, 1),
                            pad=(1, 1), no_bias=True, name=name + "_conv2")
    if dim_match:
        shortcut = data
    else:
        shortcut = sym.Convolution(data=act1, num_filter=num_filter, kernel=(1, 1),
                                   stride=stride, no_bias=True, name=name + "_sc")
    return conv2 + shortcut


def resnet(units, num_stages, filter_list, num_classes, image_shape,
           bottle_neck=True, bn_mom=0.9, fused=False):
    num_unit = len(units)
    assert num_unit == num_stages
    data = sym.var("data")
    nchannel, height, _ = image_shape
    data = sym.identity(data=data, name="id")
    body = data
    if height <= 32:  # cifar
        body = sym.Convolution(data=body, num_filter=filter_list[0], kernel=(3, 3),
                               stride=(1, 1), pad=(1, 1), no_bias=True, name="conv0")
    else:  # imagenet
        body = sym.Convolution(data=body, num_filter=filter_list[0], kernel=(7, 7),
                               stride=(2, 2), pad=(3, 3), no_bias=True, name="conv0")
        body = sym.BatchNorm(data=body, fix_gamma=False, eps=2e-5, momentum=bn_mom, name="bn0")
        body = sym.Activation(data=body, act_type="relu", name="relu0")
        body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2), pad=(1, 1), pool_type="max")

    for i in range(num_stages):
        stride = (1, 1) if i == 0 else (2, 2)
        body = residual_unit(body, filter_list[i + 1], stride, False,
                             name="stage%d_unit%d" % (i + 1, 1), bottle_neck=bottle_neck,
                             bn_mom=bn_mom)
        for j in range(units[i] - 1):
            body = residual_unit(body, filter_list[i + 1], (1, 1), True,
                                 name="stage%d_unit%d" % (i + 1, j + 2),
                                 bottle_neck=bottle_neck, bn_mom=bn_mom)
    bn1 = sym.BatchNorm(data=body, fix_gamma=False, eps=2e-5, momentum=bn_mom, name="bn1")
    relu1 = sym.Activation(data=bn1, act_type="relu", name="relu1")
    pool1 = sym.Pooling(data=relu1, global_pool=True, kernel=(7, 7), pool_type="avg", name="pool1")
    flat = sym.Flatten(data=pool1)
    fc1 = sym.FullyConnected(data=flat, num_hidden=num_classes, name="fc1")
    out = sym.SoftmaxOutput(data=fc1, name="softmax")
    if fused and bottle_neck and _fuse_enabled():
        # rule-based fusion (ISSUE 13): the builder always emits the
        # unfused graph; the IR fusion pass recognizes each bottleneck
        # unit and rewrites it to FusedBottleneckUnit, with the
        # transpose-cancel rule merging the per-unit NHWC brackets
        # into one pair around the whole residual stack — bit-exactly
        # the graph the old fused=True branch emitted by hand.
        from .. import ir

        out = ir.apply_passes(out, passes=("fusion",))
    return out


def _fuse_enabled():
    from .. import config

    return config.get_strict_bool("MXNET_IR_FUSE")


_IMAGENET_DEPTHS = {
    18: ([2, 2, 2, 2], False),
    34: ([3, 4, 6, 3], False),
    50: ([3, 4, 6, 3], True),
    101: ([3, 4, 23, 3], True),
    152: ([3, 8, 36, 3], True),
    200: ([3, 24, 36, 3], True),
    269: ([3, 30, 48, 8], True),
}


def get_symbol(num_classes=1000, num_layers=50, image_shape=(3, 224, 224), **kwargs):
    """Depth table parity with the reference resnet.py get_symbol."""
    if isinstance(image_shape, str):
        image_shape = tuple(int(x) for x in image_shape.split(","))
    height = image_shape[1]
    if height <= 32:
        num_stages = 3
        if (num_layers - 2) % 9 == 0 and num_layers >= 164:
            per_unit = [(num_layers - 2) // 9]
            bottle_neck = True
        elif (num_layers - 2) % 6 == 0 and num_layers < 164:
            per_unit = [(num_layers - 2) // 6]
            bottle_neck = False
        else:
            raise ValueError("no experiments done on num_layers %d" % num_layers)
        units = per_unit * num_stages
        filter_list = [16, 16, 32, 64] if not bottle_neck else [16, 64, 128, 256]
    else:
        num_stages = 4
        if num_layers not in _IMAGENET_DEPTHS:
            raise ValueError("no experiments done on num_layers %d" % num_layers)
        units, bottle_neck = _IMAGENET_DEPTHS[num_layers]
        filter_list = [64, 256, 512, 1024, 2048] if bottle_neck else [64, 64, 128, 256, 512]
    return resnet(units=units, num_stages=num_stages, filter_list=filter_list,
                  num_classes=num_classes, image_shape=image_shape,
                  bottle_neck=bottle_neck, **kwargs)
