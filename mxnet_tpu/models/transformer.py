"""Decoder-only transformer LM with explicit dp/tp/sp/ep SPMD sharding.

Reference counterpart: none architecturally (2017 predates transformers) —
this is the long-context / distributed flagship the survey mandates
(SURVEY §2.4, §5.7): the natural TPU generalization of the reference's
parallelism surface, exercising every mesh axis with *manual* SPMD
(`shard_map`) the way Megatron sharded layers map onto a TPU mesh:

- **dp**  batch sharding; gradient psum comes out of shard_map's
  unvarying-param transpose automatically.
- **mp**  megatron tensor parallel (ISSUE 20; ``tp`` is the legacy
  alias — whichever axis the mesh carries is resolved by
  :func:`_mp_axis`): vocab- and head-sharded embedding / qkv (column),
  row-parallel out-proj and ffn-down with ONE psum per block half —
  2 psums per block, asserted exact by
  :func:`block_collective_counts`.
- **sp**  sequence sharding with ring attention (parallel/ring.py) —
  K/V chunks ride ICI collective-permute while the MXU works.
- **ep**  expert parallel MoE ffn (soft top-k gating, experts sharded
  over ``ep``, combine via psum).

The attention core is the Pallas flash kernel (kernels/flash_attention.py)
when heads are local (tp/ulysses path) and the ring online-softmax when
sequence-sharded.

Pure-functional: ``init_params`` → flat dict, ``make_loss_fn`` returns a
shard_map'd scalar loss ready for ``jax.value_and_grad`` + pjit update
(spmd.TrainStep's functional cousin). Layer params are stacked over the
layer dim and scanned (one compiled block, XLA-friendly).
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..util import shard_map as _shard_map

from ..parallel.ring import ring_attention_inner, full_attention

__all__ = ["TransformerConfig", "init_params", "param_specs", "make_loss_fn",
           "make_train_step", "make_forward_fn", "init_kv_cache",
           "make_prefill_fn", "make_decode_fn", "make_extend_fn",
           "draft_from_layers", "decode_schedule_shape",
           "block_collective_counts", "kv_cache_spec"]


def _mp_axis(axes):
    """The tensor-parallel axis this mesh carries: ``mp`` (ISSUE 20),
    falling back to the legacy ``tp`` alias; None when the mesh has
    neither (the replicated-model path)."""
    if "mp" in axes:
        return "mp"
    if "tp" in axes:
        return "tp"
    return None


@dataclasses.dataclass
class TransformerConfig:
    vocab: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_len: int = 2048
    n_experts: int = 0          # 0 → dense ffn; >0 → MoE every layer
    dtype: str = "bfloat16"     # compute dtype (params stay fp32)
    attn: str = "auto"          # auto|ring|ulysses|full
    remat: bool = False
    # flash-attention schedule parameters (ISSUE 10): None consults the
    # on-disk schedule table at trace time (tune.schedule_for, keyed by
    # this model's attention shape/dtype/backend) and falls back to the
    # MXU-native 128; an explicit value pins the block
    attn_block_q: int | None = None
    attn_block_k: int | None = None


def init_params(config, seed=0):
    """Flat fp32 param dict; layer params stacked on a leading L dim."""
    c = config
    rng = np.random.RandomState(seed)
    dh = c.d_model // c.n_heads

    def norm(*shape, scale=0.02):
        return rng.normal(0.0, scale, shape).astype(np.float32)

    p = {
        "embed_weight": norm(c.vocab, c.d_model),
        "pos_embed_weight": norm(c.max_len, c.d_model),
        "final_ln_gamma": np.ones((c.d_model,), np.float32),
        "final_ln_beta": np.zeros((c.d_model,), np.float32),
    }
    L = c.n_layers
    p["ln1_gamma"] = np.ones((L, c.d_model), np.float32)
    p["ln1_beta"] = np.zeros((L, c.d_model), np.float32)
    p["ln2_gamma"] = np.ones((L, c.d_model), np.float32)
    p["ln2_beta"] = np.zeros((L, c.d_model), np.float32)
    p["attn_qkv_weight"] = norm(L, c.d_model, 3, c.n_heads, dh)
    p["attn_out_weight"] = norm(L, c.n_heads, dh, c.d_model)
    if c.n_experts:
        p["moe_gate_weight"] = norm(L, c.d_model, c.n_experts)
        p["ffn_up_weight"] = norm(L, c.n_experts, c.d_model, c.d_ff)
        p["ffn_down_weight"] = norm(L, c.n_experts, c.d_ff, c.d_model)
    else:
        p["ffn_up_weight"] = norm(L, c.d_model, c.d_ff)
        p["ffn_down_weight"] = norm(L, c.d_ff, c.d_model)
    return {k: jnp.asarray(v) for k, v in p.items()}


def param_specs(config, mesh):
    """PartitionSpec per param — megatron mp/tp + ep expert sharding.

    Column sharding (QKV heads, FFN-up output) and row sharding
    (attention out-proj input heads, FFN-down input) over the mesh's
    tensor-parallel axis (``mp``, or the legacy ``tp`` alias), the
    classic megatron split: each block needs exactly one psum after
    the attention out-proj and one after FFN-down."""
    ax = set(mesh.axis_names)
    tp = _mp_axis(ax)
    ep = "ep" if "ep" in ax else None
    sp = {
        "embed_weight": P(tp, None),
        "pos_embed_weight": P(),
        "final_ln_gamma": P(), "final_ln_beta": P(),
        "ln1_gamma": P(), "ln1_beta": P(), "ln2_gamma": P(), "ln2_beta": P(),
        "attn_qkv_weight": P(None, None, None, tp, None),
        "attn_out_weight": P(None, tp, None, None),
    }
    if config.n_experts:
        sp["moe_gate_weight"] = P()
        sp["ffn_up_weight"] = P(None, ep, None, tp)
        sp["ffn_down_weight"] = P(None, ep, tp, None)
    else:
        sp["ffn_up_weight"] = P(None, None, tp)
        sp["ffn_down_weight"] = P(None, tp, None)
    return sp


def _layernorm(x, gamma, beta, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    y = (x32 - mu) * lax.rsqrt(var + eps)
    return (y * gamma + beta).astype(x.dtype)


def _attention(q, k, v, *, axes, causal=True, attn="auto", blocks=None):
    """(B, H_loc, S_loc, D) in, same out; sp handled per `attn` mode.
    ``blocks``: optional (block_q, block_k) flash schedule override —
    None entries consult the schedule table (kernels/flash_attention)."""
    has_sp = "sp" in axes
    if attn == "auto":
        attn = "ring" if has_sp else "flash"
    if not has_sp:
        # flash_attention pads the head dim to the 128-lane tile internally,
        # so common head dims (64, 80, ...) all take the O(S)-memory kernel
        if attn == "flash" and jax.default_backend() == "tpu":
            from ..kernels import flash_attention
            bq, bk = blocks or (None, None)
            return flash_attention(q, k, v, causal=causal,
                                   block_q=bq, block_k=bk)
        return full_attention(q, k, v, causal=causal)
    if attn == "full":
        # debug mode: gather the whole sequence onto every sp shard and
        # attend globally (memory-heavy but exact); q keeps its shard
        idx = lax.axis_index("sp")
        kg = lax.all_gather(k, "sp", axis=2, tiled=True)
        vg = lax.all_gather(v, "sp", axis=2, tiled=True)
        return full_attention(q, kg, vg, causal=causal,
                              q_offset=idx * q.shape[2])
    if attn == "ring":
        return ring_attention_inner(q, k, v, axis_name="sp", causal=causal)
    if attn == "ulysses":
        from ..parallel.ring import ulysses_attention_inner
        return ulysses_attention_inner(q, k, v, axis_name="sp", causal=causal)
    if attn == "flash":
        raise ValueError(
            "attn='flash' attends only within the local shard and is "
            "incompatible with a sequence-parallel (sp) mesh axis; use "
            "'ring' or 'ulysses' (both use flash-style online softmax)")
    raise ValueError("unknown attn mode %r" % attn)


def _block(x, lp, c, axes, cdt):
    """One transformer block on local shards. lp: this layer's params."""
    h = _layernorm(x, lp["ln1_gamma"], lp["ln1_beta"])
    qkv = jnp.einsum("bsd,dthe->tbhse", h, lp["attn_qkv_weight"].astype(cdt))
    q, k, v = qkv[0], qkv[1], qkv[2]
    o = _attention(q, k, v, axes=axes, attn=c.attn,
                   blocks=(c.attn_block_q, c.attn_block_k))
    o = jnp.einsum("bhse,hed->bsd", o, lp["attn_out_weight"].astype(cdt))
    t = _mp_axis(axes)
    if t:
        o = lax.psum(o, t)         # row-parallel out-proj
    x = x + o
    return _ffn(x, lp, c, axes, cdt)


def _ffn(x, lp, c, axes, cdt):
    """The ffn half of a block (post-attention residual included) —
    shared verbatim between the training forward and the incremental
    decode step, so the two paths cannot drift numerically."""
    h = _layernorm(x, lp["ln2_gamma"], lp["ln2_beta"])
    t = _mp_axis(axes)
    if c.n_experts:
        gate = jax.nn.softmax(
            jnp.einsum("bsd,de->bse", h.astype(jnp.float32),
                       lp["moe_gate_weight"].astype(jnp.float32)), axis=-1)
        e_loc = lp["ffn_up_weight"].shape[0]
        e0 = lax.axis_index("ep") * e_loc if "ep" in axes else 0
        g_loc = lax.dynamic_slice_in_dim(gate, e0, e_loc, axis=-1).astype(cdt)
        up = jnp.einsum("bsd,edf->besf", h, lp["ffn_up_weight"].astype(cdt))
        act = jax.nn.relu(up)
        down = jnp.einsum("besf,efd->besd", act,
                          lp["ffn_down_weight"].astype(cdt))
        f = jnp.einsum("besd,bse->bsd", down, g_loc)
        if "ep" in axes:
            f = lax.psum(f, "ep")
        if t:
            f = lax.psum(f, t)     # d_ff was also mp-sharded
    else:
        up = jax.nn.relu(jnp.einsum("bsd,df->bsf", h,
                                    lp["ffn_up_weight"].astype(cdt)))
        f = jnp.einsum("bsf,fd->bsd", up, lp["ffn_down_weight"].astype(cdt))
        if t:
            f = lax.psum(f, t)     # row-parallel ffn-down
    return x + f


def _forward_local(params, tokens, c, axes):
    """Local-shard forward → logits (B_loc, S_loc, V). tokens int32."""
    cdt = jnp.dtype(c.dtype)
    B, S_loc = tokens.shape

    # vocab(mp)-sharded embedding: mask + psum
    t = _mp_axis(axes)
    emb_w = params["embed_weight"]
    v_loc = emb_w.shape[0]
    v0 = lax.axis_index(t) * v_loc if t else 0
    local_ids = tokens - v0
    in_range = (local_ids >= 0) & (local_ids < v_loc)
    x = jnp.take(emb_w, jnp.clip(local_ids, 0, v_loc - 1), axis=0)
    x = jnp.where(in_range[..., None], x, 0.0)
    if t:
        x = lax.psum(x, t)
    s0 = lax.axis_index("sp") * S_loc if "sp" in axes else 0
    pos = lax.dynamic_slice_in_dim(params["pos_embed_weight"], s0, S_loc, 0)
    x = (x + pos).astype(cdt)

    n_layers = params["ln1_gamma"].shape[0]

    def layer(x, lp):
        y = _block(x, lp, c, axes, cdt)
        return y, None

    if c.remat:
        layer = jax.checkpoint(layer)
    stacked = {k: v for k, v in params.items()
               if k not in ("embed_weight", "pos_embed_weight",
                            "final_ln_gamma", "final_ln_beta")}
    x, _ = lax.scan(layer, x, stacked)

    x = _layernorm(x, params["final_ln_gamma"], params["final_ln_beta"])
    logits_loc = jnp.einsum("bsd,vd->bsv", x, emb_w.astype(cdt))
    if t:
        logits = lax.all_gather(logits_loc, t, axis=2, tiled=True)
    else:
        logits = logits_loc
    return logits.astype(jnp.float32)


def make_loss_fn(config, mesh, data_axes=("dp",)):
    """shard_map'd next-token CE loss(params, tokens) → scalar.

    tokens: (B, S+1) int32 global; batch shards over ``data_axes``, the
    sequence over ``sp`` when present. Gradients via ``jax.grad`` come
    back with `param_specs` shardings (shard_map transpose inserts the
    dp psum — the reference's KVStore push, now compiler-inserted).
    """
    c = config
    axes = set(mesh.axis_names)
    specs = param_specs(c, mesh)

    # every mesh axis the batch/sequence is split over must join the
    # loss psum (incl. a multi-host "dcn" axis ahead of dp)
    reduce_axes = tuple(a for a in mesh.axis_names
                        if a in set(data_axes) | {"sp"})

    def local_loss(params, tokens):
        inp, tgt = tokens[:, :-1], tokens[:, 1:]
        logits = _forward_local(params, inp, c, axes)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, tgt[..., None].astype(jnp.int32),
                                   axis=-1)[..., 0]
        loss_sum = jnp.sum(nll)
        count = jnp.float32(nll.size)
        if reduce_axes:
            loss_sum = lax.psum(loss_sum, reduce_axes)
            count = lax.psum(count, reduce_axes)
        return loss_sum / count

    # tokens enter with seq split over sp: shard (B_loc, S_loc + 1) needs
    # the +1 target shift *before* sharding — handled by passing the full
    # sequence and slicing locally with a halo exchange. Simpler exact
    # scheme: shard tokens (B, S+1) over batch only, slice seq inside.
    def local_loss_seqsplit(params, tokens):
        if "sp" not in axes:
            return local_loss(params, tokens)
        n_sp = lax.psum(1, "sp")
        idx = lax.axis_index("sp")
        S = tokens.shape[1] - 1
        s_loc = S // n_sp
        my = lax.dynamic_slice_in_dim(tokens, idx * s_loc, s_loc + 1, 1)
        return local_loss(params, my)

    # tokens enter sharded over batch only; the sequence (+1 target
    # overlap) is sliced per-sp-shard inside local_loss_seqsplit
    token_spec = P(tuple(a for a in data_axes if a in axes) or None, None)

    def loss_fn(params, tokens):
        sp_params = {k: specs[k] for k in params}
        return _shard_map(
            local_loss_seqsplit, mesh=mesh,
            in_specs=(sp_params, token_spec), out_specs=P(),
            check_vma=False,
        )(params, tokens)

    return loss_fn, specs


def make_train_step(config, mesh, optimizer=None, data_axes=("dp",)):
    """Fused SPMD train step: loss + grad + sgd-momentum update, jitted
    with NamedShardings from `param_specs` (spmd.TrainStep's functional
    twin for the transformer family)."""
    from ..parallel.spmd import functional_optimizer, FunctionalOptimizer

    opt = optimizer or functional_optimizer("sgd", learning_rate=0.1,
                                            momentum=0.9)
    if isinstance(opt, dict):
        opt = functional_optimizer(**opt)
    assert isinstance(opt, FunctionalOptimizer)
    loss_fn, specs = make_loss_fn(config, mesh, data_axes)

    def step(carry, tokens):
        params, opt_state, n = carry
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        new_p, new_s = opt.apply(params, grads, opt_state, n)
        return (new_p, new_s, n + 1), loss

    shardings = {k: NamedSharding(mesh, s) for k, s in specs.items()}

    def place(params):
        opt_state = opt.init(params)
        params = {k: jax.device_put(v, shardings[k]) for k, v in params.items()}
        opt_state = {k: jax.tree_util.tree_map(
            lambda x: jax.device_put(x, shardings[k]), v)
            for k, v in opt_state.items()}
        return (params, opt_state, jnp.zeros((), jnp.int32))

    return jax.jit(step, donate_argnums=(0,)), place


# ---------------------------------------------------------------------------
# collective accounting (ISSUE 20): the megatron sharding's contract is
# ONE psum per block half — 2 per transformer block. Assert it from the
# traced jaxpr, not the compiled HLO: the count is backend-independent
# and survives the CPU pipeline's CSE/barrier stripping that makes HLO
# text counting unstable (the PR 19 lesson).
# ---------------------------------------------------------------------------
def _sub_jaxprs(eqn):
    try:
        from jax.extend import core as _core
    except ImportError:  # jax 0.4.x
        from jax import core as _core

    for v in eqn.params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for x in vals:
            if isinstance(x, _core.ClosedJaxpr):
                yield x.jaxpr
            elif isinstance(x, _core.Jaxpr):
                yield x


def _count_prims(jaxpr, names):
    n = {k: 0 for k in names}
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in n:
            n[eqn.primitive.name] += 1
        for sub in _sub_jaxprs(eqn):
            for k, v in _count_prims(sub, names).items():
                n[k] += v
    return n


def _scan_bodies(jaxpr):
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            yield eqn.params["jaxpr"].jaxpr
        else:
            for sub in _sub_jaxprs(eqn):
                yield from _scan_bodies(sub)


def block_collective_counts(config, mesh, data_axes=("dp",)):
    """Per-step collective bill of the shard_map'd loss forward, from
    the traced jaxpr: ``psum_per_block`` counts psums inside the
    scanned transformer-block body (exactly 2 on an mp mesh — the
    attention out-proj and ffn-down row-parallel reductions; 0 when
    the model is replicated), ``psum_outside`` the psums outside the
    scan (vocab-sharded embedding + the dp/sp loss reductions), and
    ``all_gather`` the logit gathers. Feeds ``profiler.mp_record`` and
    the exactness assert in tests/test_model_parallel.py."""
    loss_fn, _specs = make_loss_fn(config, mesh, data_axes)
    params = jax.eval_shape(lambda: init_params(config))
    B = int(np.prod([s for a, s in zip(mesh.axis_names, mesh.devices.shape)
                     if a in data_axes]) or 1)
    tokens = jax.ShapeDtypeStruct((B, 9), jnp.int32)
    jaxpr = jax.make_jaxpr(loss_fn)(params, tokens).jaxpr
    bodies = list(_scan_bodies(jaxpr))
    per_block = max((_count_prims(b, ("psum",))["psum"] for b in bodies),
                    default=0)
    total = _count_prims(jaxpr, ("psum", "all_gather"))
    return {
        "psum_per_block": per_block,
        "psum_outside": total["psum"] - per_block * len(bodies),
        "all_gather": total["all_gather"],
        "n_blocks": config.n_layers,
    }


def kv_cache_spec(mesh):
    """PartitionSpec of the paged KV cache (L, 2, P+1, page, H, Dh)
    on an mp mesh: heads sharded over the tensor-parallel axis — each
    chip holds 1/mp of every page (the sharded-serving-group memory
    claim). Replicated when the mesh has no mp/tp axis."""
    t = _mp_axis(set(mesh.axis_names))
    return P(None, None, None, None, t, None)
# PAGED per-layer KV cache. The serving tier (serving/generate.py) owns
# page allocation and batch-slot bookkeeping; the functions here are the
# pure compiled programs:
#
# - ``make_forward_fn``      one-shot logits (B, S, V) on a single
#                            device — the numerical reference the decode
#                            path must match per token.
# - ``init_kv_cache``        the cache buffer: (L, 2, P, page, H, Dh).
#                            Page 0 is the SCRATCH page — never handed
#                            out by the allocator; inactive slots and
#                            padded prompt tail positions write there.
# - ``make_prefill_fn``      causal forward over one padded prompt that
#                            scatters every position's K/V into its
#                            page (block-table order) and returns the
#                            last valid position's logits — the first
#                            generated token comes out of prefill.
# - ``make_decode_fn``       one token per active batch slot: write the
#                            token's K/V at (page, offset) derived from
#                            its position, then attend over the pages
#                            named by the slot's block table with a
#                            flash-style blocked online softmax whose
#                            ``block_k`` is consulted from the PR 10
#                            schedule table at trace time (decode-shape
#                            key: seq_q == 1, causal == 0 — the decode
#                            query attends to ALL cached keys, masked
#                            by length, not by the kernel's causal
#                            row>=col rule).
#
# The attention math mirrors kernels/flash_attention.py's online
# softmax (running max / denominator / unnormalized accumulator, fp32),
# so prefill+decode logits match the one-shot forward to
# accumulation-order tolerance — asserted in tests/test_generate.py.
# ---------------------------------------------------------------------------
def make_forward_fn(config):
    """Single-device one-shot logits fn(params, tokens (B, S) int32) →
    (B, S, V) fp32 — ``make_loss_fn``'s mesh-free twin (the serving
    parity reference and the prefill program's ancestor)."""
    c = config

    def fwd(params, tokens):
        return _forward_local(params, tokens, c, frozenset())

    return jax.jit(fwd)


def init_kv_cache(config, num_pages, page_size, dtype=None):
    """Zeroed paged KV cache (n_layers, 2, num_pages + 1, page_size,
    n_heads, head_dim) in the compute dtype. Index 0 on the page axis
    is the scratch page (see module comment); callers allocate real
    page ids from 1..num_pages."""
    c = config
    cdt = jnp.dtype(dtype if dtype is not None else c.dtype)
    dh = c.d_model // c.n_heads
    return jnp.zeros((c.n_layers, 2, int(num_pages) + 1, int(page_size),
                      c.n_heads, dh), cdt)


def decode_schedule_shape(config, slots, max_ctx):
    """The schedule-table key shape the decode step consults:
    (batch=slots, heads, seq_q=1, seq_k=max_ctx, head_dim, causal=0) —
    the same convention the flash-attention consult uses, so the
    tune_kernels decode-shape sweep populates exactly this key."""
    c = config
    return (int(slots), c.n_heads, 1, int(max_ctx),
            c.d_model // c.n_heads, 0)


def _decode_block_k(config, slots, max_ctx):
    """Trace-time consult for the decode attention chunk size."""
    from ..kernels.flash_attention import DEFAULT_BLOCK
    from ..tune import schedule_for

    sched = schedule_for("flash_attention",
                         decode_schedule_shape(config, slots, max_ctx),
                         str(jnp.dtype(config.dtype))) or {}
    block_k = int(sched.get("block_k", DEFAULT_BLOCK))
    return max(1, min(block_k, int(max_ctx)))


def _paged_decode_attention(q, k, v, positions, block_k):
    """Flash-style blocked decode attention for one query token per
    slot. q: (B, H, 1, Dh); k/v: (B, H, L, Dh) gathered from the page
    pool (L = max_pages_per_slot * page_size); key column j of slot b
    is valid iff j <= positions[b] (the slot's own token was written
    before the call). Online softmax over ``block_k``-column chunks —
    the flash forward kernel's loop in lax, so per-slot dynamic
    lengths mask exactly."""
    B, H, L, Dh = k.shape
    scale = 1.0 / (Dh ** 0.5)
    nb = -(-L // block_k)
    pad = nb * block_k - L
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    q32 = q[:, :, 0, :].astype(jnp.float32) * scale          # (B, H, Dh)
    neg = jnp.float32(-1e30)

    def body(j, carry):
        m, l, acc = carry
        kb = lax.dynamic_slice_in_dim(k, j * block_k, block_k,
                                      axis=2).astype(jnp.float32)
        vb = lax.dynamic_slice_in_dim(v, j * block_k, block_k,
                                      axis=2).astype(jnp.float32)
        s = jnp.einsum("bhd,bhkd->bhk", q32, kb,
                       preferred_element_type=jnp.float32)
        cols = j * block_k + jnp.arange(block_k)
        ok = cols[None, None, :] <= positions[:, None, None]
        s = jnp.where(ok, s, neg)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhk,bhkd->bhd", p, vb, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((B, H), neg, jnp.float32)
    l0 = jnp.zeros((B, H), jnp.float32)
    a0 = jnp.zeros((B, H, Dh), jnp.float32)
    _, l, acc = lax.fori_loop(0, nb, body, (m0, l0, a0))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out[:, :, None, :].astype(q.dtype)                # (B, H, 1, Dh)


def _paged_extend_attention(q, k, v, positions, block_k):
    """:func:`_paged_decode_attention` generalized to T query tokens per
    slot (ISSUE 16). q: (B, H, T, Dh); k/v: (B, H, L, Dh) gathered from
    the page pool; query row t of slot b sits at ``positions[b, t]`` and
    attends key column j iff ``j <= positions[b, t]`` — the per-row
    causal mask that makes one batched call serve both the shared-prefix
    tail prefill (rows are consecutive prompt-tail positions attending
    the cached prefix pages) and the speculative verify step (rows are
    the pending token + k draft proposals). Same fp32 online softmax."""
    B, H, L, Dh = k.shape
    scale = 1.0 / (Dh ** 0.5)
    nb = -(-L // block_k)
    pad = nb * block_k - L
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    T = q.shape[2]
    q32 = q.astype(jnp.float32) * scale                      # (B, H, T, Dh)
    neg = jnp.float32(-1e30)

    def body(j, carry):
        m, l, acc = carry
        kb = lax.dynamic_slice_in_dim(k, j * block_k, block_k,
                                      axis=2).astype(jnp.float32)
        vb = lax.dynamic_slice_in_dim(v, j * block_k, block_k,
                                      axis=2).astype(jnp.float32)
        s = jnp.einsum("bhtd,bhkd->bhtk", q32, kb,
                       preferred_element_type=jnp.float32)
        cols = j * block_k + jnp.arange(block_k)
        ok = cols[None, None, None, :] <= positions[:, None, :, None]
        s = jnp.where(ok, s, neg)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhtk,bhkd->bhtd", p, vb, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((B, H, T), neg, jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    a0 = jnp.zeros((B, H, T, Dh), jnp.float32)
    _, l, acc = lax.fori_loop(0, nb, body, (m0, l0, a0))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)                               # (B, H, T, Dh)


def _stacked_layer_params(params):
    return {k: v for k, v in params.items()
            if k not in ("embed_weight", "pos_embed_weight",
                         "final_ln_gamma", "final_ln_beta")}


def make_prefill_fn(config, page_size):
    """fn(params, cache, tokens (1, S_pad) int32, length () int32,
    pages (S_pad // page_size,) int32) → (cache', logits (V,) fp32).

    Runs the SAME causal block forward as ``make_forward_fn`` over the
    padded prompt (so flash/full attention and its schedule consult are
    shared), scatters each position p's K/V into
    ``cache[layer, :, pages[p // page_size], p % page_size]``, and
    returns the logits of position ``length - 1``. Padded tail
    positions write garbage K/V into whatever page their index names —
    callers pad ``pages`` with 0, the scratch page, past the allocated
    prompt pages; garbage inside an allocated page at offsets >=
    length is never attended (decode masks columns > position) and is
    overwritten before the position is reached."""
    c = config
    cdt = jnp.dtype(c.dtype)
    page_size = int(page_size)

    def prefill(params, cache, tokens, length, pages):
        _b, S = tokens.shape
        n_pages = S // page_size
        x = jnp.take(params["embed_weight"],
                     jnp.clip(tokens, 0, params["embed_weight"].shape[0] - 1),
                     axis=0)
        x = (x + params["pos_embed_weight"][:S]).astype(cdt)

        def layer(x, xs):
            lp, cl = xs
            h = _layernorm(x, lp["ln1_gamma"], lp["ln1_beta"])
            qkv = jnp.einsum("bsd,dthe->tbhse", h,
                             lp["attn_qkv_weight"].astype(cdt))
            q, k, v = qkv[0], qkv[1], qkv[2]
            # scatter K/V into this layer's pages: (1,H,S,Dh) → page grid
            kp = k[0].transpose(1, 0, 2).reshape(
                n_pages, page_size, c.n_heads, -1)
            vp = v[0].transpose(1, 0, 2).reshape(
                n_pages, page_size, c.n_heads, -1)
            cl = cl.at[0, pages].set(kp.astype(cl.dtype))
            cl = cl.at[1, pages].set(vp.astype(cl.dtype))
            o = _attention(q, k, v, axes=frozenset(), attn=c.attn,
                           blocks=(c.attn_block_q, c.attn_block_k))
            o = jnp.einsum("bhse,hed->bsd", o,
                           lp["attn_out_weight"].astype(cdt))
            return _ffn(x + o, lp, c, frozenset(), cdt), cl

        x, cache = lax.scan(layer, x, (_stacked_layer_params(params), cache))
        x = _layernorm(x, params["final_ln_gamma"], params["final_ln_beta"])
        x_last = lax.dynamic_index_in_dim(x[0], length - 1, axis=0,
                                          keepdims=False)
        logits = jnp.einsum("d,vd->v", x_last,
                            params["embed_weight"].astype(cdt))
        return cache, logits.astype(jnp.float32)

    return prefill


def make_decode_fn(config, slots, max_pages_per_slot, page_size,
                   block_k=None):
    """fn(params, cache, tokens (S,) int32, positions (S,) int32,
    block_tables (S, max_pages_per_slot) int32, active (S,) bool) →
    (cache', logits (S, V) fp32).

    One decode step for ``slots`` batch slots: embed token b at
    ``positions[b]``, write its per-layer K/V at page
    ``block_tables[b, positions[b] // page_size]`` offset
    ``positions[b] % page_size``, attend over the slot's gathered pages
    (columns <= position), and emit next-token logits. Inactive slots
    compute too (the batch shape is static) but their writes are routed
    to the scratch page and their logits zeroed. ``block_k`` defaults
    to the schedule-table consult at the decode shape
    (:func:`decode_schedule_shape`)."""
    c = config
    cdt = jnp.dtype(c.dtype)
    page_size = int(page_size)
    max_ctx = int(max_pages_per_slot) * page_size
    if block_k is None:
        block_k = _decode_block_k(c, slots, max_ctx)

    def decode(params, cache, tokens, positions, block_tables, active):
        S = tokens.shape[0]
        emb = params["embed_weight"]
        x = jnp.take(emb, jnp.clip(tokens, 0, emb.shape[0] - 1), axis=0)
        pos = jnp.take(params["pos_embed_weight"],
                       jnp.clip(positions, 0,
                                params["pos_embed_weight"].shape[0] - 1),
                       axis=0)
        x = (x + pos).astype(cdt)[:, None, :]                # (S, 1, d)

        page_idx = positions // page_size
        offset = positions % page_size
        page = jnp.take_along_axis(block_tables, page_idx[:, None],
                                   axis=1)[:, 0]
        # inactive slots (and any unset table entry) write to scratch
        page = jnp.where(active, page, 0)

        def layer(x, xs):
            lp, cl = xs
            h = _layernorm(x, lp["ln1_gamma"], lp["ln1_beta"])
            qkv = jnp.einsum("bsd,dthe->tbhse", h,
                             lp["attn_qkv_weight"].astype(cdt))
            q, k, v = qkv[0], qkv[1], qkv[2]          # (S, H, 1, Dh)
            cl = cl.at[0, page, offset].set(k[:, :, 0, :].astype(cl.dtype))
            cl = cl.at[1, page, offset].set(v[:, :, 0, :].astype(cl.dtype))
            # paged gather: (S, MP, page, H, Dh) → (S, H, L, Dh)
            kg = cl[0][block_tables].reshape(
                S, max_ctx, c.n_heads, -1).transpose(0, 2, 1, 3)
            vg = cl[1][block_tables].reshape(
                S, max_ctx, c.n_heads, -1).transpose(0, 2, 1, 3)
            o = _paged_decode_attention(q.astype(cdt), kg, vg, positions,
                                        block_k)
            o = jnp.einsum("bhse,hed->bsd", o,
                           lp["attn_out_weight"].astype(cdt))
            return _ffn(x + o, lp, c, frozenset(), cdt), cl

        x, cache = lax.scan(layer, x, (_stacked_layer_params(params), cache))
        x = _layernorm(x, params["final_ln_gamma"], params["final_ln_beta"])
        logits = jnp.einsum("bsd,vd->bsv", x,
                            params["embed_weight"].astype(cdt))[:, 0]
        logits = jnp.where(active[:, None], logits, 0.0)
        return cache, logits.astype(jnp.float32)

    return decode


def make_extend_fn(config, slots, steps, max_pages_per_slot, page_size,
                   block_k=None):
    """fn(params, cache, tokens (S, T) int32, positions (S, T) int32,
    block_tables (S, max_pages_per_slot) int32, valid (S, T) bool) →
    (cache', logits (S, T, V) fp32) with ``S == slots``, ``T == steps``.

    The multi-token generalization of ``make_decode_fn`` (ISSUE 16):
    each slot appends up to T tokens against its already-cached pages in
    ONE compiled call. Token (b, t) is written at page
    ``block_tables[b, positions[b, t] // page_size]`` offset
    ``positions[b, t] % page_size``, then every row attends the slot's
    gathered pages under the per-row mask ``col <= positions[b, t]`` —
    all T writes of a layer land before that layer's gather, so row t
    sees rows < t of its own call (in-window causality is free). Two
    callers, same program shape:

    - shared-prefix tail prefill: S = 1, rows are the uncovered prompt
      tail at positions ``prefix_len..prompt_len-1`` — they attend the
      SHARED prefix pages but, because every row's position lies past
      the shared region, only ever write the request's private pages
      (the copy-on-write guarantee, asserted in tests);
    - speculative verify: rows are the pending token + k draft
      proposals; logits row t is the target model's next-token
      distribution after prefix+row t, so acceptance (argmax equality)
      reproduces the non-speculative greedy chain token-for-token.

    Invalid rows (valid == False: padded tails, slots speculating fewer
    than k tokens) write to the scratch page and return zero logits.
    Rows at positions past the verified prefix may leave REJECTED
    tokens' K/V behind — safe for the same reason padded prefill tails
    are: columns past a row's position are masked, and a later call
    writes the position before any row attends it."""
    c = config
    cdt = jnp.dtype(c.dtype)
    page_size = int(page_size)
    max_ctx = int(max_pages_per_slot) * page_size
    if block_k is None:
        block_k = _decode_block_k(c, slots, max_ctx)

    def extend(params, cache, tokens, positions, block_tables, valid):
        S, T = tokens.shape
        positions = jnp.maximum(positions, 0)
        emb = params["embed_weight"]
        x = jnp.take(emb, jnp.clip(tokens, 0, emb.shape[0] - 1), axis=0)
        pos_emb = jnp.take(
            params["pos_embed_weight"],
            jnp.clip(positions, 0, params["pos_embed_weight"].shape[0] - 1),
            axis=0)
        x = (x + pos_emb).astype(cdt)                        # (S, T, d)

        page_idx = jnp.clip(positions // page_size, 0, block_tables.shape[1] - 1)
        offset = positions % page_size
        page = jnp.take_along_axis(block_tables, page_idx, axis=1)  # (S, T)
        # invalid rows (and any unset table entry) write to scratch
        page = jnp.where(valid, page, 0)

        def layer(x, xs):
            lp, cl = xs
            h = _layernorm(x, lp["ln1_gamma"], lp["ln1_beta"])
            qkv = jnp.einsum("bsd,dthe->tbhse", h,
                             lp["attn_qkv_weight"].astype(cdt))
            q, k, v = qkv[0], qkv[1], qkv[2]          # (S, H, T, Dh)
            cl = cl.at[0, page, offset].set(
                k.transpose(0, 2, 1, 3).astype(cl.dtype))
            cl = cl.at[1, page, offset].set(
                v.transpose(0, 2, 1, 3).astype(cl.dtype))
            kg = cl[0][block_tables].reshape(
                S, max_ctx, c.n_heads, -1).transpose(0, 2, 1, 3)
            vg = cl[1][block_tables].reshape(
                S, max_ctx, c.n_heads, -1).transpose(0, 2, 1, 3)
            o = _paged_extend_attention(q.astype(cdt), kg, vg, positions,
                                        block_k)
            o = jnp.einsum("bhse,hed->bsd", o,
                           lp["attn_out_weight"].astype(cdt))
            return _ffn(x + o, lp, c, frozenset(), cdt), cl

        x, cache = lax.scan(layer, x, (_stacked_layer_params(params), cache))
        x = _layernorm(x, params["final_ln_gamma"], params["final_ln_beta"])
        logits = jnp.einsum("btd,vd->btv", x,
                            params["embed_weight"].astype(cdt))
        logits = jnp.where(valid[..., None], logits, 0.0)
        return cache, logits.astype(jnp.float32)

    return extend


def draft_from_layers(config, params, n_layers):
    """Self-draft for speculative decoding (ISSUE 16): slice the stacked
    layer params down to the FIRST ``n_layers`` transformer blocks,
    sharing the embedding / position / final-LN tensors with the target
    model. Returns ``(draft_config, draft_params)`` ready for a second
    :class:`~mxnet_tpu.serving.generate.GenerativePredictor` — no extra
    training, no extra checkpoint, and (because ``init_params`` stacks
    every per-layer tensor on a leading L axis) no copy of the shared
    tensors. A one-layer draft of an L-layer target is the cheap
    proposer whose agreement the verify step measures as
    ``acceptance_rate``."""
    n = int(n_layers)
    if not 1 <= n <= config.n_layers:
        raise ValueError(
            "draft_from_layers: n_layers must lie in [1, %d], got %d"
            % (config.n_layers, n))
    shared = ("embed_weight", "pos_embed_weight",
              "final_ln_gamma", "final_ln_beta")
    dparams = {k: (v if k in shared else v[:n]) for k, v in params.items()}
    return dataclasses.replace(config, n_layers=n), dparams
