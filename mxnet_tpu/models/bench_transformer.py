"""Symbol-level transformer encoder for the training-graph pass bench
(ISSUE 19).

``models/transformer.py`` is the functional SPMD flagship; the IR
passes operate on *Symbol* graphs, so the remat/layout/pipeline
acceptance numbers need a transformer built from graph nodes. This is
that graph: ``n_layers`` pre-LN self-attention blocks over an
already-embedded ``(batch, seq_len, d_model)`` input, classification
head, ``SoftmaxOutput`` loss.

The shape profile is what makes it a *memory* bench: every block
materializes ``(batch * heads, seq_len, seq_len)`` attention scores
AND softmax weights plus a ``(batch, seq_len, d_ff)`` ReLU — without
remat all of them are backward residuals. The selective plan
(:mod:`~mxnet_tpu.ir.remat`) saves only the FC/batch_dot outputs and
recomputes softmax / ReLU / LayerNorm / reshapes, which is where the
``>= 30%`` compiled temp-bytes cut in tests/test_train_passes.py comes
from.
"""
from __future__ import annotations

import math

from .. import symbol as sym


def get_symbol(num_classes=16, seq_len=64, d_model=128, n_heads=4,
               n_layers=2, d_ff=512):
    """Transformer encoder Symbol over pre-embedded ``data``
    ``(batch, seq_len, d_model)`` with a ``softmax`` SoftmaxOutput
    head. All nodes are explicitly named (remat plans and the pipeline
    fingerprint key on stable structure)."""
    if d_model % n_heads:
        raise ValueError("d_model %d not divisible by n_heads %d"
                         % (d_model, n_heads))
    dh = d_model // n_heads

    def split_heads(t, nm):
        # (B, S, d) -> (B*H, S, dh)
        t = sym.Reshape(t, shape=(0, 0, n_heads, dh), name=nm + "_split")
        t = sym.transpose(t, axes=(0, 2, 1, 3), name=nm + "_perm")
        return sym.Reshape(t, shape=(-3, 0, 0), name=nm + "_fold")

    x = sym.Variable("data")
    for i in range(n_layers):
        p = "blk%d_" % i
        h = sym.LayerNorm(x, name=p + "ln1")
        q = split_heads(sym.FullyConnected(h, num_hidden=d_model,
                                           flatten=False, name=p + "q"),
                        p + "q")
        k = split_heads(sym.FullyConnected(h, num_hidden=d_model,
                                           flatten=False, name=p + "k"),
                        p + "k")
        v = split_heads(sym.FullyConnected(h, num_hidden=d_model,
                                           flatten=False, name=p + "v"),
                        p + "v")
        # (B*H, S, S) scores; the 1/sqrt(dh) scale rides softmax's
        # temperature so the scores node stays a pure batch_dot (a
        # SAVE_OPS site)
        scores = sym.batch_dot(q, k, transpose_b=True, name=p + "scores")
        attn = sym.softmax(scores, axis=-1, temperature=math.sqrt(dh),
                           name=p + "attn")
        ctx = sym.batch_dot(attn, v, name=p + "ctx")
        # (B*H, S, dh) -> (B, S, d)
        ctx = sym.Reshape(ctx, shape=(-4, -1, n_heads, 0, 0),
                          name=p + "ctx_unfold")
        ctx = sym.transpose(ctx, axes=(0, 2, 1, 3), name=p + "ctx_perm")
        ctx = sym.Reshape(ctx, shape=(0, 0, -3), name=p + "ctx_merge")
        proj = sym.FullyConnected(ctx, num_hidden=d_model, flatten=False,
                                  name=p + "proj")
        x = sym.broadcast_add(x, proj, name=p + "res1")
        h2 = sym.LayerNorm(x, name=p + "ln2")
        up = sym.FullyConnected(h2, num_hidden=d_ff, flatten=False,
                                name=p + "ffn_up")
        act = sym.Activation(up, act_type="relu", name=p + "ffn_relu")
        down = sym.FullyConnected(act, num_hidden=d_model, flatten=False,
                                  name=p + "ffn_down")
        x = sym.broadcast_add(x, down, name=p + "res2")
    x = sym.LayerNorm(x, name="final_ln")
    x = sym.Flatten(x, name="head_flatten")
    x = sym.FullyConnected(x, num_hidden=num_classes, name="head_fc")
    return sym.SoftmaxOutput(x, name="softmax")
