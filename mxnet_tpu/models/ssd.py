"""SSD detector (Single Shot MultiBox, VGG16-reduced backbone).

Reference counterpart: ``example/ssd/symbol/symbol_builder.py`` +
``symbol/vgg16_reduced.py`` — the 77.8 mAP VOC07 headline config
(example/ssd/README.md:35-40, SURVEY §6). Multi-scale feature maps feed
a shared multibox head; training uses MultiBoxTarget (anchor matching +
hard negative mining semantics) with softmax cls loss and smooth-L1 loc
loss; inference decodes with MultiBoxDetection NMS. All three contrib
ops are XLA-vectorized (ops/contrib.py).
"""
from .. import symbol as sym

# per-layer anchor config for 300x300 (ref: example/ssd/symbol/symbol_factory.py)
_SIZES = [(0.1, 0.141), (0.2, 0.272), (0.37, 0.447), (0.54, 0.619),
          (0.71, 0.79), (0.88, 0.961)]
_RATIOS = [(1, 2, 0.5), (1, 2, 0.5, 3, 1.0 / 3), (1, 2, 0.5, 3, 1.0 / 3),
           (1, 2, 0.5, 3, 1.0 / 3), (1, 2, 0.5), (1, 2, 0.5)]


def _conv_act(data, name, num_filter, kernel=(3, 3), pad=(1, 1),
              stride=(1, 1)):
    c = sym.Convolution(data=data, kernel=kernel, pad=pad, stride=stride,
                        num_filter=num_filter, name=name)
    return sym.Activation(data=c, act_type="relu", name=name + "_relu")


def _backbone(data):
    """VGG16-reduced: conv stages + dilated fc6/fc7 convs; returns the
    multi-scale feature pyramid."""
    from .vgg import _CFGS

    feats = []
    x = data
    for i, (reps, filters) in enumerate(_CFGS[16], 1):
        for j in range(1, reps + 1):
            x = _conv_act(x, "conv%d_%d" % (i, j), filters)
        if i == 4:
            feats.append(x)  # conv4_3 → 38x38 head (L2-normalized below)
        if i < 5:
            # pooling_convention="full" (ceil) keeps conv4_3 at 38x38 and
            # fc7 at 19x19 for 300x300 input (ref vgg16_reduced.py)
            x = sym.Pooling(data=x, kernel=(2, 2), stride=(2, 2),
                            pool_type="max", pooling_convention="full",
                            name="pool%d" % i)
        else:
            x = sym.Pooling(data=x, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                            pool_type="max", name="pool%d" % i)
    x = sym.Convolution(data=x, kernel=(3, 3), pad=(6, 6), dilate=(6, 6),
                        num_filter=1024, name="fc6")
    x = sym.Activation(data=x, act_type="relu")
    x = _conv_act(x, "fc7", 1024, kernel=(1, 1), pad=(0, 0))
    feats.append(x)  # 19x19
    for k, (f1, f2, s) in enumerate(
            [(256, 512, 2), (128, 256, 2), (128, 256, 2), (128, 256, 2)], 8):
        x = _conv_act(x, "conv%d_1" % k, f1, kernel=(1, 1), pad=(0, 0))
        pad = (1, 1) if s == 2 and k < 10 else (0, 0)
        kernel = (3, 3)
        x = _conv_act(x, "conv%d_2" % k, f2, kernel=kernel, pad=pad,
                      stride=(s, s) if k < 10 else (1, 1))
        feats.append(x)
    return feats


def _multibox_head(feats, num_classes, sizes=None, ratios=None,
                   l2norm_first=True, prefix=""):
    """Shared per-feature-map loc/cls/anchor assembly; the layout
    contract (transpose/Flatten/Reshape ordering) consumed by
    MultiBoxTarget/Detection lives only here."""
    sizes = _SIZES if sizes is None else sizes
    ratios = _RATIOS if ratios is None else ratios
    loc_preds, cls_preds, anchors = [], [], []
    for i, feat in enumerate(feats):
        if i == 0 and l2norm_first:
            feat = sym.L2Normalization(data=feat, mode="channel",
                                       name="conv4_3_norm")
        n_anchor = len(sizes[i]) + len(ratios[i]) - 1
        loc = sym.Convolution(data=feat, kernel=(3, 3), pad=(1, 1),
                              num_filter=n_anchor * 4,
                              name="%sloc_pred%d" % (prefix, i))
        loc = sym.transpose(loc, axes=(0, 2, 3, 1))
        loc_preds.append(sym.Flatten(data=loc))
        cls = sym.Convolution(data=feat, kernel=(3, 3), pad=(1, 1),
                              num_filter=n_anchor * (num_classes + 1),
                              name="%scls_pred%d" % (prefix, i))
        cls = sym.transpose(cls, axes=(0, 2, 3, 1))
        cls = sym.Reshape(data=cls, shape=(0, -1, num_classes + 1))
        cls_preds.append(cls)
        anchors.append(sym.contrib.MultiBoxPrior(
            feat, sizes=sizes[i], ratios=ratios[i], clip=True,
            name="%sanchor%d" % (prefix, i)))
    loc_pred = sym.Concat(*loc_preds, dim=1, name=prefix + "multibox_loc_pred")
    cls_pred = sym.Concat(*cls_preds, dim=1,
                          name=prefix + "multibox_cls_concat")
    cls_pred = sym.transpose(cls_pred, axes=(0, 2, 1))  # (N, C+1, A)
    anchor = sym.Concat(*anchors, dim=1, name=prefix + "multibox_anchors")
    return loc_pred, cls_pred, anchor


def _assemble_train(loc_pred, cls_pred, anchor):
    """Training tail: MultiBoxTarget + softmax cls + smooth-L1 loc
    (ref symbol_builder.py:get_symbol_train)."""
    label = sym.var("label")
    box_target, box_mask, cls_target = sym.contrib.MultiBoxTarget(
        anchor, label, cls_pred, overlap_threshold=0.5,
        ignore_label=-1.0, negative_mining_ratio=3.0,
        variances=(0.1, 0.1, 0.2, 0.2), name="multibox_target")
    cls_prob = sym.SoftmaxOutput(data=cls_pred, label=cls_target,
                                 ignore_label=-1.0, use_ignore=True,
                                 multi_output=True,
                                 normalization="valid", name="cls_prob")
    loc_diff = loc_pred - box_target
    masked = box_mask * loc_diff
    loc_loss = sym.MakeLoss(sym.smooth_l1(masked, scalar=1.0),
                            grad_scale=1.0, normalization="valid",
                            name="loc_loss")
    cls_label = sym.MakeLoss(data=cls_target, grad_scale=0.0,
                             name="cls_label")
    return sym.Group([cls_prob, loc_loss, cls_label])


def _assemble_detect(loc_pred, cls_pred, anchor, nms_thresh, force_suppress,
                     nms_topk):
    """Inference tail: MultiBoxDetection output (N, A, 6)
    [cls, score, xmin, ymin, xmax, ymax] (ref get_symbol)."""
    cls_prob = sym.softmax(cls_pred, axis=1, name="cls_prob")
    return sym.contrib.MultiBoxDetection(
        cls_prob, loc_pred, anchor, nms_threshold=nms_thresh,
        force_suppress=force_suppress, nms_topk=nms_topk,
        variances=(0.1, 0.1, 0.2, 0.2), name="detection")


def get_symbol_train(num_classes=20, nms_thresh=0.5, force_suppress=False,
                     nms_topk=400, **kwargs):
    """Training symbol: outputs [cls_prob, loc_loss, cls_label]."""
    data = sym.var("data")
    loc_pred, cls_pred, anchor = _multibox_head(_backbone(data), num_classes)
    return _assemble_train(loc_pred, cls_pred, anchor)


def get_symbol(num_classes=20, nms_thresh=0.5, force_suppress=False,
               nms_topk=400, **kwargs):
    """Inference symbol over the full VGG16-reduced backbone."""
    data = sym.var("data")
    loc_pred, cls_pred, anchor = _multibox_head(_backbone(data), num_classes)
    return _assemble_detect(loc_pred, cls_pred, anchor, nms_thresh,
                            force_suppress, nms_topk)


# ---------------------------------------------------------------------------
# Tiny detector: the same target-assign → detect → NMS chain on a
# 3-stage backbone with one anchor layer. CPU-affordable, so the
# end-to-end mAP evidence (train → MultiBoxDetection → VOC07MApMetric)
# can run in CI; the full-size config above is the benchmark path.
# ---------------------------------------------------------------------------
def _tiny_head(data, num_classes):
    x = data
    for i, f in enumerate((16, 32, 64), 1):
        x = _conv_act(x, "tconv%d" % i, f)
        x = sym.Pooling(data=x, kernel=(2, 2), stride=(2, 2),
                        pool_type="max", name="tpool%d" % i)
    return _multibox_head([x], num_classes, sizes=[(0.35, 0.6)],
                          ratios=[(1.0, 2.0, 0.5)], l2norm_first=False,
                          prefix="t")


def get_tiny_symbol_train(num_classes=2, **kwargs):
    data = sym.var("data")
    loc_pred, cls_pred, anchor = _tiny_head(data, num_classes)
    return _assemble_train(loc_pred, cls_pred, anchor)


def get_tiny_symbol(num_classes=2, nms_thresh=0.45, force_suppress=False,
                    nms_topk=100, **kwargs):
    data = sym.var("data")
    loc_pred, cls_pred, anchor = _tiny_head(data, num_classes)
    return _assemble_detect(loc_pred, cls_pred, anchor, nms_thresh,
                            force_suppress, nms_topk)
