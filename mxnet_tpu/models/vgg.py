"""VGG-11/13/16/19 symbolic builder.

Reference counterpart: ``example/image-classification/symbols/vgg.py``
(also the SSD backbone, example/ssd). Architecture per Simonyan &
Zisserman 2014; optional BatchNorm variant.
"""
from .. import symbol as sym
from ..base import MXNetError

_CFGS = {
    11: ((1, 64), (1, 128), (2, 256), (2, 512), (2, 512)),
    13: ((2, 64), (2, 128), (2, 256), (2, 512), (2, 512)),
    16: ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512)),
    19: ((2, 64), (2, 128), (4, 256), (4, 512), (4, 512)),
}


def get_feature(data, num_layers=16, batch_norm=False):
    if num_layers not in _CFGS:
        raise MXNetError("vgg: num_layers must be one of %s" % list(_CFGS))
    for i, (reps, filters) in enumerate(_CFGS[num_layers], 1):
        for j in range(1, reps + 1):
            data = sym.Convolution(data=data, kernel=(3, 3), pad=(1, 1),
                                   num_filter=filters,
                                   name="conv%d_%d" % (i, j))
            if batch_norm:
                data = sym.BatchNorm(data=data, fix_gamma=False,
                                     name="bn%d_%d" % (i, j))
            data = sym.Activation(data=data, act_type="relu",
                                  name="relu%d_%d" % (i, j))
        data = sym.Pooling(data=data, kernel=(2, 2), stride=(2, 2),
                           pool_type="max", name="pool%d" % i)
    return data


def get_symbol(num_classes=1000, num_layers=16, batch_norm=False,
               dtype="float32", **kwargs):
    data = sym.var("data")
    feat = get_feature(data, num_layers, batch_norm)
    flat = sym.Flatten(data=feat)
    fc6 = sym.FullyConnected(data=flat, num_hidden=4096, name="fc6")
    r6 = sym.Activation(data=fc6, act_type="relu")
    d6 = sym.Dropout(data=r6, p=0.5)
    fc7 = sym.FullyConnected(data=d6, num_hidden=4096, name="fc7")
    r7 = sym.Activation(data=fc7, act_type="relu")
    d7 = sym.Dropout(data=r7, p=0.5)
    fc8 = sym.FullyConnected(data=d7, num_hidden=num_classes, name="fc8")
    return sym.SoftmaxOutput(data=fc8, name="softmax")
