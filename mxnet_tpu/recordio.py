"""RecordIO: binary record container + indexed variant + image record header.

Reference counterpart: ``python/mxnet/recordio.py`` (456 LoC) over dmlc
recordio. Same on-disk format (magic 0xced7230a, length-framed records with
32-bit content checksumless header, 4-byte alignment) so record files made
by the reference's ``tools/im2rec`` are readable here and vice versa.
"""
from __future__ import annotations

import ctypes
import numbers
import os
import struct

import numpy as np

from .base import MXNetError

_MAGIC = 0xCED7230A
_LREC_HEADER = struct.Struct("<II")  # magic, lrec(len + cflag<<29)


def _encode_lrec(cflag, length):
    return (cflag << 29) | length


def _decode_lrec(lrec):
    return (lrec >> 29) & 7, lrec & ((1 << 29) - 1)


class MXRecordIO:
    """Sequential record reader/writer (ref: recordio.py MXRecordIO).

    Uses the native C++ runtime (src/recordio.cc via _native) when
    available — the dmlc-core tier of the reference — and falls back to
    pure Python (same on-disk bytes either way).
    """

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.writable = None
        self._nat = None      # (lib, native handle) when native-backed
        self.open()

    def open(self):
        from . import _native

        lib = _native.get_lib()
        if self.flag == "w":
            self.writable = True
        elif self.flag == "r":
            self.writable = False
        else:
            raise MXNetError("Invalid flag %s" % self.flag)
        if lib is not None:
            uri = self.uri.encode()
            h = (lib.MXTRecordIOWriterCreate(uri) if self.writable
                 else lib.MXTRecordIOReaderCreate(uri))
            if not h:
                raise MXNetError(_native.last_error()
                                 or "cannot open %s" % self.uri)
            self._nat = (lib, h)
        else:
            self.handle = open(self.uri, "wb" if self.writable else "rb")
        self.pid = os.getpid()

    def close(self):
        if self._nat is not None:
            lib, h = self._nat
            self._nat = None
            (lib.MXTRecordIOWriterClose if self.writable
             else lib.MXTRecordIOReaderClose)(h)
        if self.handle is not None:
            self.handle.close()
            self.handle = None

    def __del__(self):
        self.close()

    def __getstate__(self):
        d = dict(self.__dict__)
        d["handle"] = None
        d["_nat"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        if self.flag == "w":
            # reopen for append after unpickle in a worker process
            self.handle = open(self.uri, "ab")
        else:
            self.open()

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        if self._nat is not None:
            lib, h = self._nat
            return (lib.MXTRecordIOWriterTell if self.writable
                    else lib.MXTRecordIOReaderTell)(h)
        return self.handle.tell()

    def seek(self, pos):
        if self.writable:
            raise MXNetError("seek on a writable recordio")
        if self._nat is not None:
            lib, h = self._nat
            if lib.MXTRecordIOReaderSeek(h, pos) != 0:
                from . import _native

                raise MXNetError("recordio seek(%d) failed: %s"
                                 % (pos, _native.last_error()))
        else:
            self.handle.seek(pos)

    def write(self, buf):
        assert self.writable
        if not isinstance(buf, bytes):
            buf = bytes(buf)
        if self._nat is not None:
            lib, h = self._nat
            if lib.MXTRecordIOWriterWrite(h, buf, len(buf)) != 0:
                from . import _native

                raise MXNetError("recordio write failed: %s"
                                 % _native.last_error())
            return
        self.handle.write(_LREC_HEADER.pack(_MAGIC, _encode_lrec(0, len(buf))))
        self.handle.write(buf)
        pad = (4 - (len(buf) % 4)) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        if self._nat is not None:
            lib, h = self._nat
            out = ctypes.c_char_p()
            size = ctypes.c_size_t()
            rc = lib.MXTRecordIOReaderNext(h, ctypes.byref(out),
                                           ctypes.byref(size))
            if rc == 0:
                return None
            if rc < 0:
                from . import _native

                raise MXNetError("recordio read failed: %s"
                                 % _native.last_error())
            return ctypes.string_at(out, size.value)
        # split-record reassembly (cflag 1=first, 2=middle, 3=last chunk;
        # dmlc splits payloads at embedded magic words and the reader
        # re-inserts them) — same logic as the native src/recordio.cc
        parts = []
        in_split = False
        while True:
            header = self.handle.read(8)
            if len(header) < 8:
                if in_split:
                    raise MXNetError("truncated split record")
                return None if not parts else b"".join(parts)
            magic, lrec = _LREC_HEADER.unpack(header)
            if magic != _MAGIC:
                raise MXNetError("invalid record magic %x" % magic)
            cflag, length = _decode_lrec(lrec)
            if in_split:
                parts.append(_LREC_HEADER.pack(_MAGIC, 0)[:4])  # the magic
            parts.append(self.handle.read(length))
            pad = (4 - (length % 4)) % 4
            if pad:
                self.handle.read(pad)
            if cflag in (0, 3):
                return b"".join(parts)
            if cflag in (1, 2):
                in_split = True
                continue
            raise MXNetError("unknown cflag %d in recordio stream" % cflag)


class MXIndexedRecordIO(MXRecordIO):
    """Keyed random-access records via .idx file (ref: MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin.readlines():
                    line = line.strip().split("\t")
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)
        if self.writable:
            self.fidx = open(self.idx_path, "w")

    def close(self):
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None
        super().close()

    def read_idx(self, idx):
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


# image record header binary layout (flag, label, id, id2)
_IR_STRUCT = struct.Struct("IfQQ")


class _HeaderTuple(tuple):
    @property
    def flag(self):
        return self[0]

    @property
    def label(self):
        return self[1]

    @property
    def id(self):
        return self[2]

    @property
    def id2(self):
        return self[3]


def IRHeader(flag, label, id, id2):  # noqa: A002 — reference signature
    """Image record header constructor (ref: recordio.py
    ``IRHeader = namedtuple('HeaderType', ['flag','label','id','id2'])``)."""
    return _HeaderTuple((flag, label, id, id2))


def pack(header, s):
    """Pack a (flag,label,id,id2) header + payload bytes into one record.

    Multi-label: flag holds the label count and the float labels are
    prepended to the payload (same convention as the reference)."""
    flag, label, idx, idx2 = header
    if isinstance(label, numbers.Number):
        hdr = _IR_STRUCT.pack(flag, float(label), int(idx), int(idx2))
    else:
        label = np.asarray(label, dtype=np.float32)
        hdr = _IR_STRUCT.pack(len(label), 0.0, int(idx), int(idx2))
        s = label.tobytes() + s
    return hdr + s


def unpack(s):
    """Unpack a record into (header, payload)."""
    hdr = _HeaderTuple(_IR_STRUCT.unpack(s[: _IR_STRUCT.size]))
    s = s[_IR_STRUCT.size :]
    if hdr.flag > 0:
        n = hdr.flag
        label = np.frombuffer(s[: 4 * n], dtype=np.float32)
        return _HeaderTuple((hdr.flag, label, hdr.id, hdr.id2)), s[4 * n :]
    return hdr, s


def unpack_img(s, iscolor=1):
    """Unpack record into header + decoded image (ref: recordio.py unpack_img)."""
    hdr, img_bytes = unpack(s)
    from .image.image import imdecode_bytes

    img = imdecode_bytes(img_bytes, iscolor)
    return hdr, img


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    from .image.image import imencode_bytes

    buf = imencode_bytes(img, img_fmt, quality)
    return pack(header, buf)
