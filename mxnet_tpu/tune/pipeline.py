"""Training-pipeline ranking: remat x layout choices priced by the
learned cost model (ISSUE 19 tentpole, third leg).

PR 15's loop ranks *kernel schedules* — tile sizes for one Pallas
call. The same machinery prices *graph-level pipeline* choices: should
this training graph run with selective remat? with the layout pass?
Each candidate pipeline is compiled once and featurized from the
compiler's OWN analyses (``TrainStep.compiled_memory_stats``: peak /
temp bytes from ``memory_analysis()``, FLOPs and bytes-accessed from
``cost_analysis()``) plus the pass gauges (save/recompute site counts,
transposes cancelled), mapped onto the ``plan_summary`` feature keys
so the one :class:`~.model.CostModel` learns both levels.

Discipline is identical to the ranked kernel sweeps:

- **abstain-to-default** — no model, too few banked rows, or a
  validation correlation below the floor means the sweep times every
  candidate (exhaustive) and the trace-time consult
  (:func:`pipeline_for`) returns the hand default; predicted vs
  measured ms ride the sweep report and ``tuningStats``.
- **one table** — winners commit to the shared
  :class:`~.table.ScheduleTable` under the constant kernel name
  ``train_pipeline`` (so the model groups pipeline rows across
  graphs), keyed by a structural graph FINGERPRINT folded into the
  shape dims (node names excluded: two builds of the same
  architecture share an entry). Banked timings embed their feature
  plans, so :meth:`~.model.CostModel.fit_from_table` trains on them
  with zero changes.
- **no miss registry** — a pipeline key miss is a fallback, not
  background-tuner work (``sweep_for_key`` has no recipe for graphs);
  ``pipeline_for`` counts hits/misses/fallbacks itself.

``tools/tpu_kernel_smoke.py --passes`` runs the sweep in the scripted
tunnel session; ``tools/dump_graph.py --train`` shows the per-pass
plan a choice lowers to.
"""
from __future__ import annotations

import hashlib
import time

from .. import config
from ..base import MXNetError
from .table import get_table, make_key

PIPELINE_KERNEL = "train_pipeline"

# schedule codes (table schedules are ints >= 1 by contract)
REMAT_CODES = {"off": 1, "pass": 2, "conv": 3}
LAYOUT_CODES = {"off": 1, "on": 2}
_REMAT_NAMES = {v: k for k, v in REMAT_CODES.items()}
_LAYOUT_NAMES = {v: k for k, v in LAYOUT_CODES.items()}

# the abstain-mode choice: today's TrainStep defaults, bit-identical
# to a job that never heard of pipeline ranking
HAND_DEFAULT = {"remat": "off", "layout": "off"}


def candidate_pipelines():
    """The enumerable pipeline space: remat off|pass|conv x layout
    off|on. Small by design — each candidate costs one XLA
    compilation to featurize."""
    return [{"remat": r, "layout": l}
            for r in ("off", "pass", "conv")
            for l in ("off", "on")]


def schedule_of(choice):
    """Encode a pipeline choice as a table schedule (known int knobs)."""
    try:
        return {"remat": REMAT_CODES[choice["remat"]],
                "layout": LAYOUT_CODES[choice["layout"]]}
    except KeyError as e:
        raise MXNetError("unknown pipeline choice field/value: %s in %r"
                         % (e, choice))


def choice_of(schedule):
    """Decode a table schedule back into a pipeline choice; unknown
    codes raise (a corrupt entry must not silently train differently)."""
    try:
        return {"remat": _REMAT_NAMES[int(schedule["remat"])],
                "layout": _LAYOUT_NAMES[int(schedule["layout"])]}
    except (KeyError, TypeError, ValueError):
        raise MXNetError("not a pipeline schedule: %r" % (schedule,))


def graph_fingerprint(symbol):
    """Structural md5 over the graph: op names, sorted attrs, arity and
    input topology indices — node NAMES excluded, so two builds of the
    same architecture (auto-named differently) share a table entry."""
    h = hashlib.md5()
    nodes = symbol._topo()
    index = {id(n): i for i, n in enumerate(nodes)}
    for n in nodes:
        if n.is_variable():
            h.update(b"var;")
            continue
        h.update(n.op.name.encode())
        for k in sorted(n.attrs):
            h.update(("|%s=%s" % (k, n.attrs[k])).encode())
        for inp, idx in n.inputs:
            h.update(("|%d.%d" % (index[id(inp)], idx)).encode())
        h.update(b";")
    return h.hexdigest()


def pipeline_table_shape(symbol, batch_shape):
    """The table-key shape dims: the fingerprint's leading 32 bits
    folded in as an int dim, then the data batch shape — make_key only
    speaks int dims, and this keeps distinct graphs/batch shapes in
    distinct entries."""
    return (int(graph_fingerprint(symbol)[:8], 16),) + tuple(
        int(d) for d in batch_shape)


def featurize(stats, n_nodes, n_save=0, n_recompute=0,
              transposes_cancelled=0):
    """Map one compiled candidate onto the ``plan_summary`` feature
    keys (the CostModel join contract): m/k/n/work carry the XLA
    analyses, calls/nb/th/bco the graph and pass gauges. All values
    are floored to 1 inside ``features_from_plan``."""
    return {
        "m": int(stats.get("peak_bytes", 0)),
        "k": int(stats.get("bytes_accessed", 0)),
        "n": int(stats.get("flops", 0)),
        "work": int(stats.get("temp_bytes", 0)),
        "calls": int(n_nodes),
        "grid": (1, 1, 1),
        "nb": int(n_save) + 1,
        "th": int(n_recompute) + 1,
        "bco": int(transposes_cancelled) + 1,
    }


def _step_kwargs(choice):
    """TrainStep ctor kwargs realizing a pipeline choice."""
    remat = choice["remat"]
    return {
        "remat": False if remat == "off" else remat,
        "train_passes": ("layout",) if choice["layout"] == "on" else (),
    }


def build_train_step(symbol, optimizer, choice, **kw):
    """A TrainStep realizing ``choice`` over ``symbol`` (sweep helper;
    also how a caller applies :func:`pipeline_for`'s decision)."""
    from ..parallel.spmd import TrainStep

    merged = dict(kw)
    merged.update(_step_kwargs(choice))
    return TrainStep(symbol, optimizer, **merged)


def _compile_candidate(symbol, optimizer, choice, batch, data_shapes,
                       seed, step_kw):
    """Build + compile one candidate; returns (TrainStep, carry, plan)
    where plan is the featurization dict."""
    import jax

    ts = build_train_step(symbol, optimizer, choice, **step_kw)
    params, opt_state, aux = ts.init_params(data_shapes, seed=seed)
    carry = ts.place(params, opt_state, aux)
    stats = ts.compiled_memory_stats(carry, batch, jax.random.PRNGKey(0))
    n_nodes = sum(1 for n in ts.symbol._topo() if not n.is_variable())
    plan = featurize(
        stats, n_nodes,
        n_save=ts._remat_plan.n_save if ts._remat_plan else 0,
        n_recompute=ts._remat_plan.n_recompute if ts._remat_plan else 0)
    return ts, carry, stats, plan


def _time_candidate(ts, carry, batch, steps):
    """Median-free mean ms/step over ``steps`` post-warmup steps (the
    compile already happened in featurization, so step 0 is warm)."""
    import jax

    key = jax.random.PRNGKey(1)
    carry, loss = ts(carry, batch, key)        # warmup / donation settle
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        carry, loss = ts(carry, batch, key)
    jax.block_until_ready(loss)
    return (time.perf_counter() - t0) * 1e3 / max(steps, 1)


def sweep_train_pipelines(symbol, optimizer, batch, *, table=None,
                          backend=None, ranked=None, topk=None, steps=3,
                          seed=0, data_names=("data",), step_kw=None):
    """Compile + featurize every candidate pipeline for ``symbol``,
    rank with the cost model (abstain -> exhaustive), time the
    survivors end-to-end, commit the winner to the schedule table and
    refit the model from the banked rows — the graph-level mirror of
    ``search.sweep_fused``.

    ``batch`` is a dict of host/device arrays covering the data AND
    label names ``TrainStep`` expects; timing runs ``steps`` steps per
    survivor after one warmup. Returns the sweep report (trajectory
    with predicted + measured ms per candidate, ranker mode, winner).
    """
    import numpy as np

    from . import model as cost_model_mod
    from .. import profiler
    from .search import _resolve_ranker

    t_start = time.perf_counter()
    if backend is None:
        import jax

        backend = jax.default_backend()
    table = table if table is not None else get_table()
    ranked, topk = _resolve_ranker(ranked, topk)
    step_kw = dict(step_kw or {})
    step_kw.setdefault("data_names", tuple(data_names))
    data_shapes = {n: tuple(batch[n].shape) for n in step_kw["data_names"]}
    batch_shape = data_shapes[step_kw["data_names"][0]]
    shape = pipeline_table_shape(symbol, batch_shape)
    dtype = str(batch[step_kw["data_names"][0]].dtype)

    entries = []
    for choice in candidate_pipelines():
        status = "default" if choice == HAND_DEFAULT else "candidate"
        entries.append({"choice": dict(choice),
                        "schedule": schedule_of(choice), "status": status})

    # featurize: one compile per candidate (this is the sweep's cost)
    compiled = {}
    for e in entries:
        ts, carry, stats, plan = _compile_candidate(
            symbol, optimizer, e["choice"], batch, data_shapes, seed,
            step_kw)
        compiled[id(e)] = (ts, carry)
        e["plan"] = plan
        e["peak_bytes"] = stats["peak_bytes"]
        e["temp_bytes"] = stats["temp_bytes"]

    # rank (the _apply_ranking discipline, on embedded plans)
    cands = [e for e in entries if e["status"] == "candidate"]
    rank_info = {"mode": "exhaustive", "abstained": False}
    if ranked:
        m = cost_model_mod.get_model(cost_model_mod.model_path_for(table))
        ok, why = m.usable(PIPELINE_KERNEL, backend)
        if not ok:
            profiler.tuning_record(ranker_abstains=1)
            rank_info = {"mode": "exhaustive", "abstained": True,
                         "reason": why}
        else:
            pred = m.predict(PIPELINE_KERNEL, backend,
                             [e["plan"] for e in cands])
            order = np.argsort(pred, kind="mergesort")
            keep = set(int(i) for i in order[:topk])
            skipped = 0
            for i, e in enumerate(cands):
                e["predicted_ms"] = round(float(pred[i]), 6)
                if i not in keep:
                    e["status"] = "skipped_ranked"
                    skipped += 1
            profiler.tuning_record(candidates_ranked=len(cands),
                                   timings_skipped=skipped)
            rank_info = {
                "mode": "ranked", "abstained": False, "topk": topk,
                "n_scored": len(cands), "n_skipped": skipped,
                "group": cost_model_mod.group_key(PIPELINE_KERNEL,
                                                  backend),
                "val_corr": (m.group(PIPELINE_KERNEL, backend)
                             or {}).get("val_corr")}

    # time the default + surviving candidates
    timed = [e for e in entries if e["status"] in ("default", "candidate")]
    for e in timed:
        ts, carry = compiled[id(e)]
        e["ms_per_iter"] = round(_time_candidate(ts, carry, batch, steps),
                                 5)

    default = next(e for e in timed if e["status"] == "default")
    winner = min(timed, key=lambda e: e["ms_per_iter"])
    rec = {
        "schedule": dict(winner["schedule"]),
        "ms_per_iter": winner["ms_per_iter"],
        "default_schedule": dict(default["schedule"]),
        "default_ms_per_iter": default["ms_per_iter"],
        "speedup_vs_default": round(
            default["ms_per_iter"] / winner["ms_per_iter"], 3)
        if winner["ms_per_iter"] else 1.0,
        # banked rows EMBED their plans: plan_for has no recipe for
        # graphs, so the model's _record_rows must never need it here
        "timings": [{"schedule": dict(e["schedule"]),
                     "ms_per_iter": e["ms_per_iter"],
                     "plan": dict(e["plan"])} for e in timed],
    }
    table.record(PIPELINE_KERNEL, shape, dtype, backend, rec)
    key = make_key(PIPELINE_KERNEL, shape, dtype, backend)
    profiler.tuning_record(kernel=key,
                           schedule=dict(winner["schedule"]),
                           source="sweep")
    report = {
        "key": key, "kernel": PIPELINE_KERNEL, "shape": list(shape),
        "dtype": dtype, "backend": backend,
        "fingerprint": graph_fingerprint(symbol),
        "trajectory": [
            {k: v for k, v in e.items() if k != "plan"} for e in entries],
        "n_candidates": len(entries),
        "n_timed": len(timed),
        "n_skipped_ranked": sum(1 for e in entries
                                if e["status"] == "skipped_ranked"),
        "ranker": rank_info,
        "winner": {"choice": dict(winner["choice"]),
                   "schedule": dict(winner["schedule"]),
                   "ms_per_iter": winner["ms_per_iter"],
                   "peak_bytes": winner["peak_bytes"],
                   "speedup_vs_default": rec["speedup_vs_default"]},
    }
    try:
        fit_rep = cost_model_mod.fit_cost_model(table)
        report["model_refit"] = fit_rep["fit"]
    except cost_model_mod.CostModelError as e:
        report["model_refit_error"] = str(e)
    report["wall_s"] = round(time.perf_counter() - t_start, 4)
    return report


def pipeline_for(symbol, batch_shape, dtype="float32", backend=None,
                 table=None):
    """Trace-time consult: the committed pipeline choice for this
    graph fingerprint + batch shape, or the hand default.

    Returns ``(choice, source)`` with source ``"table"`` or
    ``"default"``. Abstain-to-default discipline: tuning disabled, no
    entry, or an undecodable schedule all return :data:`HAND_DEFAULT`
    (today's TrainStep behavior) and count a fallback; never raises on
    a missing entry and never enqueues background-tuner work (there is
    no sweep recipe reconstructable from a table key alone)."""
    from .. import profiler

    if not config.get_bool("MXNET_TPU_TUNE", True):
        return dict(HAND_DEFAULT), "default"
    if backend is None:
        import jax

        backend = jax.default_backend()
    table = table if table is not None else get_table()
    shape = pipeline_table_shape(symbol, batch_shape)
    key = make_key(PIPELINE_KERNEL, shape, str(dtype), backend)
    sched = table.lookup(PIPELINE_KERNEL, shape, str(dtype), backend,
                         record_stats=False)
    if sched is None:
        profiler.tuning_record(misses=1, fallbacks=1)
        return dict(HAND_DEFAULT), "default"
    try:
        choice = choice_of(sched)
    except MXNetError:
        profiler.tuning_record(fallbacks=1)
        return dict(HAND_DEFAULT), "default"
    profiler.tuning_record(hits=1, kernel=key, schedule=dict(sched),
                           source="table")
    return choice, "table"
