"""Background tuning inside training jobs (ISSUE 15).

The PR 10 sweep is an offline chore; this module makes tuning
something long training jobs do *continuously*: a
:class:`BackgroundTuner`, armed by ``MXNET_TUNE_BACKGROUND=1``, steals
**bounded idle slots at drain boundaries** — the points where the PR 5
dispatch-ahead pipeline has already been drained (epoch end's
``get_params``, checkpoint quiesce) — and times one ranked candidate
set for a shape the job actually traced.

Safety contract (the README "Autotuning" section documents it):

- **Drain-boundary only.** ``Module.fit`` calls :meth:`on_drain` right
  after the epoch-end ``get_params``/``set_params`` pair, i.e. after
  the dispatch-ahead pipeline blocked to empty — never inside the
  steady-state step loop, so pipeline/inflight counters stay flat.
- **Bounded per-slot budget.** One missed key per slot, at most
  ``MXNET_TUNE_BG_BUDGET`` timed programs (hand default included),
  with short calibration targets — a slot costs a bounded sliver of
  an epoch.
- **Zero effect when there is nothing to do.** The work queue is the
  schedule table's miss registry (``table.recorded_misses`` — filled
  by the trace-time ``schedule_for`` consults), so a job whose shapes
  are all tuned, or that never traces a Pallas kernel, pays nothing.
  ``MXNET_TPU_TUNE=0`` disables the consult and therefore the tuner.
- **Never crashes training.** A failed sweep logs, drops the miss,
  and the job continues; commits ride the table's atomic
  merge-base-re-reading path, so two concurrent jobs sharing one
  table file cannot clobber each other's winners.

Winners are committed atomically, so the *next* trace of the same
shape (and any later job) picks them up — tuning becomes a property
of running training, not a separate tool invocation.
"""
from __future__ import annotations

import logging
import os

from .. import config, profiler
from . import search
from .table import clear_miss, get_table, recorded_misses

log = logging.getLogger("mxnet_tpu.tune")


class BackgroundTuner:
    """Steals bounded tuning slots at a training job's drain
    boundaries; see the module docstring for the safety contract."""

    def __init__(self, budget=2, table=None, logger=None, sweep_kw=None):
        import jax

        self.budget = int(budget)
        self._table = table if table is not None else get_table()
        self._log = logger or log
        on_tpu = jax.default_backend() == "tpu"
        # bounded per-slot timing discipline: short calibration target,
        # few repeats — a slot is a sliver of an epoch, not a bench run
        self._sweep_kw = dict(
            repeats=2,
            target_sec=0.2 if on_tpu else 0.02,
            min_iters=100 if on_tpu else 2,
            interpret=None if on_tpu else True)
        if sweep_kw:
            self._sweep_kw.update(sweep_kw)

    @classmethod
    def from_env(cls, logger=None):
        """The arming gate ``Module.fit`` consults: returns a tuner
        when ``MXNET_TUNE_BACKGROUND=1`` (strict bool — malformed
        raises naming the knob), else None. ``MXNET_TPU_TUNE=0`` also
        disarms: with the trace-time consult off no misses are
        recorded, so there is nothing to tune. Only rank 0 of a
        multi-worker job arms: every worker traces the same shapes, so
        N workers sweeping the same miss at the same drain boundary
        would pay N bounded slots for one winner — rank 0 tunes,
        everyone picks the commit up at the next trace."""
        if not config.get_strict_bool("MXNET_TUNE_BACKGROUND"):
            return None
        if not config.get_bool("MXNET_TPU_TUNE", True):
            return None
        rank = (os.environ.get("DMLC_WORKER_ID")
                or os.environ.get("DMLC_RANK") or "0")
        try:
            rank = int(rank)
        except ValueError:
            rank = 0
        import jax

        if rank != 0 or jax.process_index() != 0:
            return None
        return cls(budget=config.get_positive_int("MXNET_TUNE_BG_BUDGET"),
                   logger=logger)

    def pending(self):
        """Misses with a sweep recipe that the table has not satisfied
        yet — what the next slots will tune, oldest trace first.
        Re-reads the table file (one bounded read), so another job's
        commits clear their misses here instead of this process's
        memoized negative serving forever; recipe-less misses are
        dropped (nothing will ever tune them)."""
        from . import model as cost_model_mod

        self._table.reload()   # see another job's commits, not the memo
        # same for the model: an external refit (tune_kernels, another
        # job's ranked sweep) must un-abstain this job's slots
        cost_model_mod.get_model(
            cost_model_mod.model_path_for(self._table)).reload()
        out = []
        for miss in recorded_misses():
            if miss["kernel"] not in search.SWEEPABLE_KERNELS:
                clear_miss(miss["key"])   # no sweep recipe: don't retry
                continue
            if self._table.lookup(miss["kernel"], miss["shape"],
                                  miss["dtype"], miss["backend"],
                                  record_stats=False) is not None:
                clear_miss(miss["key"])   # another job tuned it already
                continue
            out.append(miss)
        return out

    def on_drain(self):
        """One bounded tuning slot: sweep the oldest pending miss
        (ranked when the model is usable — ``MXNET_TUNE_RANKER``
        semantics apply unchanged) and commit the winner atomically.
        Returns the sweep report, or None when nothing was pending.
        Exceptions never propagate — background tuning must not crash
        the training job."""
        for miss in self.pending():
            profiler.tuning_record(bg_slots=1)
            try:
                rep = search.sweep_for_key(
                    miss["kernel"], miss["shape"], miss["dtype"],
                    backend=miss["backend"], table=self._table,
                    budget=self.budget, **self._sweep_kw)
            except Exception as e:   # noqa: BLE001 — never crash training
                clear_miss(miss["key"])
                self._log.warning("background tune of %s failed: %s",
                                  miss["key"], e)
                return None
            clear_miss(miss["key"])
            profiler.tuning_record(bg_commits=1)
            self._log.info(
                "background tune committed %s -> %s (%d timed, %.2fs)",
                miss["key"], rep["winner"]["schedule"], rep["n_timed"],
                rep.get("wall_s") or 0.0)
            return rep
        return None
