"""Schedule autotuner for the Pallas kernels (ISSUE 10, ROADMAP item 1).

Reference counterpart: the reference framework leaned on cuDNN's
autotuner (``MXNET_CUDNN_AUTOTUNE_DEFAULT``) and marked the concern
"subsumed" by XLA in config.py — but the *Pallas* kernels sit below
XLA's autotuning: their row-tile / channel-block / batch-fold and
flash-attention block sizes were hand-picked constants. TVM
(arXiv:1802.04799) showed schedule search over exactly this tile/block
knob space beats hand schedules, and Relay (arXiv:1810.00952) that the
payoff compounds when tuned schedules are consulted at bind time rather
than baked into call sites. This package is that loop for the Pallas
tier:

- :mod:`.table` — the on-disk schedule table: versioned JSON records
  keyed by ``(kernel, shape, dtype, backend)``, atomic writes
  (``checkpoint.atomic_write_bytes``), a process-local memo so the
  hot-path :func:`schedule_for` lookup is a dict hit, and loud-but-
  non-fatal handling of corrupt/stale tables (a broken table must
  never crash a training job — it logs, falls back to the hand
  defaults, and is rewritten by the next tune).
- :mod:`.harness` — the loop-amortized single-jitted-``lax.scan``
  timing harness (the PR 1 measurement half, shared with
  tools/bench_kernel.py).
- :mod:`.search` — candidate generation over the existing knob space,
  pre-timing pruning (illegal tiles and, where the shape can meet it,
  sub-``MXU_WORK_FLOOR`` candidates — ``mxu_plan`` is the legality/
  work oracle), round-robin candidate timing, and table commits.

Kernel entry points (``fused_block`` fwd/wgrad/dgrad,
``flash_attention``) consult :func:`schedule_for` at trace time with
the current hand defaults as fallback, so an empty table is
bit-identical to the pre-autotuner behavior. ``tools/tune_kernels.py``
runs the sweep offline; ``profiler.tuning_stats`` counts table
hits/misses/fallbacks and records each kernel's chosen schedule.

ISSUE 15 grows the loop with a *learned* half:

- :mod:`.model` — a pure-numpy learned cost model (ridge on log
  plan-summary features) trained on the table's banked timings,
  cross-validated per (kernel, backend), abstaining (exhaustive
  fallback) when under-trained or below the rank-correlation floor.
- ranked sweeps — :func:`sweep_fused`/:func:`sweep_flash` time only
  the model's top-``MXNET_TUNE_TOPK`` candidates (hand default always
  included) and refit the model from every commit.
- :mod:`.background` — :class:`BackgroundTuner`: long training jobs
  tune the shapes they actually traced in bounded slots at drain
  boundaries (armed by ``MXNET_TUNE_BACKGROUND=1``).
"""
from .table import (ScheduleTable, TABLE_VERSION, clear_misses,
                    default_table_path, get_table, make_key,
                    recorded_misses, schedule_for)
from .table import reset as _reset_table
from .search import (FLASH_BLOCKS, FUSED_KINDS, SWEEPABLE_KERNELS,
                     flash_candidates, fused_candidates, sweep_flash,
                     sweep_for_key, sweep_fused)
from .model import (CostModel, CostModelError, MODEL_VERSION,
                    default_model_path, features_from_plan,
                    fit_cost_model, get_model, plan_for)
from .model import reset as _reset_model
from .background import BackgroundTuner
from .pipeline import (HAND_DEFAULT, LAYOUT_CODES, PIPELINE_KERNEL,
                       REMAT_CODES, build_train_step, candidate_pipelines,
                       choice_of, graph_fingerprint, pipeline_for,
                       pipeline_table_shape, schedule_of,
                       sweep_train_pipelines)


def reset():
    """Drop the process-global table, miss registry, and cost model —
    tests, and long-lived processes that want to pick up externally
    updated files."""
    _reset_table()
    _reset_model()



def rule_kernels():
    """{IR rule name: kernel names it lands on} from the pass
    framework's rule registry (ISSUE 13): a fusion rule *names* the
    Pallas kernel family its rewrite consults, and the autotuner folds
    those names into its sweep set automatically — new fusions become
    searchable schedule-table keys with zero edits here."""
    from ..ir.rules import registered_kernels

    return registered_kernels()


def sweepable_kernels():
    """Kernel names the offline sweep covers by default: the built-in
    families plus every kernel a registered IR rule names (unknown
    rule-named kernels are surfaced by tools/tune_kernels.py as
    unsweepable rather than silently dropped)."""
    names = list(SWEEPABLE_KERNELS)
    for kernels in rule_kernels().values():
        for k in kernels:
            if k not in names:
                names.append(k)
    return tuple(names)


__all__ = [
    "ScheduleTable", "TABLE_VERSION", "default_table_path", "get_table",
    "make_key", "reset", "schedule_for", "recorded_misses", "clear_misses",
    "FLASH_BLOCKS", "FUSED_KINDS", "SWEEPABLE_KERNELS", "flash_candidates",
    "fused_candidates", "rule_kernels", "sweepable_kernels",
    "sweep_flash", "sweep_fused", "sweep_for_key",
    "BackgroundTuner", "CostModel", "CostModelError", "MODEL_VERSION",
    "default_model_path", "features_from_plan", "fit_cost_model",
    "get_model", "plan_for",
    "PIPELINE_KERNEL", "HAND_DEFAULT", "REMAT_CODES", "LAYOUT_CODES",
    "candidate_pipelines", "schedule_of", "choice_of",
    "graph_fingerprint", "pipeline_table_shape", "build_train_step",
    "sweep_train_pipelines", "pipeline_for",
]
