"""Learned cost model for the schedule autotuner (ISSUE 15).

TVM's actual lesson (arXiv:1802.04799) is not the exhaustive sweep PR
10 shipped — it is a *learned cost model* that ranks candidates so
only the top few are ever timed, and that keeps learning from every
measurement the tuner banks. This module is that model, pure numpy
(ridge regression on log features — no new dependencies):

- **Featurization joins on ``search.plan_summary``.** A candidate's
  feature vector is derived from exactly the ``mxu_plan`` summary the
  schedule table banks per timing and ``bench_kernel`` emits per
  record (``grid/nb/th/bco/m/k/n/work/calls``), so table entries,
  bench records, and model inputs all join on the same keys. Flash
  attention maps its ``(block_q, block_k)`` space onto the same
  summary shape (:func:`plan_for`), so one featurization covers every
  kernel family.
- **Grouped per (kernel, backend).** A model fit on CPU-interpret
  timings says nothing about the MXU; groups are keyed
  ``kernel|backend`` and each group is cross-validated independently
  (k-fold, pooled Spearman rank correlation — ranking is the job, so
  rank correlation is the score).
- **Abstains instead of guessing.** :meth:`CostModel.usable` is the
  ranked sweep's gate: a missing group, fewer than ``MIN_FIT_ROWS``
  training rows, or a validation rank correlation below
  ``CORR_FLOOR`` all fall back to the PR 10 exhaustive sweep — an
  empty or missing model is behaviorally identical to today.
- **Corruption-proof like the schedule table.** One versioned JSON
  file written through ``checkpoint.atomic_write_bytes``; a
  truncated/garbage/version-mismatched file logs, behaves as absent
  (exhaustive fallback), and is rewritten whole by the next fit.
  ``load(strict=True)`` raises typed :class:`CostModelError` for
  tooling that wants the loud version.

The training rows come from :meth:`ScheduleTable.entries`: every sweep
commit now banks *all* its candidate timings (not just the winner), so
the model improves across sweeps — including the background-tuning
slots a long ``Module.fit`` run steals at drain boundaries
(:mod:`.background`).
"""
from __future__ import annotations

import json
import logging
import math
import os
import threading

import numpy as np

from .. import config
from ..base import MXNetError
from .search import FUSED_KINDS, plan_summary

log = logging.getLogger("mxnet_tpu.tune")

MODEL_VERSION = 1

# the featurization contract: log-space values derived from the
# plan_summary keys (plus the per-axis grid dims, the grid product,
# and the whole-kernel MAC total — the dominant cross-shape
# predictor; the axis split matters because loop overhead scales with
# the contraction-axis block count, not just the product). Changing
# this list is a model-file version change — loads reject files whose
# feature list differs.
FEATURE_NAMES = ("m", "k", "n", "work", "calls", "grid", "g0", "g1",
                 "g2", "nb", "th", "bco", "total_work")

MIN_FIT_ROWS = 8      # fewer banked rows than this: the group abstains
CORR_FLOOR = 0.5      # validation Spearman below this: abstain
RIDGE_LAMBDA = 1e-1   # heavy-ish: training sets are small and noisy


class CostModelError(MXNetError):
    """Typed error for corrupt/version-mismatched cost-model files and
    insufficient-data refits (the loud paths; the ranked sweep itself
    always degrades to exhaustive instead of raising)."""


def default_model_path():
    """``MXNET_TUNE_MODEL`` when set, else next to the schedule table
    it learns from (``<table>.model.json``) — a test/tool that scopes
    the table to a tmp dir scopes the model with it."""
    override = config.get("MXNET_TUNE_MODEL")
    if override:
        return override
    from .table import default_table_path

    return default_table_path() + ".model.json"


def model_path_for(table):
    """Model path scoped to one :class:`ScheduleTable` instance:
    ``MXNET_TUNE_MODEL`` still wins, else the model lives next to THE
    table being swept — a sweep over a custom ``table=`` must not read
    or rewrite the default table's model file."""
    override = config.get("MXNET_TUNE_MODEL")
    if override:
        return override
    return table.path + ".model.json"


def group_key(kernel, backend):
    """The model's group key: prediction quality is cross-validated
    per (kernel, backend) — a CPU-interpret fit never ranks a TPU
    sweep."""
    return "%s|%s" % (kernel, backend)


# ---------------------------------------------------------------------------
# featurization (shared with search.plan_summary — the join contract)
# ---------------------------------------------------------------------------
def plan_for(kernel, shape, schedule):
    """A ``plan_summary``-shaped dict for any sweepable kernel at a
    table-key ``shape`` under ``schedule`` — the one featurization
    entry point. Fused kernels go through ``fused_block.mxu_plan``;
    flash attention maps (block_q, block_k) onto the same keys: the
    per-block matmul is (block_q x d) @ (d x block_k) and the grid is
    (batch*heads, q-blocks, k-blocks)."""
    if kernel in FUSED_KINDS:
        from ..kernels import fused_block as fb

        n, h, wd, ci, co, k, stride = (int(d) for d in shape)
        return plan_summary(fb.mxu_plan(
            kernel[len("fused_"):], (n, h, wd, ci), (k, k, ci, co),
            stride=stride, schedule=schedule))
    if kernel == "flash_attention":
        b, h, sq, sk, d, causal = (int(v) for v in shape)
        bq = int(schedule["block_q"])
        bk = int(schedule["block_k"])
        qb = -(-sq // bq)
        kb = -(-sk // bk)
        if causal:
            # the kernel truncates the k-loop per q-block (causal
            # costs ~half the FLOPs — flash_attention.py), so the
            # feature is the *visited* k-block count: causal and
            # non-causal rows with the same blocks must not carry
            # identical features for ~2x-different measured ms
            kb = max(1, (kb + 1) // 2)
        return {"grid": [b * h, qb, kb], "nb": 1, "th": bq, "bco": bk,
                "m": bq, "k": d, "n": bk, "work": bq * d * bk,
                "calls": 1}
    raise CostModelError("no featurization for kernel %r" % (kernel,))


def features_from_plan(plan):
    """Log-space feature vector (len == len(FEATURE_NAMES)) from a
    ``plan_summary`` dict — the shared representation table timings,
    bench_kernel records, and model inputs all reduce to."""
    dims = [max(int(d), 1) for d in plan.get("grid") or (1,)][:3]
    dims += [1] * (3 - len(dims))
    grid = 1
    for d in dims:
        grid *= d
    vals = (plan["m"], plan["k"], plan["n"], plan["work"], plan["calls"],
            grid, dims[0], dims[1], dims[2], plan.get("nb", 1),
            plan.get("th", 1), plan.get("bco", 1),
            float(plan["work"]) * float(plan["calls"]) * grid)
    return np.array([math.log(max(float(v), 1.0)) for v in vals],
                    np.float64)


# ---------------------------------------------------------------------------
# ridge + rank correlation (pure numpy)
# ---------------------------------------------------------------------------
def _ranks(v):
    v = np.asarray(v, np.float64)
    order = np.argsort(v, kind="mergesort")
    r = np.empty(len(v), np.float64)
    i = 0
    while i < len(v):
        j = i
        while j + 1 < len(v) and v[order[j + 1]] == v[order[i]]:
            j += 1
        r[order[i:j + 1]] = (i + j) / 2.0
        i = j + 1
    return r


def spearman(a, b):
    """Spearman rank correlation — the validation score: the ranker's
    job is ordering candidates, not predicting absolute ms."""
    ra, rb = _ranks(a), _ranks(b)
    sa, sb = ra.std(), rb.std()
    if sa == 0 or sb == 0:
        return 0.0
    return float(((ra - ra.mean()) * (rb - rb.mean())).mean() / (sa * sb))


def _ridge_fit(X, y, lam=RIDGE_LAMBDA):
    mu = X.mean(0)
    sd = X.std(0)
    sd = np.where(sd == 0, 1.0, sd)
    Z = (X - mu) / sd
    ym = float(y.mean())
    A = Z.T @ Z + lam * max(len(y), 1) * np.eye(Z.shape[1])
    w = np.linalg.solve(A, Z.T @ (y - ym))
    return w, mu, sd, ym


def _ridge_predict(X, w, mu, sd, intercept):
    return ((X - mu) / sd) @ w + intercept


def _cv_corr(X, y, lam=RIDGE_LAMBDA):
    """k-fold cross-validation rank correlation: strided folds, pooled
    held-out predictions, one Spearman over the pool."""
    n = len(y)
    k = min(5, n)
    preds = np.empty(n, np.float64)
    idx = np.arange(n)
    for f in range(k):
        test = idx[f::k]
        train = np.setdiff1d(idx, test)
        w, mu, sd, b = _ridge_fit(X[train], y[train], lam)
        preds[test] = _ridge_predict(X[test], w, mu, sd, b)
    return spearman(preds, y)


def _valid_group(g):
    if not isinstance(g, dict):
        return False
    try:
        rows = g["rows"]
        corr = float(g["val_corr"])
        intercept = float(g["intercept"])
        w = np.asarray(g["weights"], np.float64)
        mu = np.asarray(g["mu"], np.float64)
        sd = np.asarray(g["sd"], np.float64)
    except (KeyError, TypeError, ValueError):
        return False
    if not (isinstance(rows, int) and not isinstance(rows, bool)
            and rows >= 1):
        return False
    nfeat = len(FEATURE_NAMES)
    if w.shape != (nfeat,) or mu.shape != (nfeat,) or sd.shape != (nfeat,):
        return False
    return bool(np.isfinite(w).all() and np.isfinite(mu).all()
                and np.isfinite(sd).all() and np.isfinite(corr)
                and np.isfinite(intercept))


# ---------------------------------------------------------------------------
# the model
# ---------------------------------------------------------------------------
class CostModel:
    """One on-disk cost-model file + its per-(kernel, backend) ridge
    groups. Mirrors :class:`ScheduleTable`'s load discipline: lazy,
    memo'd, corruption logs + behaves as absent."""

    def __init__(self, path=None):
        self.path = path or default_model_path()
        self._lock = threading.Lock()
        self._groups = None   # group_key -> group dict; None until loaded
        self.load_error = None

    # -- load / persist ----------------------------------------------------
    def _load_locked(self):
        if self._groups is not None:
            return
        self._groups = {}
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                raw = f.read()
        except FileNotFoundError:
            return
        except OSError as e:
            self.load_error = "unreadable: %s" % e
            log.warning("cost model %s unreadable (%s); ranker abstains "
                        "(exhaustive sweeps)", self.path, e)
            return
        try:
            data = json.loads(raw)
            if not isinstance(data, dict):
                raise ValueError("top level is %s, not an object"
                                 % type(data).__name__)
            version = data.get("version")
            if version != MODEL_VERSION:
                raise ValueError("version %r != %d" % (version,
                                                       MODEL_VERSION))
            if tuple(data.get("features") or ()) != FEATURE_NAMES:
                raise ValueError("feature list %r does not match this "
                                 "build's featurization"
                                 % (data.get("features"),))
            groups = data["groups"]
            if not isinstance(groups, dict):
                raise ValueError("groups is %s, not an object"
                                 % type(groups).__name__)
            loaded = {}
            for gk, g in groups.items():
                if not _valid_group(g):
                    raise ValueError("malformed group record for %r" % gk)
                loaded[gk] = dict(g)
        except (ValueError, KeyError, TypeError) as e:
            # corrupt/stale model: behave as ABSENT — ranked sweeps
            # abstain into the exhaustive path and the next fit
            # rewrites the whole file. Never crash a job.
            self.load_error = str(e)
            log.warning(
                "cost model %s is corrupt or from another version (%s); "
                "ranker abstains (exhaustive sweeps) — the next model "
                "fit rewrites it", self.path, e)
            return
        self._groups = loaded

    def reload(self):
        """Drop the memoized load so the next read re-reads the file —
        a long-lived process picking up an external refit (mirrors
        :meth:`ScheduleTable.reload`; the background tuner calls both
        once per drain slot)."""
        with self._lock:
            self._groups = None
            self.load_error = None

    def load(self, strict=False):
        """Force the lazy load; ``strict=True`` raises typed
        :class:`CostModelError` on a corrupt/mismatched file instead of
        the silent absent-fallback."""
        with self._lock:
            self._load_locked()
            if strict and self.load_error is not None:
                raise CostModelError("cost model %s: %s"
                                     % (self.path, self.load_error))
            return {gk: dict(g) for gk, g in self._groups.items()}

    def _persist_locked(self):
        payload = {"version": MODEL_VERSION,
                   "features": list(FEATURE_NAMES),
                   "groups": self._groups}
        data = json.dumps(payload, indent=1, sort_keys=True).encode("utf-8")
        d = os.path.dirname(os.path.abspath(self.path))
        if d:
            os.makedirs(d, exist_ok=True)
        from ..checkpoint import atomic_write_bytes

        atomic_write_bytes(self.path, data)
        self.load_error = None

    # -- prediction --------------------------------------------------------
    def usable(self, kernel, backend):
        """(ok, reason) — the ranked sweep's abstain gate for one
        (kernel, backend) group."""
        with self._lock:
            self._load_locked()
            g = self._groups.get(group_key(kernel, backend))
        if g is None:
            return False, ("no model for %s" % group_key(kernel, backend)
                           if not self.load_error
                           else "model unusable: %s" % self.load_error)
        if g["rows"] < MIN_FIT_ROWS:
            return False, ("%d rows < %d minimum"
                           % (g["rows"], MIN_FIT_ROWS))
        if g["val_corr"] < CORR_FLOOR:
            return False, ("validation rank correlation %.3f < %.2f floor"
                           % (g["val_corr"], CORR_FLOOR))
        return True, ""

    def predict(self, kernel, backend, plans):
        """Predicted ms-per-iter array for ``plans`` (plan_summary
        dicts), or None when the group is missing — callers that want
        the abstain semantics should gate on :meth:`usable` first."""
        with self._lock:
            self._load_locked()
            g = self._groups.get(group_key(kernel, backend))
        if g is None:
            return None
        if not plans:
            return np.zeros(0)
        X = np.stack([features_from_plan(p) for p in plans])
        logms = _ridge_predict(X, np.asarray(g["weights"], np.float64),
                               np.asarray(g["mu"], np.float64),
                               np.asarray(g["sd"], np.float64),
                               float(g["intercept"]))
        return np.exp(logms)

    def group(self, kernel, backend):
        with self._lock:
            self._load_locked()
            g = self._groups.get(group_key(kernel, backend))
            return dict(g) if g else None

    # -- fitting -----------------------------------------------------------
    def fit_rows(self, kernel, backend, plans, ms):
        """Fit one group from (plan_summary, measured ms) rows; raises
        typed :class:`CostModelError` below ``MIN_FIT_ROWS`` — the
        insufficient-data refit is a caller error when requested
        explicitly (the table-driven :meth:`fit_from_table` catches it
        per group and abstains instead)."""
        if len(plans) != len(ms):
            raise CostModelError("plans/ms length mismatch (%d vs %d)"
                                 % (len(plans), len(ms)))
        if len(plans) < MIN_FIT_ROWS:
            raise CostModelError(
                "cost model fit for %s needs >= %d rows, got %d"
                % (group_key(kernel, backend), MIN_FIT_ROWS, len(plans)))
        X = np.stack([features_from_plan(p) for p in plans])
        y = np.log(np.maximum(np.asarray(ms, np.float64), 1e-9))
        corr = _cv_corr(X, y)
        w, mu, sd, intercept = _ridge_fit(X, y)
        return {"rows": int(len(plans)), "val_corr": round(corr, 4),
                "weights": [float(v) for v in w],
                "mu": [float(v) for v in mu],
                "sd": [float(v) for v in sd],
                "intercept": float(intercept)}

    def fit_from_table(self, table=None):
        """Refit every (kernel, backend) group from the schedule
        table's banked timings and rewrite the model file whole
        (atomic). Groups with too few rows are skipped (they abstain
        at sweep time); per-group validation rank correlation rides
        ``profiler.tuning_stats`` as the predicted-vs-measured gauge.
        Returns ``{"fit": {group: val_corr}, "skipped": {group:
        reason}, "path": ...}``."""
        from .table import get_table

        table = table if table is not None else get_table()
        rows = {}     # group_key -> ([plans], [ms], kernel, backend)
        for rec in table.entries().values():
            kernel = rec.get("kernel")
            backend = rec.get("backend")
            if not kernel or not backend:
                continue
            gk = group_key(kernel, backend)
            bucket = rows.setdefault(gk, ([], [], kernel, backend))
            for plan, ms in _record_rows(rec):
                bucket[0].append(plan)
                bucket[1].append(ms)
        fit, skipped = {}, {}
        for gk, (plans, ms, kernel, backend) in sorted(rows.items()):
            try:
                fit[gk] = self.fit_rows(kernel, backend, plans, ms)
            except CostModelError as e:
                skipped[gk] = str(e)
        report = {"fit": {gk: g["val_corr"] for gk, g in fit.items()},
                  "skipped": skipped, "path": self.path}
        if fit:
            with self._lock:
                # merge-forward: refit groups overwrite, but groups
                # learned from OTHER tables survive — several tables
                # may share one model file via MXNET_TUNE_MODEL, and a
                # refit over table B must not erase table A's
                # validated groups (a corrupt file still loads as
                # empty, so it is still rewritten whole)
                self._load_locked()
                groups = dict(self._groups)
                groups.update(fit)
                self._groups = groups
                self._persist_locked()
            from .. import profiler

            profiler.tuning_record(model_refits=1, corr=report["fit"])
        return report


def _record_rows(rec):
    """(plan_summary, ms) training rows banked in one table record:
    every entry of the PR 15 ``timings`` list, or — for a PR 10-era
    record — the winner and default measurements it carries. Rows the
    featurization cannot digest (a hand-edited or foreign-build plan
    dict, a non-numeric ms) are SKIPPED, per the module's corrupt-data-
    behaves-as-absent discipline — table loading validates only each
    record's top-level schedule, so bad banked rows must not escape as
    untyped errors from every refit over that table."""
    kernel = rec.get("kernel")
    shape = tuple(rec.get("shape") or ())
    out = []

    def _row(sched, ms, plan=None):
        try:
            ms = float(ms) if ms else 0.0
        except (TypeError, ValueError):
            return
        if not sched or not ms:
            return
        if plan is None and shape:
            try:
                plan = plan_for(kernel, shape, sched)
            except (CostModelError, ValueError, KeyError, TypeError):
                return
        if not isinstance(plan, dict):
            return
        try:
            features_from_plan(plan)
        except (KeyError, TypeError, ValueError):
            return
        out.append((plan, ms))

    timings = rec.get("timings")
    if timings:
        for t in timings:
            if isinstance(t, dict):
                _row(t.get("schedule"), t.get("ms_per_iter"),
                     t.get("plan"))
    else:
        _row(rec.get("schedule"), rec.get("ms_per_iter"))
        _row(rec.get("default_schedule"), rec.get("default_ms_per_iter"))
    return out


# ---------------------------------------------------------------------------
# process-global model (mirrors table.get_table)
# ---------------------------------------------------------------------------
_GLOBAL_LOCK = threading.Lock()
_GLOBAL = None  # (path, CostModel)


def get_model(path=None):
    """The process-global cost model for ``path`` (default:
    knob-resolved next to the schedule table)."""
    global _GLOBAL
    resolved = path or default_model_path()
    with _GLOBAL_LOCK:
        if _GLOBAL is None or _GLOBAL[0] != resolved:
            _GLOBAL = (resolved, CostModel(resolved))
        return _GLOBAL[1]


def reset():
    """Drop the process-global model — tests, and processes that want
    to pick up an externally refit model file."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = None


def fit_cost_model(table=None, path=None):
    """Convenience: refit the (process-global) cost model from a
    schedule table's banked timings — the offline half of the learning
    loop (``tools/tune_kernels.py --compare`` calls this between its
    exhaustive and ranked passes). An explicit ``table`` scopes the
    model next to it (unless ``path``/``MXNET_TUNE_MODEL`` says
    otherwise)."""
    if path is None and table is not None:
        path = model_path_for(table)
    return get_model(path).fit_from_table(table)
