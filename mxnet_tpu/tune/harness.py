"""Loop-amortized kernel timing harness (the PR 1 measurement half).

One jitted ``lax.scan`` runs the kernel N iterations per timed program
so dispatch cost amortizes to nothing; a tiny (*1e-30-scaled*) data
dependence feeds each iteration's output back into the next input so
XLA cannot hoist or CSE the kernel out of the loop (bit-identical in
bf16). Originally written in tools/bench_kernel.py (round 6); hoisted
here so the schedule search (:mod:`.search`) and the benchmark share
one definition — bench_kernel imports these names back.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
from jax import lax


def make_run(fn, iters):
    """The timed program: ``iters`` dependent invocations of ``fn``
    inside one jitted ``lax.scan`` (first operand is the carry)."""
    @jax.jit
    def run(x, rest):
        def body(c, _):
            out = fn(c, *rest)
            lead = jax.tree.leaves(out)[0]
            dep = (lead.reshape(-1)[0].astype(jnp.float32)
                   * 1e-30).astype(c.dtype)
            return c + dep, ()
        y, _ = lax.scan(body, x, None, length=iters)
        return y
    return run


def pin_single_core():
    """Pin the process to one core for CPU harness-validation mode, so
    the process-CPU clock sees fixed work regardless of how a shared
    host schedules XLA's worker threads across cores. Shared by
    tools/bench_kernel.py and tools/tune_kernels.py — one definition,
    one discipline."""
    import os

    if not hasattr(os, "sched_setaffinity"):
        return
    try:
        os.sched_setaffinity(0, {sorted(os.sched_getaffinity(0))[0]})
    except OSError:
        pass


def clock():
    """Wall time on TPU (the device executes; host noise only shifts
    the final block_until_ready return). On CPU backends the compute
    runs in-process and a shared host's steal-time bursts put >60%
    spread on *fixed* work, so harness-validation mode times process
    CPU seconds instead — steal-immune, and identical threading for
    every variant keeps comparisons fair."""
    return (time.perf_counter if jax.default_backend() == "tpu"
            else time.process_time)


def prepare_run(fn, operands, iters, target_sec=0.5, min_iters=10):
    """Calibrate + compile + warm one kernel's timed program; returns
    (run, carry, rest, iters). Calibration uses WALL time (bounds the
    tool's runtime even when CPU utilization is low); measurement uses
    :func:`clock`."""
    x0, rest = operands[0], tuple(operands[1:])
    if iters is None:
        probe_n = max(min_iters // 10, 5)
        probe = make_run(fn, probe_n)
        probe(x0, rest).block_until_ready()      # compile + warm caches
        t0 = time.perf_counter()
        probe(x0, rest).block_until_ready()
        per = (time.perf_counter() - t0) / probe_n
        iters = max(min_iters,
                    min(200000, int(target_sec / max(per, 1e-9))))
    run = make_run(fn, iters)
    run(x0, rest).block_until_ready()            # compile + warm caches
    return run, x0, rest, iters


def summarize(runs):
    """Trimmed mean + spread: shared-CPU hosts show ~65% max-min spread
    on FIXED numpy work (steal-time bursts + sustained frequency
    drift), so the extremes measure the machine, not the kernel — drop
    len//3 runs from each end and report the middle."""
    n = len(runs)
    if not n:
        return 0.0, 0.0
    trim = max(1, n // 3) if n >= 4 else 0
    mid = sorted(runs)[trim:-trim] if trim else sorted(runs)
    mean = sum(mid) / len(mid)
    spread = (max(mid) - min(mid)) / mean if mean else 0.0
    return mean, spread


def time_round_robin(prepared, repeats):
    """Interleaved timing of several prepared programs: every repeat of
    every program samples the same machine-noise epoch, so sustained
    drift hits all candidates alike and a schedule comparison cannot
    flip on scheduling luck (the bench_kernel round-robin discipline).

    ``prepared``: [(name, run, x0, rest, iters)];
    returns {name: [ms_per_iter, ...]}.
    """
    clk = clock()
    runs = {name: [] for name, *_ in prepared}
    for _ in range(repeats):
        for name, run, x0, rest, iters in prepared:
            t0 = clk()
            run(x0, rest).block_until_ready()
            runs[name].append((clk() - t0) / iters * 1e3)
    return runs
