"""On-disk schedule table: searched Pallas schedules keyed by
``(kernel, shape, dtype, backend)``.

Design constraints (ISSUE 10):

- **Hot path is a dict hit.** Kernel entry points call
  :func:`schedule_for` at trace time; after the first lookup of a key
  the answer (including the negative answer) sits in a process-local
  memo, so re-traces cost one dict ``get``.
- **Versioned, atomic, corruption-proof.** The table is one JSON file
  (``{"version": 1, "entries": {key: record}}``) written through
  ``checkpoint.atomic_write_bytes`` (tmp + fsync + rename — a crash
  mid-commit leaves the old table). A truncated/garbage/version-
  mismatched file logs a warning, behaves as empty (hand defaults),
  and is fully rewritten by the next tune commit — it must never
  crash a training job.
- **Backend-keyed.** A schedule searched on the CPU interpreter says
  nothing about the MXU; ``backend`` (``jax.default_backend()``) is
  part of the key so CPU smoke tables can never leak into TPU runs.

Location: ``MXNET_TPU_TUNE_TABLE`` when set, else
``~/.cache/mxnet_tpu/schedule_table.json``. ``MXNET_TPU_TUNE=0``
disables the trace-time consult entirely (hand defaults, zero reads).
"""
from __future__ import annotations

import json
import logging
import os
import threading

from .. import config

log = logging.getLogger("mxnet_tpu.tune")

TABLE_VERSION = 1

# schedule knobs a record may carry, per kernel family; anything else
# in a loaded schedule is rejected (the entry falls back to defaults)
_KNOWN_KNOBS = frozenset(
    ("row_tile", "chan_block", "batch_fold", "block_q", "block_k",
     # ISSUE 19 training-pipeline choices ride the same table; values
     # are small positive codes (tune/pipeline.py REMAT/LAYOUT_CODES)
     "remat", "layout"))


def default_table_path():
    override = config.get("MXNET_TPU_TUNE_TABLE")
    if override:
        return override
    return os.path.join(os.path.expanduser("~"), ".cache", "mxnet_tpu",
                        "schedule_table.json")


def make_key(kernel, shape, dtype, backend):
    """The table/report key: ``kernel|d0xd1x...|dtype|backend``."""
    dims = "x".join(str(int(d)) for d in shape)
    return "%s|%s|%s|%s" % (kernel, dims, dtype, backend)


# ---------------------------------------------------------------------------
# miss registry (ISSUE 15): every trace-time consult that found no
# table entry records WHAT was missing — (kernel, shape, dtype,
# backend), enough to reconstruct a sweep — so the background tuner
# can time ranked candidates for exactly the shapes the job traced.
# Process-local, bounded, cleared when a commit satisfies the key.
# ---------------------------------------------------------------------------
_MISS_LOCK = threading.Lock()
_MISSES = {}          # key -> {key, kernel, shape, dtype, backend, count}
_MISS_CAP = 512


def _record_miss(key, kernel, shape, dtype, backend):
    with _MISS_LOCK:
        m = _MISSES.get(key)
        if m is not None:
            m["count"] += 1
            return
        if len(_MISSES) >= _MISS_CAP:
            return
        _MISSES[key] = {"key": key, "kernel": str(kernel),
                        "shape": tuple(int(d) for d in shape),
                        "dtype": str(dtype), "backend": str(backend),
                        "count": 1}


def recorded_misses():
    """Snapshot of the schedule-table misses this process recorded via
    trace-time consults (``schedule_for``), insertion-ordered — the
    background tuner's work queue."""
    with _MISS_LOCK:
        return [dict(m) for m in _MISSES.values()]


def clear_miss(key):
    with _MISS_LOCK:
        _MISSES.pop(key, None)


def clear_misses():
    with _MISS_LOCK:
        _MISSES.clear()


def _valid_schedule(schedule):
    if not isinstance(schedule, dict) or not schedule:
        return False
    for k, v in schedule.items():
        if k not in _KNOWN_KNOBS:
            return False
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            return False
    return True


class ScheduleTable:
    """One JSON schedule table + its process-local memo."""

    def __init__(self, path=None):
        self.path = path or default_table_path()
        self._lock = threading.Lock()
        self._memo = {}        # key -> schedule dict | None (negative)
        self._entries = None   # key -> full record; None until loaded
        self.load_error = None

    # -- load / persist ----------------------------------------------------
    def _load_locked(self):
        if self._entries is not None:
            return
        self._entries = {}
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                raw = f.read()
        except FileNotFoundError:
            return
        except OSError as e:
            self.load_error = "unreadable: %s" % e
            log.warning("schedule table %s unreadable (%s); using default "
                        "schedules", self.path, e)
            return
        try:
            data = json.loads(raw)
            if not isinstance(data, dict):
                raise ValueError("top level is %s, not an object"
                                 % type(data).__name__)
            version = data.get("version")
            if version != TABLE_VERSION:
                raise ValueError("version %r != %d" % (version,
                                                       TABLE_VERSION))
            entries = data["entries"]
            if not isinstance(entries, dict):
                raise ValueError("entries is %s, not an object"
                                 % type(entries).__name__)
            loaded = {}
            for key, rec in entries.items():
                if not (isinstance(rec, dict)
                        and _valid_schedule(rec.get("schedule"))):
                    raise ValueError("malformed record for key %r" % key)
                loaded[key] = rec
        except (ValueError, KeyError, TypeError) as e:
            # corrupt/stale table: behave as empty — the kernels fall
            # back to their hand defaults and the next tune commit
            # rewrites the whole file
            self.load_error = str(e)
            log.warning(
                "schedule table %s is corrupt or from another version "
                "(%s); falling back to default schedules — the next "
                "tools/tune_kernels.py run rewrites it", self.path, e)
            return
        self._entries = loaded

    def _persist_locked(self):
        payload = {"version": TABLE_VERSION, "entries": self._entries}
        data = json.dumps(payload, indent=1, sort_keys=True).encode("utf-8")
        d = os.path.dirname(os.path.abspath(self.path))
        if d:
            os.makedirs(d, exist_ok=True)
        from ..checkpoint import atomic_write_bytes

        atomic_write_bytes(self.path, data)
        self.load_error = None

    # -- API ---------------------------------------------------------------
    def lookup(self, kernel, shape, dtype, backend, record_stats=True):
        """Schedule dict for the key, or None. Counts a table hit or
        miss in ``profiler.tuning_stats`` (``record_stats=False`` for
        introspection that must not skew the counters)."""
        key = make_key(kernel, shape, dtype, backend)
        if key in self._memo:
            sched = self._memo[key]
        else:
            with self._lock:
                self._load_locked()
                rec = self._entries.get(key)
                sched = dict(rec["schedule"]) if rec else None
                self._memo[key] = sched
        if record_stats:
            from .. import profiler

            if sched is not None:
                profiler.tuning_record(hits=1, kernel=key,
                                       schedule=dict(sched), source="table")
            else:
                profiler.tuning_record(misses=1)
                _record_miss(key, kernel, shape, dtype, backend)
        return dict(sched) if sched else None

    def reload(self):
        """Drop the in-memory entries AND the consult memo so the next
        read re-reads the table file — how a long-lived process picks
        up another job's commits (the background tuner calls this once
        per drain slot, so its tuned-elsewhere check and the trace-time
        consults both see cross-process winners; without it ``lookup``
        would serve the memoized miss forever)."""
        with self._lock:
            self._entries = None
            self.load_error = None
            self._memo = {}

    def entry(self, kernel, shape, dtype, backend):
        """The full stored record (schedule + timings), or None."""
        with self._lock:
            self._load_locked()
            rec = self._entries.get(make_key(kernel, shape, dtype, backend))
            return dict(rec) if rec else None

    def entries(self):
        """Snapshot of every stored record keyed by table key — the
        cost model's training-row source (ISSUE 15)."""
        with self._lock:
            self._load_locked()
            return {k: dict(v) for k, v in self._entries.items()}

    def record(self, kernel, shape, dtype, backend, record):
        """Commit one winner record (atomic whole-file rewrite).

        The merge base is re-read from disk at commit time, so two
        tuner processes sharing one table file (a manual sweep next to
        bench.py's tune variant) don't clobber each other's winners
        with stale process-lifetime snapshots; the remaining race is
        two commits in the same instant, which a tuning tool can live
        with. Banked ``timings`` rows merge against the re-read base
        the same way (fresh measurement of a schedule wins): a
        topk-bounded ranked sweep or background slot GROWS the cost
        model's training set, never shrinks another sweep's bank
        (ISSUE 15)."""
        if not _valid_schedule(record.get("schedule")):
            raise ValueError("record.schedule must be a non-empty dict of "
                             "known integer knobs >= 1, got %r"
                             % (record.get("schedule"),))
        key = make_key(kernel, shape, dtype, backend)
        with self._lock:
            self._entries = None
            self.load_error = None
            self._load_locked()
            prev = self._entries.get(key)
            if prev and prev.get("timings"):
                if record.get("timings"):
                    # loading validates only the top-level schedule, so
                    # a hand-edited/foreign-build banked row can be
                    # anything — skip what the merge key cannot digest
                    # (corrupt-data-behaves-as-absent, like the model's
                    # _record_rows), never break every future commit
                    # for the key
                    merged = {}
                    for t in list(prev["timings"]) + list(record["timings"]):
                        try:
                            merged[frozenset(t["schedule"].items())] = t
                        except (AttributeError, KeyError, TypeError):
                            continue
                    record = dict(record, timings=list(merged.values()))
                else:
                    # a winner-only commit (PR 10-era caller, the
                    # --compare recommit) must never destroy the bank
                    record = dict(record, timings=prev["timings"])
            self._entries[key] = dict(record, kernel=kernel,
                                      shape=[int(d) for d in shape],
                                      dtype=str(dtype), backend=backend)
            self._persist_locked()
            self._memo[key] = dict(record["schedule"])
        clear_miss(key)   # a commit satisfies the recorded miss
        return key

    def __len__(self):
        with self._lock:
            self._load_locked()
            return len(self._entries)


# ---------------------------------------------------------------------------
# process-global table + the trace-time consult API
# ---------------------------------------------------------------------------
_GLOBAL_LOCK = threading.Lock()
_GLOBAL = None  # (path, ScheduleTable)


def get_table(path=None):
    """The process-global table for ``path`` (default: knob-resolved).
    A changed ``MXNET_TPU_TUNE_TABLE`` between calls gets a fresh
    table; the common case is one table for the process lifetime."""
    global _GLOBAL
    resolved = path or default_table_path()
    with _GLOBAL_LOCK:
        if _GLOBAL is None or _GLOBAL[0] != resolved:
            _GLOBAL = (resolved, ScheduleTable(resolved))
        return _GLOBAL[1]


def reset():
    """Drop the process-global table (memo included) and the miss
    registry — tests, and long-lived processes that want to pick up an
    externally updated table file."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = None
    clear_misses()


def schedule_for(kernel, shape, dtype, backend=None):
    """The trace-time consult the kernel entry points use.

    Returns the searched schedule dict for
    ``(kernel, shape, dtype, backend)`` or None (caller falls back to
    its hand defaults — an empty table is bit-identical to the
    pre-autotuner behavior). ``MXNET_TPU_TUNE=0`` short-circuits to
    None without touching the table or the counters.
    """
    if not config.get_bool("MXNET_TPU_TUNE", True):
        return None
    if backend is None:
        import jax

        backend = jax.default_backend()
    return get_table().lookup(kernel, tuple(shape), str(dtype), backend)
